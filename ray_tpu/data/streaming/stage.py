"""Stage actors: the long-lived workers of a streaming pipeline.

Each stage worker executes ONE ``run_loop`` actor call for the whole
pipeline run (the Sebulba shape — rl/podracer/sebulba.py): blocks flow
in over sealed-ring edges, through the stage's operator plus any fused
block fns, and out over the next edge, shm-to-shm, with **zero control
dispatches per block** in steady state. The only actor calls a pipeline
ever issues are the one loop start per worker and (on abort) nothing —
teardown rides the shared stop flag.

Stage kinds:

* ``source`` — no input edge; executes its share of read tasks (or
  fetches its share of pre-materialized block refs) in plan order and
  emits ``(idx, block)``. Worker ``w`` of ``W`` owns idxs ``w (mod W)``
  — the stripe-sender contract downstream ordered receivers rely on.
* ``pool`` — the streaming ActorPoolMapOperator: constructs the user's
  callable class ONCE (model load / XLA compile paid once), then maps
  its stripe of blocks through it in order. Pool feeds are
  deterministic (worker ``w`` owns idxs ``w (mod W)``) rather than
  work-stealing: that is what keeps the credit graph deadlock-free and
  the output bit-identical — a slow block head-of-lines its own worker
  only, the same profile as the task executor's plan-order delivery.
* ``repartition`` — the one materializing stage: an all-to-all by
  definition, it must see every input block before emitting output
  block 0. Splits each arriving block contiguously as it arrives
  (arrow slices are cheap views) and concatenates at end-of-stream —
  the exact math of the task executor's repartition(shuffle=False), so
  results stay bit-identical.
* ``zip`` — two ordered input edges; aligns row ranges and emits
  column-concatenated chunks as soon as BOTH sides have rows, holding
  only the rate-mismatch carry (bounded by the edges' credit windows).
  Error-path divergence from the task executor, on purpose: mismatched
  row counts raise at END of stream (after the aligned prefix already
  flowed downstream), because a streaming zip cannot know totals up
  front without materializing both sides — the task executor counts
  both materialized sides first and raises before yielding anything.
  Success-path results are bit-identical.

A worker that hits an error lets the exception fly: the run_loop ref
fails, the driver's idle probe surfaces it within a wait slice and
seals the stop flag, and every other parked worker unwinds through
ChannelClosed. On abort each worker sweeps its own channel windows, so
the store returns to its pre-pipeline object count.
"""
from __future__ import annotations

import dataclasses
import os
from typing import Any

from ...core import flight
from ...dag.channel import ChannelClosed
from .channels import BlockReceiver, BlockSender, EdgeSpec


@dataclasses.dataclass
class StageSpec:
    """Everything one stage worker needs, cloudpickled into its single
    run_loop call (fns ride the blob, edges are plain id bases)."""

    kind: str                 # "source" | "pool" | "repartition" | "zip"
    idx: int                  # stage position in the pipeline (flight)
    width: int                # workers in this stage
    fused: list               # block fns applied to every emitted block
    in_edges: list            # [] | [EdgeSpec] | [left, right] for zip
    in_modes: list            # receiver mode per in edge
    out_edge: EdgeSpec
    out_mode: str             # "stripe" | "steal"
    payload: Any              # per kind, see _run_* below


def _apply_fused(fused, block):
    for fn in fused:
        block = fn(block)
    return block


def run_stage_loop(spec_blob: bytes, worker_idx: int) -> dict:
    """The one long-lived task per (stage, worker) slot. A task — not an
    actor — on purpose: it runs on the shared worker pool, so a finished
    pipeline returns its workers to the pool intact (no per-run process
    churn, and the workers' flight-recorder rings survive for `cli
    timeline`), while a wedged stage is still force-reapable via
    ``ray.cancel(ref, force=True)``. Spawned with max_retries=0: a
    retried loop would replay rings whose cursors moved."""
    return PipelineStageWorker().run_loop(spec_blob, worker_idx)


class PipelineStageWorker:
    """Stage worker body; its whole life is one ``run_loop`` call."""

    def run_loop(self, spec_blob: bytes, worker_idx: int) -> dict:
        import cloudpickle

        from ...core import runtime as rt_mod
        spec: StageSpec = cloudpickle.loads(spec_blob)
        if os.environ.get("RTPU_OWN_STORE") == "1":
            # this worker's store is NOT the head's shm segment: slots
            # sealed here would be invisible to the pipeline's consumers
            # (the queue.py RolloutProducer contract). Raise — the
            # driver's idle probe surfaces this within a wait slice —
            # rather than wedge every consumer on never-sealed slots.
            raise RuntimeError(
                "streaming stage landed on an own-store node; sealed "
                "channels need the cluster's shared shm store — pin "
                "the pipeline to the head node or set "
                "DataContext.streaming_executor='off'")
        rt = rt_mod.get_runtime_if_exists()
        store = getattr(rt, "store", None)
        if store is None:
            raise RuntimeError(
                "streaming stage needs a shared shm object store "
                "(own-store nodes can't join a pipeline)")
        sender = BlockSender(store, spec.out_edge, worker_idx,
                             spec.out_mode)
        # consumer slot = worker index: stage worker w owns idxs
        # w (mod width) on its input edge (width-1 stages are slot 0)
        receivers = [BlockReceiver(store, e, worker_idx, mode=m)
                     for e, m in zip(spec.in_edges, spec.in_modes)]
        flight.evt(flight.DATA_STAGE_BEGIN, spec.idx, worker_idx)
        blocks = 0
        aborted = False
        try:
            runner = getattr(self, f"_run_{spec.kind}")
            blocks = runner(spec, worker_idx, receivers, sender)
            sender.finish()
        except ChannelClosed:
            aborted = True   # teardown: stop flag sealed mid-wait
        except BaseException:
            aborted = True
            # a failed stage dooms the WHOLE pipeline: seal the stop
            # flag so every parked consumer (including split shards in
            # other processes, which have no driver probe) wakes within
            # one wait slice instead of waiting out its timeout; the
            # driver still surfaces THIS error through the failed ref
            try:
                from ...dag.channel import signal_stop
                signal_stop(store, spec.out_edge.stop_oid())
            except Exception:
                pass  # store closing; consumers die with it
            raise            # driver's probe surfaces this ref's error
        finally:
            if aborted or sender.closed():
                sender.sweep()
                for r in receivers:
                    r.sweep()
            flight.evt(flight.DATA_STAGE_END, spec.idx, blocks)
        return {"blocks": blocks, "worker": worker_idx}

    # -- stage kinds ---------------------------------------------------- #

    def _run_source(self, spec, worker_idx, receivers, sender) -> int:
        kind, items = spec.payload
        n = 0
        for k in range(worker_idx, len(items), spec.width):
            if kind == "tasks":
                block = items[k]()
            else:                      # "refs": pre-materialized blocks
                import ray_tpu
                block = ray_tpu.get(items[k])
            block = _apply_fused(spec.fused, block)
            flight.evt(flight.DATA_BLOCK, spec.idx, k)
            sender.send(k, block)
            n += 1
        return n

    def _run_pool(self, spec, worker_idx, receivers, sender) -> int:
        import cloudpickle
        cls, args, kwargs, wrap = cloudpickle.loads(spec.payload)
        fn = cls(*args, **kwargs) if isinstance(cls, type) else cls
        recv = receivers[0]
        n = 0
        while True:
            got = recv.next_block()
            if got is None:
                return n
            idx, block = got
            out = _apply_fused(spec.fused, wrap(fn, block))
            flight.evt(flight.DATA_BLOCK, spec.idx, idx)
            sender.send(idx, out)
            n += 1

    def _run_repartition(self, spec, worker_idx, receivers, sender) -> int:
        from .. import block as B
        from ..executor import _split_for_exchange
        n_out = int(spec.payload)
        recv = receivers[0]
        parts: list = []          # per input block: tuple of n_out slices
        while True:
            got = recv.next_block()
            if got is None:
                break
            parts.append(_split_for_exchange(got[1], n_out, False, 0))
        for j in range(n_out):
            out = B.concat([p[j] for p in parts]) if parts \
                else B.concat([])
            out = _apply_fused(spec.fused, out)
            flight.evt(flight.DATA_BLOCK, spec.idx, j)
            sender.send(j, out)
        return n_out

    def _run_zip(self, spec, worker_idx, receivers, sender) -> int:
        from .. import block as B
        left, right = receivers
        lbuf = rbuf = None            # rate-mismatch carry per side
        ldone = rdone = False
        ltotal = rtotal = 0           # rows seen per side (error report)
        out_idx = 0

        def rows(b) -> int:
            return b.num_rows if b is not None else 0

        while not (ldone and rdone):
            if rows(lbuf) == 0 and not ldone:
                got = left.next_block()
                if got is None:
                    ldone = True
                else:
                    ltotal += got[1].num_rows
                    lbuf = got[1]
                continue
            if rows(rbuf) == 0 and not rdone:
                got = right.next_block()
                if got is None:
                    rdone = True
                else:
                    rtotal += got[1].num_rows
                    rbuf = got[1]
                continue
            take = min(rows(lbuf), rows(rbuf))
            if take == 0:
                break   # one side ended while the other still has rows
            from ..executor import zip_blocks
            lchunk = B.slice_block(lbuf, 0, take)
            rchunk = B.slice_block(rbuf, 0, take)
            lbuf = B.slice_block(lbuf, take, rows(lbuf))
            rbuf = B.slice_block(rbuf, take, rows(rbuf))
            out = _apply_fused(spec.fused, zip_blocks(lchunk, rchunk))
            flight.evt(flight.DATA_BLOCK, spec.idx, out_idx)
            sender.send(out_idx, out)
            out_idx += 1
        # drain whatever is left (counts only) so a length mismatch
        # reports the true totals, like the task executor's up-front check
        while not ldone:
            got = left.next_block()
            if got is None:
                ldone = True
            else:
                ltotal += got[1].num_rows
        while not rdone:
            got = right.next_block()
            if got is None:
                rdone = True
            else:
                rtotal += got[1].num_rows
        if ltotal != rtotal:
            raise ValueError(f"zip requires equal row counts ({ltotal} "
                             f"vs {rtotal})")
        return out_idx


