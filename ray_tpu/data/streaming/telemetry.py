"""Data-plane telemetry: rtpu_data_* metrics + metrics_summary().

The dispatch-economy proof for the streaming executor, shaped exactly
like rtpu_rl_* (bench_rl) and the serve stream counters (bench_serve
--decode-plan): both executors count the control dispatches they issue
and the blocks they deliver, so ``dispatches_per_block`` is
counter-verified per path instead of inferred.

Metric names and label sets:
  rtpu_data_blocks_total{path}         counter — blocks delivered to the
      consumer (path=chan: streaming pipeline sink; path=task: the
      task-per-block executor's per-block yield)
  rtpu_data_dispatches_total{path}     counter — control-plane calls
      issued to move blocks: ONE run_loop call per stage worker for the
      streaming path (steady state adds none), one task submission per
      block for the task path. The headline ratio
      dispatches/block -> ~0 streaming, >= 1 task.
  rtpu_data_backpressure_waits_total   counter — times a stage sender
      found every consumer ring at its credit limit and parked (the
      bounded-memory proof under skew: blocks park in rings, not in the
      store)
  rtpu_data_queue_depth                gauge — sealed-but-unread blocks
      at the sink's rings (sampled while the consumer iterates)

``metrics_summary()`` condenses the merged store into the numbers a run
report cites; ``state.summary()["data"]`` exposes the same rollup.
"""
from __future__ import annotations

from ...util.metrics import (Counter, Gauge, cached_metric as _metric,
                             collect_store as _collect_store)


def blocks() -> Counter:
    return _metric(Counter, "rtpu_data_blocks_total",
                   "dataset blocks delivered to the consumer",
                   tag_keys=("path",))


def dispatches() -> Counter:
    return _metric(Counter, "rtpu_data_dispatches_total",
                   "control-plane calls issued to move dataset blocks",
                   tag_keys=("path",))


def backpressure_waits() -> Counter:
    return _metric(Counter, "rtpu_data_backpressure_waits_total",
                   "stage senders parked at the ring credit limit")


def queue_depth() -> Gauge:
    return _metric(Gauge, "rtpu_data_queue_depth",
                   "sealed-but-unread blocks at the pipeline sink")


def note_backpressure() -> None:
    try:
        backpressure_waits().inc(1.0)
    except Exception:
        pass  # telemetry must never fail the data plane


def note_blocks(n: float, path: str) -> None:
    try:
        blocks().inc(n, tags={"path": path})
    except Exception:
        pass  # telemetry must never fail the data plane


def note_dispatches(n: float, path: str) -> None:
    try:
        dispatches().inc(n, tags={"path": path})
    except Exception:
        pass  # telemetry must never fail the data plane


def note_depth(d: float) -> None:
    try:
        queue_depth().set(d)
    except Exception:
        pass  # telemetry must never fail the data plane


def _by_tag(rec, tag: str) -> dict:
    out: dict = {}
    for key, val in (rec or {}).get("series", {}).items():
        label = next((v for k, v in key if k == tag), "")
        out[label] = out.get(label, 0.0) + val
    return out


def metrics_summary() -> dict:
    """Per-path block/dispatch totals with the dispatches_per_block
    headline, plus backpressure-wait totals and the last sampled sink
    depth. Store merge is the util/metrics helper every other summary
    uses."""
    store = _collect_store()
    out: dict = {}
    blks = _by_tag(store.get("rtpu_data_blocks_total"), "path")
    disp = _by_tag(store.get("rtpu_data_dispatches_total"), "path")
    if blks or disp:
        paths: dict = {}
        for p in set(blks) | set(disp):
            rec = {"blocks": blks.get(p, 0.0),
                   "dispatches": disp.get(p, 0.0)}
            if rec["blocks"]:
                rec["dispatches_per_block"] = (
                    rec["dispatches"] / rec["blocks"])
            paths[p] = rec
        out["path"] = paths
    bp = _by_tag(store.get("rtpu_data_backpressure_waits_total"), "")
    if bp:
        out["backpressure_waits"] = sum(bp.values())
    rec = store.get("rtpu_data_queue_depth")
    if rec and rec["series"]:
        out["queue_depth"] = max(rec["series"].values())
    return out
