"""Exception hierarchy.

Reference parity: python/ray/exceptions.py (RayError, RayTaskError,
WorkerCrashedError, ActorDiedError, TaskCancelledError, ObjectLostError,
GetTimeoutError, ObjectStoreFullError).
"""
from __future__ import annotations

import traceback


class RayError(Exception):
    """Base class for all framework errors."""


class RayTaskError(RayError):
    """Wraps an exception raised by user task/actor code, carrying the remote
    traceback so `ray.get` shows where the failure happened."""

    def __init__(self, function_name: str, cause: BaseException,
                 remote_tb: str | None = None):
        self.function_name = function_name
        self.cause = cause
        self.remote_tb = remote_tb or "".join(
            traceback.format_exception(type(cause), cause, cause.__traceback__))
        super().__init__(
            f"task {function_name} failed:\n{self.remote_tb}")

    def __reduce__(self):
        return (RayTaskError,
                (self.function_name, self.cause, self.remote_tb))

    def as_instanceof_cause(self) -> BaseException:
        """Return an exception that is an instance of the cause's class (so
        `except ValueError` works across the task boundary), still carrying
        the remote traceback in its message.

        Reference analog: RayTaskError.as_instanceof_cause
        (python/ray/exceptions.py).
        """
        cause = self.cause
        if isinstance(cause, RayError):
            return cause
        try:
            cls = type(cause)
            err = cls.__new__(cls)
            err.args = cause.args
            err.__cause__ = self
            return err
        except Exception:
            return self


class WorkerCrashedError(RayError):
    """The worker process executing the task died unexpectedly."""


class ActorDiedError(RayError):
    """The actor is dead (crashed, killed, or out of restarts)."""


class ActorUnavailableError(RayError):
    """The actor is temporarily unreachable (restarting)."""


class TaskCancelledError(RayError):
    """The task was cancelled."""


class ObjectLostError(RayError):
    """The object was evicted/lost and could not be reconstructed."""


class GetTimeoutError(RayError, TimeoutError):
    """`ray.get(..., timeout=...)` expired."""


class ObjectStoreFullError(RayError, MemoryError):
    """The shared-memory object store is out of space."""


class RuntimeEnvSetupError(RayError):
    """Setting up the runtime environment for a task/actor failed."""


class PlacementGroupUnavailableError(RayError):
    """Placement group cannot be scheduled with current cluster resources."""


class PendingCallsLimitExceeded(RayError):
    """An actor handle with ``max_pending_calls`` set has that many calls
    in flight (reference: ray.exceptions.PendingCallsLimitExceeded, raised
    by the actor task submitter's client-side backpressure)."""


class ExitActorSignal(BaseException):
    """Control-flow signal raised by ray_tpu.exit_actor() inside an actor
    method; the worker catches it and exits the actor intentionally
    (no restart). BaseException so user ``except Exception`` blocks
    cannot swallow it — the same reason the reference's sync path raises
    SystemExit (ray.actor.exit_actor)."""
