"""ray_tpu.experimental — device objects (Ray Direct Transport analog)."""
from .device_objects import DeviceObject, device_object_stats

__all__ = ["DeviceObject", "device_object_stats"]
