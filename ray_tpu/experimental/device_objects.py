"""Device objects: values whose payload stays on the accelerator.

Reference parity: "Ray Direct Transport" / GPU objects
(_private/gpu_object_manager.py:41 GPUObjectManager,
@ray.method(tensor_transport=...)) — ObjectRefs whose tensor payload
stays in device memory and moves via collective transports instead of
plasma.

TPU-first reduction: each worker process owns a device-object registry;
``DeviceObject.wrap(x)`` records the jax.Array there and what travels
through the object store is a tiny stub (owner wid + key + aval). A
consumer in the SAME process gets the original array back with zero
copies or transfers; a consumer elsewhere fetches the host representation
from the owner over the control plane and re-places it on its own device.
On a multi-host pod the cross-process path is where an ICI/DCN collective
transport slots in (jax.experimental transfer — the single-chip image has
no second device to exercise it, so host relay is the fallback the way
the reference falls back to object-store copies for non-NCCL-able pairs).

    @ray_tpu.remote
    class Producer:
        def make(self):
            return DeviceObject.wrap(jnp.ones((1024, 1024)))

    obj = ray_tpu.get(p.make.remote())   # a stub — no device transfer yet
    x = obj.to_device()                  # local hit or owner fetch
"""
from __future__ import annotations

import threading
import uuid
from typing import Any, Optional

_registry: dict[str, Any] = {}
_lock = threading.Lock()
_stats = {"wrapped": 0, "local_hits": 0, "remote_fetches": 0,
          "released": 0}
_MAX_ENTRIES = 256


def _my_wid() -> str:
    from ..core import runtime as rt_mod
    rt = rt_mod.get_runtime_if_exists()
    wid = getattr(rt, "wid", None)
    return wid if wid is not None else "driver"


def device_object_stats() -> dict:
    with _lock:
        return dict(_stats, registered=len(_registry))


class DeviceObject:
    """Pickles as (owner, key, aval); the array never rides the pickle."""

    def __init__(self, owner: str, key: str, shape, dtype):
        self.owner = owner
        self.key = key
        self.shape = shape
        self.dtype = dtype

    # -- producer ------------------------------------------------------- #

    @classmethod
    def wrap(cls, array) -> "DeviceObject":
        key = uuid.uuid4().hex
        with _lock:
            if len(_registry) >= _MAX_ENTRIES:
                raise RuntimeError(
                    f"device-object registry full ({_MAX_ENTRIES}); "
                    f"release() finished objects")
            _registry[key] = array
            _stats["wrapped"] += 1
        return cls(_my_wid(), key, tuple(array.shape), str(array.dtype))

    # -- consumer ------------------------------------------------------- #

    def to_device(self, timeout_s: float = 60.0):
        """The array: zero-copy when this process owns it, owner fetch +
        device_put otherwise."""
        with _lock:
            arr = _registry.get(self.key)
        if arr is not None:
            with _lock:
                _stats["local_hits"] += 1
            return arr
        host = self._fetch_host(timeout_s)
        import jax
        arr = jax.device_put(host)
        with _lock:
            _stats["remote_fetches"] += 1
        return arr

    def _fetch_host(self, timeout_s: float):
        import time as _time

        from ..core import runtime as rt_mod
        from ..core.ids import ObjectID
        from ..core.object_store import GetTimeoutError
        rt = rt_mod.get_runtime_if_exists()
        if rt is None:
            raise RuntimeError("ray_tpu.init() first")
        reply = ObjectID.from_random()
        rb = reply.binary()
        deadline = _time.monotonic() + timeout_s
        if hasattr(rt, "_rpc"):      # worker / driver client
            rt.send({"t": "device_fetch", "owner": self.owner,
                     "key": self.key, "reply_oid": rb})
            # the payload may come back over the conn (own-store nodes)
            # or through the shared store — poll both
            while True:
                got = rt._rpc_replies.pop(rb, None)
                if got is not None:
                    status, payload = got
                    break
                try:
                    status, payload = rt.store.get(reply, timeout_ms=200)
                    rt.store.delete(reply)
                    break
                except GetTimeoutError:
                    if _time.monotonic() > deadline:
                        # a late conn-delivered payload must be dropped,
                        # not parked forever (worker._rpc does the same)
                        rt._rpc_abandoned.add(rb)
                        raise TimeoutError(
                            f"device object fetch from {self.owner} "
                            f"timed out") from None
        else:                        # head driver
            rt.device_fetch(self.owner, self.key, rb, requester="driver")
            while True:
                try:
                    status, payload = rt.store.get(reply, timeout_ms=200)
                    rt.store.delete(reply)
                    break
                except GetTimeoutError:
                    if _time.monotonic() > deadline:
                        raise TimeoutError(
                            f"device object fetch from {self.owner} "
                            f"timed out") from None
        if status == "err":
            raise RuntimeError(payload)
        return payload

    def release(self) -> bool:
        """Drop the owner-side registration (owner process only)."""
        with _lock:
            hit = _registry.pop(self.key, None)
            if hit is not None:
                _stats["released"] += 1
            return hit is not None

    def __repr__(self):
        return (f"DeviceObject(owner={self.owner}, shape={self.shape}, "
                f"dtype={self.dtype})")


def _fetch_payload(key: str):
    """Owner-side: the (status, host-array) payload for a device_fetch
    (delivery is the runtime's job — store or conn, per requester)."""
    import numpy as np
    with _lock:
        arr = _registry.get(key)
    if arr is None:
        return ("err", f"device object {key!r} not registered "
                       f"(released or evicted)")
    return ("ok", np.asarray(arr))
