"""Device objects: values whose payload stays on the accelerator.

Reference parity: "Ray Direct Transport" / GPU objects
(_private/gpu_object_manager.py:41 GPUObjectManager,
@ray.method(tensor_transport=...)) — ObjectRefs whose tensor payload
stays in device memory and moves via collective transports instead of
plasma.

TPU-first reduction: each worker process owns a device-object registry;
``DeviceObject.wrap(x)`` records the jax.Array there and what travels
through the object store is a tiny stub (owner wid + key + aval). A
consumer in the SAME process gets the original array back with zero
copies or transfers; a consumer elsewhere fetches the host representation
from the owner over the control plane and re-places it on its own device.
On a multi-host pod the cross-process path is where an ICI/DCN collective
transport slots in (jax.experimental transfer — the single-chip image has
no second device to exercise it, so host relay is the fallback the way
the reference falls back to object-store copies for non-NCCL-able pairs).

    @ray_tpu.remote
    class Producer:
        def make(self):
            return DeviceObject.wrap(jnp.ones((1024, 1024)))

    obj = ray_tpu.get(p.make.remote())   # a stub — no device transfer yet
    x = obj.to_device()                  # local hit or owner fetch
"""
from __future__ import annotations

import threading
import uuid
from typing import Any, Optional

_registry: dict[str, Any] = {}
_lock = threading.Lock()
_stats = {"wrapped": 0, "local_hits": 0, "remote_fetches": 0,
          "released": 0}
_MAX_ENTRIES = 256


def _my_wid() -> str:
    from ..core import runtime as rt_mod
    rt = rt_mod.get_runtime_if_exists()
    wid = getattr(rt, "wid", None)
    return wid if wid is not None else "driver"


def device_object_stats() -> dict:
    with _lock:
        return dict(_stats, registered=len(_registry))


class DeviceObject:
    """Pickles as (owner, key, aval); the array never rides the pickle."""

    def __init__(self, owner: str, key: str, shape, dtype):
        self.owner = owner
        self.key = key
        self.shape = shape
        self.dtype = dtype

    # -- producer ------------------------------------------------------- #

    @classmethod
    def wrap(cls, array) -> "DeviceObject":
        key = uuid.uuid4().hex
        with _lock:
            if len(_registry) >= _MAX_ENTRIES:
                raise RuntimeError(
                    f"device-object registry full ({_MAX_ENTRIES}); "
                    f"release() finished objects")
            _registry[key] = array
            _stats["wrapped"] += 1
        return cls(_my_wid(), key, tuple(array.shape), str(array.dtype))

    # -- consumer ------------------------------------------------------- #

    def to_device(self, timeout_s: float = 60.0):
        """The array: zero-copy when this process owns it, owner fetch +
        device_put otherwise."""
        with _lock:
            arr = _registry.get(self.key)
        if arr is not None:
            with _lock:
                _stats["local_hits"] += 1
            return arr
        host = self._fetch_host(timeout_s)
        import jax
        arr = jax.device_put(host)
        with _lock:
            _stats["remote_fetches"] += 1
        return arr

    def _fetch_host(self, timeout_s: float):
        from ..core import runtime as rt_mod
        from ..core.ids import ObjectID
        rt = rt_mod.get_runtime_if_exists()
        if rt is None:
            raise RuntimeError("ray_tpu.init() first")
        reply = ObjectID.from_random()
        if hasattr(rt, "_rpc"):      # worker / driver client
            rt.send({"t": "device_fetch", "owner": self.owner,
                     "key": self.key, "reply_oid": reply.binary()})
        else:                        # head driver
            rt.device_fetch(self.owner, self.key, reply.binary())
        import time as _time
        from ..core.object_store import GetTimeoutError
        deadline = _time.monotonic() + timeout_s
        while True:
            try:
                status, payload = rt.store.get(reply, timeout_ms=200)
                break
            except GetTimeoutError:
                if _time.monotonic() > deadline:
                    raise TimeoutError(
                        f"device object fetch from {self.owner} timed out")
        rt.store.delete(reply)
        if status == "err":
            raise RuntimeError(payload)
        return payload

    def release(self) -> bool:
        """Drop the owner-side registration (owner process only)."""
        with _lock:
            hit = _registry.pop(self.key, None)
            if hit is not None:
                _stats["released"] += 1
            return hit is not None

    def __repr__(self):
        return (f"DeviceObject(owner={self.owner}, shape={self.shape}, "
                f"dtype={self.dtype})")


def _serve_fetch(store, key: str, reply_oid_bytes: bytes) -> None:
    """Owner-side: answer a device_fetch by writing the HOST copy of the
    array into the store at the caller-chosen reply oid."""
    import numpy as np

    from ..core.ids import ObjectID
    with _lock:
        arr = _registry.get(key)
    oid = ObjectID(reply_oid_bytes)
    if arr is None:
        store.put(oid, ("err", f"device object {key!r} not registered "
                               f"(released or evicted)"))
    else:
        store.put(oid, ("ok", np.asarray(arr)))
