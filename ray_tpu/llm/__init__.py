"""ray_tpu.llm — LLM serving and batch inference.

Reference parity: python/ray/llm (serve.llm vllm_engine.py:180 VLLMEngine /
llm_server.py:409, batch processor/base.py:104). The external vLLM engine is
replaced by JAX-native continuous-batching engines: paged_engine.py is the
production path (paged KV cache with block tables, Pallas paged decode
attention, chunked prefill so admission never stalls decode); engine.py is
the simpler dense-slot variant. Jitted prefill/decode over the whole batch,
in-jit sampling — attention/matmuls stay on the MXU, the Python loop only
admits/retires requests and allocates pages.

    from ray_tpu import llm
    engine = llm.InferenceEngine(llm.EngineConfig(model=cfg), params)
    out = engine.generate(["hello"], llm.SamplingParams(max_tokens=16))

Serving: llm.serving.build_llm_deployment(...) -> a Serve app exposing an
OpenAI-style completions API. Batch: llm.batch.build_llm_processor(...)
maps a Dataset through tokenize -> generate -> detokenize stages
(reference: data/llm.py:248).
"""
from .engine import EngineConfig, InferenceEngine, SamplingParams
from .paged_engine import PagedEngineConfig, PagedInferenceEngine
from .tokenizer import ByteTokenizer, get_tokenizer

__all__ = ["EngineConfig", "InferenceEngine", "SamplingParams",
           "PagedEngineConfig", "PagedInferenceEngine",
           "ByteTokenizer", "get_tokenizer", "serving", "batch", "lora",
           "multilora", "openai_api"]

from . import serving, batch, lora, multilora, openai_api  # noqa: E402
