"""data.llm analog: batch inference processors over Datasets.

Reference parity: python/ray/data/llm.py:248 build_llm_processor and
llm/_internal/batch/processor/base.py:104 (Processor = chained stages:
preprocess -> tokenize -> engine -> detokenize -> postprocess, each a Data
transform). Here the engine stage is a map_batches over the JAX engine —
one engine per task keeps it simple in round 1 (an actor-pool engine stage
is the optimization path).
"""
from __future__ import annotations

import dataclasses
from typing import Callable, Optional

from .engine import EngineConfig, InferenceEngine, SamplingParams

_ENGINE_CACHE: dict[str, InferenceEngine] = {}


def _get_engine(cfg: EngineConfig) -> InferenceEngine:
    key = repr((cfg.model, cfg.max_batch_size, cfg.max_seq_len,
                cfg.prefill_buckets))
    if key not in _ENGINE_CACHE:
        _ENGINE_CACHE[key] = InferenceEngine(cfg)
    return _ENGINE_CACHE[key]


@dataclasses.dataclass
class ProcessorConfig:
    engine: Optional[EngineConfig] = None
    sampling: SamplingParams = dataclasses.field(
        default_factory=SamplingParams)
    prompt_column: str = "prompt"
    output_column: str = "generated_text"
    batch_size: int = 8


class Processor:
    """(reference: processor/base.py:104) `__call__(Dataset) -> Dataset`."""

    def __init__(self, cfg: ProcessorConfig,
                 preprocess: Optional[Callable] = None,
                 postprocess: Optional[Callable] = None):
        self.cfg = cfg
        self.preprocess = preprocess
        self.postprocess = postprocess

    def __call__(self, ds):
        cfg = self.cfg
        if self.preprocess is not None:
            ds = ds.map(self.preprocess)

        def run_engine(batch: dict) -> dict:
            from ..models import llama
            engine_cfg = cfg.engine or EngineConfig(
                model=llama.llama_tiny(),
                max_batch_size=cfg.batch_size)
            # engines cache per worker process: model init + XLA compiles
            # are paid once, not once per block
            engine = _get_engine(engine_cfg)
            prompts = [str(p) for p in batch[cfg.prompt_column]]
            outs = engine.generate(prompts, cfg.sampling)
            result = dict(batch)
            result[cfg.output_column] = [o["text"] for o in outs]
            result["num_generated_tokens"] = [
                len(o["token_ids"]) for o in outs]
            return result

        ds = ds.map_batches(run_engine)
        if self.postprocess is not None:
            ds = ds.map(self.postprocess)
        return ds


def build_llm_processor(config: ProcessorConfig,
                        preprocess: Optional[Callable] = None,
                        postprocess: Optional[Callable] = None) -> Processor:
    """(reference: data/llm.py:248)"""
    return Processor(config, preprocess, postprocess)
