"""data.llm analog: batch inference processors over Datasets.

Reference parity: python/ray/data/llm.py:248 build_llm_processor,
llm/_internal/batch/processor/base.py:104 (Processor = an ordered chain
of stages wrapped by user preprocess/postprocess), and the stage family
under llm/_internal/batch/stages/ (chat_template_stage.py,
tokenize_stage.py, vllm_engine_stage.py, http_request_stage.py).

TPU-first shape: every stage is a Dataset transform; the engine stage is
a stateful map_batches over an AUTOSCALING actor pool (one engine per
actor — model init + XLA compiles paid once per actor, pool size scales
(min,max) with queue depth via data/executor.py), and the HTTP stage
fans rows out to any OpenAI-compatible endpoint (e.g. a ray_tpu serve
app or a disaggregated P/D deployment).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional

from .engine import EngineConfig, InferenceEngine, SamplingParams

_ENGINE_CACHE: dict[str, InferenceEngine] = {}


def _get_engine(cfg: EngineConfig) -> InferenceEngine:
    key = repr((cfg.model, cfg.max_batch_size, cfg.max_seq_len,
                cfg.prefill_buckets))
    if key not in _ENGINE_CACHE:
        _ENGINE_CACHE[key] = InferenceEngine(cfg)
    return _ENGINE_CACHE[key]


@dataclasses.dataclass
class ProcessorConfig:
    """(reference: processor/base.py:21 + OfflineProcessorConfig:55)"""
    engine: Optional[EngineConfig] = None
    sampling: SamplingParams = dataclasses.field(
        default_factory=SamplingParams)
    prompt_column: str = "prompt"
    output_column: str = "generated_text"
    batch_size: int = 8
    # engine actor pool (reference: OfflineProcessorConfig concurrency);
    # a (min, max) tuple autoscales with queue depth
    concurrency: Any = None


# --------------------------------------------------------------------- #
# stages (reference: llm/_internal/batch/stages/)
# --------------------------------------------------------------------- #

class Stage:
    """One Dataset -> Dataset transform with a name (reference:
    stages/base.py StatefulStage)."""

    name = "stage"

    def __call__(self, ds):
        raise NotImplementedError


class ChatTemplateStage(Stage):
    """messages column -> prompt column via the chat template (reference:
    stages/chat_template_stage.py)."""

    name = "ChatTemplate"

    def __init__(self, messages_column: str = "messages",
                 prompt_column: str = "prompt"):
        self.messages_column = messages_column
        self.prompt_column = prompt_column

    def __call__(self, ds):
        mc, pc = self.messages_column, self.prompt_column

        def apply(row: dict) -> dict:
            from .openai_api import apply_chat_template
            out = dict(row)
            out[pc] = apply_chat_template(list(row[mc]))
            return out

        return ds.map(apply)


class TokenizeStage(Stage):
    """prompt -> token ids (reference: stages/tokenize_stage.py Tokenize
    half). The engine consumes raw prompts too, but pre-tokenizing lets
    the pipeline dedupe/sort by length before engine admission."""

    name = "Tokenize"

    def __init__(self, prompt_column: str = "prompt",
                 ids_column: str = "input_ids", tokenizer: Any = None):
        self.prompt_column = prompt_column
        self.ids_column = ids_column
        self.tokenizer = tokenizer

    def __call__(self, ds):
        pc, ic = self.prompt_column, self.ids_column
        tok_spec = self.tokenizer

        def apply_batch(batch: dict) -> dict:
            from .tokenizer import get_tokenizer
            tok = get_tokenizer(tok_spec)  # built once per BLOCK, not row
            out = dict(batch)
            out[ic] = [tok.encode(str(p)) for p in batch[pc]]
            return out

        return ds.map_batches(apply_batch)


class DetokenizeStage(Stage):
    """token ids -> text (reference: tokenize_stage.py Detokenize
    half)."""

    name = "Detokenize"

    def __init__(self, ids_column: str = "generated_ids",
                 text_column: str = "generated_text",
                 tokenizer: Any = None):
        self.ids_column = ids_column
        self.text_column = text_column
        self.tokenizer = tokenizer

    def __call__(self, ds):
        ic, tc = self.ids_column, self.text_column
        tok_spec = self.tokenizer

        def apply_batch(batch: dict) -> dict:
            from .tokenizer import get_tokenizer
            tok = get_tokenizer(tok_spec)  # built once per BLOCK, not row
            out = dict(batch)
            out[tc] = [tok.decode(list(ids)) for ids in batch[ic]]
            return out

        return ds.map_batches(apply_batch)


def _default_engine_cfg(cfg: ProcessorConfig) -> EngineConfig:
    from ..models import llama
    return cfg.engine or EngineConfig(model=llama.llama_tiny(),
                                      max_batch_size=cfg.batch_size)


def _engine_batch(engine, sampling, prompt_column, output_column,
                  batch: dict) -> dict:
    """The one batch->result shaping both engine paths share."""
    prompts = [str(p) for p in batch[prompt_column]]
    outs = engine.generate(prompts, sampling)
    result = dict(batch)
    result[output_column] = [o["text"] for o in outs]
    result["generated_ids"] = [list(o["token_ids"]) for o in outs]
    result["num_generated_tokens"] = [len(o["token_ids"]) for o in outs]
    return result


class LLMPredictor:
    """Stateful pool member for ``Dataset.map_batches(LLMPredictor,
    concurrency=N, fn_constructor_args=(engine_cfg, sampling))``: builds
    its engine ONCE per pool actor (model init + XLA compiles paid once),
    then generates per batch (reference: vllm_engine_stage.py — one vLLM
    engine per stage actor).

    The offline batch-inference workhorse. Under the streaming executor
    (data/streaming, the default), the pool becomes a stage of
    long-lived workers fed over sealed channels: each predictor owns a
    deterministic stripe of the block sequence (worker ``w`` processes
    idxs ``w mod W`` in order — what keeps the pipeline deadlock-free
    and results bit-identical), streaming through its engine with no
    per-block task dispatches — at document scale the control-plane
    bill drops from one dispatch per block to one ``run_loop`` call per
    predictor for the whole run (rtpu_data_* counters prove it)."""

    def __init__(self, engine_cfg=None, sampling=None,
                 prompt_column: str = "prompt",
                 output_column: str = "generated_text"):
        if engine_cfg is None:
            engine_cfg = _default_engine_cfg(ProcessorConfig())
        self.engine = InferenceEngine(engine_cfg)
        self.sampling = sampling if sampling is not None \
            else SamplingParams()
        self.pc = prompt_column
        self.oc = output_column

    def __call__(self, batch: dict) -> dict:
        return _engine_batch(self.engine, self.sampling, self.pc,
                             self.oc, batch)


#: backwards-compat alias (pre-streaming name)
_EngineActor = LLMPredictor


class EngineStage(Stage):
    """The LLM stage (reference: vllm_engine_stage.py). With
    ``cfg.concurrency`` the engines run in a (min,max)-autoscaling actor
    pool; without, a cached engine per worker process via plain
    map_batches."""

    name = "Engine"

    def __init__(self, cfg: ProcessorConfig):
        self.cfg = cfg

    def __call__(self, ds):
        cfg = self.cfg
        engine_cfg = _default_engine_cfg(cfg)
        if cfg.concurrency is not None:
            return ds.map_batches(
                LLMPredictor, concurrency=cfg.concurrency,
                fn_constructor_args=(engine_cfg, cfg.sampling,
                                     cfg.prompt_column,
                                     cfg.output_column))

        def run_engine(batch: dict) -> dict:
            # engines cache per worker process: model init + XLA compiles
            # are paid once, not once per block
            return _engine_batch(_get_engine(engine_cfg), cfg.sampling,
                                 cfg.prompt_column, cfg.output_column,
                                 batch)

        return ds.map_batches(run_engine)


class HttpRequestStage(Stage):
    """POST each row's payload to an OpenAI-compatible endpoint
    (reference: stages/http_request_stage.py — concurrent requests with
    retry on transient failures). Rows of a block fan out over a thread
    pool; 429/5xx and socket errors retry with exponential backoff."""

    name = "HttpRequest"

    def __init__(self, url: str, payload_fn: Callable[[dict], dict],
                 output_column: str = "response",
                 timeout_s: float = 120.0, headers: Optional[dict] = None,
                 max_retries: int = 3, requests_per_block: int = 8):
        self.url = url
        self.payload_fn = payload_fn
        self.output_column = output_column
        self.timeout_s = timeout_s
        self.headers = headers or {}
        self.max_retries = max_retries
        self.requests_per_block = requests_per_block

    def __call__(self, ds):
        url, payload_fn = self.url, self.payload_fn
        oc, timeout_s = self.output_column, self.timeout_s
        headers = self.headers
        retries, width = self.max_retries, self.requests_per_block

        def one(payload: dict):
            import json as _json
            import time as _time
            import urllib.error
            import urllib.request
            delay = 0.5
            for attempt in range(retries + 1):
                try:
                    req = urllib.request.Request(
                        url, data=_json.dumps(payload).encode(),
                        headers={"Content-Type": "application/json",
                                 **headers})
                    with urllib.request.urlopen(req,
                                                timeout=timeout_s) as r:
                        return _json.loads(r.read())
                except urllib.error.HTTPError as e:
                    # 4xx (except 429) is the caller's bug: no retry
                    if e.code not in (429, 500, 502, 503, 504) \
                            or attempt == retries:
                        raise
                except (urllib.error.URLError, OSError):
                    if attempt == retries:
                        raise
                _time.sleep(delay)
                delay = min(delay * 2, 8.0)

        def apply_batch(batch: dict) -> dict:
            import concurrent.futures as cf
            n = len(next(iter(batch.values())))
            rows = [{k: batch[k][i] for k in batch} for i in range(n)]
            with cf.ThreadPoolExecutor(max_workers=width) as pool:
                resp = list(pool.map(
                    lambda row: one(payload_fn(row)), rows))
            out = dict(batch)
            out[oc] = resp
            return out

        return ds.map_batches(apply_batch)


# --------------------------------------------------------------------- #
# processor
# --------------------------------------------------------------------- #

class Processor:
    """(reference: processor/base.py:104) `__call__(Dataset) -> Dataset`:
    user preprocess -> ordered stages -> user postprocess."""

    def __init__(self, cfg: ProcessorConfig,
                 preprocess: Optional[Callable] = None,
                 postprocess: Optional[Callable] = None,
                 stages: Optional[list] = None):
        self.cfg = cfg
        self.preprocess = preprocess
        self.postprocess = postprocess
        self.stages: list[Stage] = (list(stages) if stages is not None
                                    else [EngineStage(cfg)])

    def list_stage_names(self) -> list[str]:
        return [s.name for s in self.stages]

    def __call__(self, ds):
        if self.preprocess is not None:
            ds = ds.map(self.preprocess)
        for stage in self.stages:
            ds = stage(ds)
        if self.postprocess is not None:
            ds = ds.map(self.postprocess)
        return ds


def build_llm_processor(config: ProcessorConfig,
                        preprocess: Optional[Callable] = None,
                        postprocess: Optional[Callable] = None,
                        stages: Optional[list] = None) -> Processor:
    """(reference: data/llm.py:248). Default = one EngineStage; pass
    ``stages`` for custom chains, e.g.::

        build_llm_processor(cfg, stages=[
            ChatTemplateStage(), EngineStage(cfg)])
    """
    return Processor(config, preprocess, postprocess, stages)
