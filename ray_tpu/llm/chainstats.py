"""Fixed-memory per-chain prefix-cache heat table (the engine half of
the cache heat plane).

A *chain* is a family of prompts sharing the same first full KV page —
the chain-head hash ``h_0 = H(salt || page_0_tokens)`` of the engine's
chained content hashes (paged_engine._hash_chain). Every request whose
prompt opens with the same system prompt (under the same tenant salt)
lands in one chain, so chain granularity is exactly the granularity
cache policy cares about: "this assistant's system prompt is hot",
"that tenant's adapter preamble went cold an hour ago".

Memory model — the same discipline as obs/tsdb.py's series table:

- every counter lives in a numpy array preallocated at construction;
  updates are ``arr[slot] += n`` — O(1), no per-update objects;
- distinct chains are capped at ``slots``; the first sight of a chain
  past the cap folds it into slot 0, the ``__overflow__`` sink, so
  client-controlled prompt diversity can NEVER grow engine memory
  (chains already established keep exact per-chain counts);
- per-slot identity (key bytes, display label, tenant label) is
  allocated once at slot creation — bounded by the cap — and reused
  verbatim as the metric label value afterwards, which is what keeps
  the shipped ``rtpu_llm_prefix_chain_*`` series inside the bounded
  top-K/``__overflow__`` vocabulary graftlint GL011 demands;
- ``stats()`` reports the byte ceiling the table can ever reach.

The table is observation only. Nothing in the engine's admission or
eviction policy reads it — the paged engine's outputs are bit-identical
with the table enabled or disabled (tests/test_cache_heat.py pins it).
"""
from __future__ import annotations

import time
from typing import Optional

import numpy as np

#: slot 0 — where chains past the cap (and pages whose chain was never
#: learned) aggregate. Mirrors obs/tsdb.py's OVERFLOW_KEY sink.
OVERFLOW_LABEL = "__overflow__"

#: per-slot bookkeeping estimate outside the numpy arrays: key dict
#: entry (~64B) + 16B digest + label/tenant strings (~80B). Used only
#: for the stats() byte ceiling — a reporting bound, not an allocator.
_SLOT_OVERHEAD_BYTES = 160


class ChainStatsTable:
    """Per-chain hit/miss/eviction/import/export accounting with a hard
    cardinality cap. NOT thread-safe by itself: updates happen under the
    engine's existing pool lock / stepping serialization (the same call
    sites that mutate ``engine.stats``); report paths read monotonically
    growing arrays, which is safe for telemetry snapshots."""

    def __init__(self, slots: int, page_bytes: int = 0):
        n = int(slots) + 1              # + the __overflow__ sink at 0
        self.cap = int(slots)
        self.page_bytes = int(page_bytes)
        self.hits = np.zeros((n,), np.int64)
        self.misses = np.zeros((n,), np.int64)
        self.tokens_saved = np.zeros((n,), np.int64)
        self.evictions = np.zeros((n,), np.int64)
        self.imported_pages = np.zeros((n,), np.int64)
        self.exported_pages = np.zeros((n,), np.int64)
        self.resident_pages = np.zeros((n,), np.int64)
        # spill tier (llm/tiering.py): pages of the chain resident in
        # the host tier, and pages promoted back into HBM from it —
        # zero everywhere while kv_spill is off, so legacy accounting
        # is reproduced exactly
        self.spilled_pages = np.zeros((n,), np.int64)
        self.promotions = np.zeros((n,), np.int64)
        self.last_hit = np.zeros((n,), np.float64)  # time.monotonic()
        self._slot_by_key: dict[bytes, int] = {}
        # slot identity, written once at creation (bounded label mint)
        self.labels: list[str] = [OVERFLOW_LABEL] + [""] * self.cap
        self.tenants: list[str] = [OVERFLOW_LABEL] + [""] * self.cap
        self._next = 1
        self.overflow_assignments = 0   # slot_for calls folded into 0

    # -- slot assignment (allocates at most `cap` times, ever) ---------

    def slot_for(self, head: bytes, salt: bytes = b"") -> int:
        """Slot for the chain-head hash; assigns a fresh slot on first
        sight while capacity remains, else the overflow sink. Steady
        state is one dict lookup."""
        s = self._slot_by_key.get(head)
        if s is not None:
            return s
        if self._next > self.cap:
            self.overflow_assignments += 1
            return 0
        s = self._next
        self._next = s + 1
        self._slot_by_key[head] = s
        self.labels[s] = head.hex()[:12]
        self.tenants[s] = salt.hex()[:8] if salt else "base"
        return s

    def peek(self, head: bytes) -> int:
        """Slot for a chain-head, or the overflow sink — never assigns."""
        return self._slot_by_key.get(head, 0)

    # -- O(1) hot-path updates (mirrors of the engine.stats bumps) -----

    def hit(self, slot: int, pages: int, tokens: int = 0) -> None:
        self.hits[slot] += pages
        self.tokens_saved[slot] += tokens
        self.last_hit[slot] = time.monotonic()

    def miss(self, slot: int, pages: int) -> None:
        self.misses[slot] += pages

    def evict(self, slot: int) -> None:
        self.evictions[slot] += 1

    def imported(self, slot: int, pages: int) -> None:
        self.imported_pages[slot] += pages

    def exported(self, slot: int, pages: int) -> None:
        self.exported_pages[slot] += pages

    def resident_add(self, slot: int) -> None:
        self.resident_pages[slot] += 1

    def resident_sub(self, slot: int) -> None:
        self.resident_pages[slot] -= 1

    def spilled_add(self, slot: int) -> None:
        self.spilled_pages[slot] += 1

    def spilled_sub(self, slot: int) -> None:
        self.spilled_pages[slot] -= 1

    def promoted(self, slot: int, pages: int) -> None:
        self.promotions[slot] += pages

    # -- reporting -----------------------------------------------------

    def _row(self, s: int, now: float) -> dict:
        return {
            "chain": self.labels[s],
            "tenant": self.tenants[s],
            "hits": int(self.hits[s]),
            "misses": int(self.misses[s]),
            "tokens_saved": int(self.tokens_saved[s]),
            "evictions": int(self.evictions[s]),
            "imported_pages": int(self.imported_pages[s]),
            "exported_pages": int(self.exported_pages[s]),
            "resident_pages": int(self.resident_pages[s]),
            "resident_bytes": int(self.resident_pages[s]) * self.page_bytes,
            "spilled_pages": int(self.spilled_pages[s]),
            "promotions": int(self.promotions[s]),
            "last_hit_age_s": round(now - self.last_hit[s], 3)
            if self.last_hit[s] else None,
        }

    def top(self, k: int, now: Optional[float] = None) -> list[dict]:
        """The k hottest tracked chains (by hits, ties to recency) plus
        the overflow sink whenever it holds anything — the bounded set
        telemetry ships and the directory publishes."""
        now = time.monotonic() if now is None else now
        used = self._next
        order = sorted(range(1, used),
                       key=lambda s: (-int(self.hits[s]),
                                      -self.last_hit[s]))
        rows = [self._row(s, now) for s in order[:max(int(k), 0)]]
        if (self.hits[0] or self.misses[0] or self.evictions[0]
                or self.overflow_assignments):
            rows.append(self._row(0, now))
        return rows

    def totals(self) -> dict:
        """Whole-table sums (overflow included). The counter-verification
        contract: each total equals the matching engine.stats aggregate —
        every aggregate bump has exactly one chain attribution."""
        return {
            "hits": int(self.hits.sum()),
            "misses": int(self.misses.sum()),
            "tokens_saved": int(self.tokens_saved.sum()),
            "evictions": int(self.evictions.sum()),
            "imported_pages": int(self.imported_pages.sum()),
            "exported_pages": int(self.exported_pages.sum()),
            "resident_pages": int(self.resident_pages.sum()),
            "spilled_pages": int(self.spilled_pages.sum()),
            "promotions": int(self.promotions.sum()),
        }

    def stats(self) -> dict:
        arrays = (self.hits, self.misses, self.tokens_saved,
                  self.evictions, self.imported_pages,
                  self.exported_pages, self.resident_pages,
                  self.spilled_pages, self.promotions, self.last_hit)
        return {
            "slots": self.cap,
            "tracked": self._next - 1,
            "overflow_assignments": self.overflow_assignments,
            "page_bytes": self.page_bytes,
            # the ceiling: preallocated arrays + at most `cap` slot
            # identities — what "client prompts can never grow engine
            # memory" means in bytes
            "max_bytes": sum(a.nbytes for a in arrays)
            + self.cap * _SLOT_OVERHEAD_BYTES,
        }

    def report(self, top_k: int = 8) -> dict:
        now = time.monotonic()
        return {"table": self.stats(), "totals": self.totals(),
                "chains": self.top(top_k, now)}
