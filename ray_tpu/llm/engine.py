"""JAX-native continuous-batching inference engine.

The vLLM replacement (reference: llm/_internal/serve/deployments/llm/vllm/
vllm_engine.py:180 — engine loop, scheduling, sampling; here re-designed for
XLA): a fixed pool of batch *slots* backs a slot-indexed KV cache; prefill
and decode are two jitted programs with static shapes (prompt lengths bucket
to powers of two to bound recompiles); sampling (greedy/temperature/top-k)
runs in-jit. The Python-side loop only admits requests into free slots and
retires finished ones — all math stays compiled.

Continuous batching: new requests join the running batch at any step; a
finished slot frees immediately. Decode cost is one [B, 1] step per token
over all active slots.
"""
from __future__ import annotations

import dataclasses
import threading
from collections import deque
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..models import llama
from .tokenizer import get_tokenizer


@dataclasses.dataclass
class SamplingParams:
    """(reference: vLLM SamplingParams surface)"""
    max_tokens: int = 64
    temperature: float = 0.0          # 0 = greedy
    top_k: int = 0                    # 0 = no top-k
    stop_token_ids: tuple = ()
    seed: int = 0
    # > 0: return the chosen token's log-probability per generated token
    # (model-natural log_softmax, not temperature-scaled; top-N
    # alternatives are not reported). Paged engine only.
    logprobs: int = 0


@dataclasses.dataclass
class EngineConfig:
    model: llama.LlamaConfig
    max_batch_size: int = 8
    max_seq_len: int = 1024
    prefill_buckets: tuple = (32, 64, 128, 256, 512, 1024)
    tokenizer: Any = None


@dataclasses.dataclass
class _Request:
    """One in-flight generation (shared by both engines)."""
    rid: int
    prompt_ids: list[int]
    params: SamplingParams
    out_ids: list[int] = dataclasses.field(default_factory=list)
    out_logps: list[float] = dataclasses.field(default_factory=list)
    slot: int = -1
    pages: list[int] = dataclasses.field(default_factory=list)
    prefill_pos: int = 0          # prompt tokens already prefilled (paged)
    # multi-LoRA (paged engine, cfg.max_adapters): the slot-table row
    # this request's dispatches gather — 0 = base model. Pinned for the
    # request's whole life: a hot-swap to a newer adapter version lands
    # in a different slot, so in-flight requests finish on the version
    # they were admitted with.
    adapter_slot: int = 0
    # prefix-cache chain seed (paged engine): empty for base traffic;
    # serving salts it with (adapter_id, version) so cached pages and
    # cluster-directory entries can never match across tenants
    prefix_salt: bytes = b""
    # content-hash chain of the prompt's FULL pages (paged engine prefix
    # caching); computed lazily at admission, None until then
    page_hashes: Optional[list] = None
    # cache heat plane (llm/chainstats.py): the per-chain stats slot
    # this request's prompt family resolved to; -1 = untracked
    chain_slot: int = -1
    done: bool = False
    submit_t: float = 0.0
    first_token_t: float = 0.0    # TTFT = first_token_t - submit_t
    # telemetry (llm/telemetry.py): admission time, wall-clock submit
    # (spans use wall time), serve request id, and the submitter's trace
    # context so the engine thread can emit an llm.request span
    admit_t: float = 0.0
    submit_wall: float = 0.0
    request_id: str = ""
    trace_ctx: Optional[tuple] = None
    event: threading.Event = dataclasses.field(
        default_factory=threading.Event)


def sample_logits(logits: jax.Array, rng: jax.Array, temperature: float,
                  top_k: int) -> jax.Array:
    """In-jit sampling over [B, V] logits (greedy / temperature / top-k)."""
    if temperature <= 0.0:
        return jnp.argmax(logits, axis=-1)
    logits = logits / temperature
    if top_k > 0:
        kth = jnp.sort(logits, axis=-1)[:, -top_k][:, None]
        logits = jnp.where(logits < kth, -1e30, logits)
    return jax.random.categorical(rng, logits, axis=-1)


def sample_logits_batch(logits: jax.Array, rng: jax.Array,
                        temps: jax.Array, top_ks: jax.Array, *,
                        any_sampled: bool = True,
                        any_topk: bool = True,
                        want_logp: bool = True):
    """Per-ROW sampling over [B, V] logits with per-row params, fully
    in-jit (no shape depends on the params, so one compiled program covers
    every request mix — the piece that lets sampling fuse into the decode
    step instead of costing a host round-trip per token).

    temps[b] <= 0 selects greedy for that row; top_ks[b] > 0 masks to that
    row's top-k logits, honored exactly for any k (per-row threshold from
    one full sort — the same cost the scalar sample_logits path paid).
    any_sampled/any_topk are STATIC hints the caller derives from the
    batch at dispatch time (it keys its jit cache on them): all-greedy
    batches skip the categorical entirely, no-top-k batches skip the sort.
    """
    def chosen_logp(tok):
        # model-natural log-probability of the chosen token (OpenAI
        # logprobs semantics): from the RAW logits, not the
        # temperature/top-k-processed ones. want_logp is STATIC like
        # any_sampled: batches with no logprobs request skip the
        # full-vocab log_softmax entirely (same design rule that lets
        # all-greedy batches skip the categorical).
        if not want_logp:
            return None
        lsm = jax.nn.log_softmax(logits, axis=-1)
        return jnp.take_along_axis(lsm, tok[:, None], axis=-1)[:, 0]

    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    if not any_sampled:
        return greedy, chosen_logp(greedy)
    scaled = logits / jnp.maximum(temps, 1e-6)[:, None]
    if any_topk:
        v = logits.shape[-1]
        svals = jnp.sort(scaled, axis=-1)                 # [B, V] asc
        k_idx = v - jnp.clip(top_ks, 1, v)
        kth = jnp.take_along_axis(svals, k_idx[:, None], axis=1)
        scaled = jnp.where((top_ks[:, None] > 0) & (scaled < kth),
                           -1e30, scaled)
    sampled = jax.random.categorical(rng, scaled, axis=-1)
    tok = jnp.where(temps <= 0.0, greedy, sampled).astype(jnp.int32)
    return tok, chosen_logp(tok)


class _EngineBase:
    """Request intake, sampling dispatch and result shaping shared by the
    dense-slot and paged engines (the engine-loop surface of the reference's
    VLLMEngine). Subclasses provide step()/has_work() and the two compiled
    programs; they must maintain self.cfg (with .max_seq_len), self._lock,
    self._pending, self._active, self._rng, self.tokenizer."""

    telemetry_kind = "dense"

    def generate(self, prompts, params=None) -> list[dict]:
        """Blocking batch generation; returns [{text, token_ids,
        prompt_tokens, ttft_s, finish_reason}] in prompt order."""
        if params is None:
            params = SamplingParams()
        plist = params if isinstance(params, list) else \
            [params] * len(prompts)
        reqs = [self.submit(p, sp) for p, sp in zip(prompts, plist)]
        while not all(r.done for r in reqs):
            self.step()
        return [self._result(r) for r in reqs]

    def submit(self, prompt, params: SamplingParams,
               adapter_slot: int = 0,
               prefix_salt: bytes = b"") -> _Request:
        import time
        ids = (self.tokenizer.encode(prompt) if isinstance(prompt, str)
               else list(prompt))
        # keep the prompt (up to the cache capacity) and clamp max_tokens
        # to the remaining room — never silently discard the prompt
        ids = ids[: self.cfg.max_seq_len - 2]
        if not ids:
            raise ValueError("empty prompt")
        if adapter_slot:
            table = getattr(self, "lora", None)
            if table is None:
                raise ValueError(
                    "adapter_slot requires a paged engine with "
                    "PagedEngineConfig.max_adapters > 0")
            if not 0 < adapter_slot < table.max_adapters:
                raise ValueError(
                    f"adapter_slot {adapter_slot} outside the slot "
                    f"table [1, {table.max_adapters})")
        capacity = self.cfg.max_seq_len - 1 - len(ids)
        if params.max_tokens > capacity:
            params = dataclasses.replace(params,
                                         max_tokens=max(1, capacity))
        from . import telemetry
        with self._lock:
            req = _Request(self._next_rid, ids, params)
            req.adapter_slot = int(adapter_slot)
            req.prefix_salt = bytes(prefix_salt)
            req.submit_t = time.perf_counter()
            self._next_rid += 1
            # stamp trace/request identity BEFORE publishing: once req is
            # in _pending a concurrently stepping engine thread can retire
            # a short request and emit its span/metrics immediately
            telemetry.on_submit(self, req)
            self._pending.append(req)
        return req

    def _finish_request(self, req: _Request, finish=None):
        """Retire a request: mark done, wake waiters, emit telemetry
        (TTFT/ITL/e2e observations + the request's trace span)."""
        if req.done:
            return
        req.done = True
        req.event.set()
        from . import telemetry
        telemetry.on_finish(self, req, finish)

    def has_work(self) -> bool:
        return bool(self._pending or self._active)

    def run_until_done(self, reqs: list[_Request]):
        while not all(r.done for r in reqs):
            self.step()

    def _sample_one(self, logits, params: SamplingParams):
        self._rng, sub = jax.random.split(self._rng)
        return np.asarray(sample_logits(logits, sub, params.temperature,
                                        params.top_k))

    def _sample_next_tokens(self, logits, rng) -> dict[int, int]:
        """Per-slot next token, batching slots that share sampling params."""
        by_temp: dict[tuple, list[int]] = {}
        for slot, req in self._active.items():
            by_temp.setdefault(
                (req.params.temperature, req.params.top_k), []).append(slot)
        next_tokens: dict[int, int] = {}
        for (temp, top_k), slots in by_temp.items():
            sampled = np.asarray(sample_logits(
                logits[jnp.asarray(slots)], rng, temp, top_k))
            for s, t in zip(slots, sampled):
                next_tokens[s] = int(t)
        return next_tokens

    def _eos_id(self):
        return getattr(self.tokenizer, "eos_id",
                       getattr(self.tokenizer, "eos_token_id", None))

    def _result(self, req: _Request) -> dict:
        eos = getattr(self.tokenizer, "eos_id", None)
        trimmed = [t for t in req.out_ids if t != eos]
        return {
            "text": self.tokenizer.decode(trimmed),
            "token_ids": req.out_ids,
            "prompt_tokens": len(req.prompt_ids),
            "ttft_s": (req.first_token_t - req.submit_t
                       if req.first_token_t else None),
            "finish_reason": ("stop" if eos is not None and eos in req.out_ids
                              else "length"),
            "logprobs": (list(req.out_logps) if req.params.logprobs
                         and req.out_logps else None),
        }


class InferenceEngine(_EngineBase):
    """Synchronous engine; the serving layer runs it on a background thread
    and exposes an async API (reference: VLLMEngine's engine loop)."""

    def __init__(self, cfg: EngineConfig, params: Optional[dict] = None,
                 rng_seed: int = 0):
        self.cfg = cfg
        self.model_cfg = cfg.model
        self.tokenizer = get_tokenizer(cfg.tokenizer)
        if params is None:
            params = llama.init(jax.random.PRNGKey(rng_seed), cfg.model)
        self.params = params
        self.cache = llama.init_slot_cache(cfg.model, cfg.max_batch_size,
                                           cfg.max_seq_len)
        self._free_slots = deque(range(cfg.max_batch_size))
        self._active: dict[int, _Request] = {}      # slot -> request
        self._pending: deque[_Request] = deque()
        self._next_rid = 0
        self._rng = jax.random.PRNGKey(rng_seed)
        self._lock = threading.Lock()
        # observability: dispatch/token counts (paged engine parity;
        # telemetry ships deltas from here to the Prometheus counters)
        self.stats = {"prefill_dispatches": 0, "decode_dispatches": 0,
                      "tokens_out": 0}

        mc = cfg.model
        max_len = cfg.max_seq_len

        @jax.jit
        def _prefill(params, cache, tokens, slot, true_len):
            """tokens [1, S] (right-padded to a bucket) -> writes K/V into
            the slot's cache row, sets its length to true_len, and returns
            the logits at the last REAL prompt position [V]. Pad positions'
            K/V land beyond true_len and are never attended (decode masks
            k_pos <= length) before being overwritten."""
            logits, ks, vs = llama.apply_with_kv(params, tokens, mc)
            cache_k = jax.lax.dynamic_update_slice(
                cache["k"], ks[:, 0:1].astype(cache["k"].dtype),
                (0, slot, 0, 0, 0))
            cache_v = jax.lax.dynamic_update_slice(
                cache["v"], vs[:, 0:1].astype(cache["v"].dtype),
                (0, slot, 0, 0, 0))
            lengths = cache["lengths"].at[slot].set(true_len)
            last = jax.lax.dynamic_index_in_dim(logits[0], true_len - 1, 0,
                                                keepdims=False)
            return last, {"k": cache_k, "v": cache_v, "lengths": lengths}

        @jax.jit
        def _decode(params, cache, tokens, active):
            """tokens [B] -> (logits [B, V], cache); inactive rows don't
            advance their length."""
            logits, new_cache = llama.decode_batched(
                params, tokens[:, None], cache, mc)
            lengths = jnp.where(active, new_cache["lengths"],
                                cache["lengths"])
            lengths = jnp.minimum(lengths, max_len - 1)
            return logits, {"k": new_cache["k"], "v": new_cache["v"],
                            "lengths": lengths}

        self._prefill_fn = _prefill
        self._decode_fn = _decode

    # -- engine loop -------------------------------------------------------

    def step(self):
        """One engine iteration: admit pending prompts (prefill), then one
        batched decode step over all active slots."""
        self._admit()
        if not self._active:
            return
        bs = self.cfg.max_batch_size
        tokens = np.zeros((bs,), np.int32)
        active = np.zeros((bs,), bool)
        for slot, req in self._active.items():
            tokens[slot] = req.out_ids[-1]
            active[slot] = True
        self._rng, sub = jax.random.split(self._rng)
        logits, self.cache = self._decode_fn(
            self.params, self.cache, jnp.asarray(tokens),
            jnp.asarray(active))
        self.stats["decode_dispatches"] += 1
        self._sample_and_retire(logits, sub)
        from . import telemetry
        telemetry.on_step(self)

    def _admit(self):
        with self._lock:
            from . import telemetry
            while self._pending and self._free_slots:
                req = self._pending.popleft()
                slot = self._free_slots.popleft()
                req.slot = slot
                self._active[slot] = req
                telemetry.on_admit(self, req)
                self._do_prefill(req)

    def _bucket(self, n: int) -> int:
        for b in self.cfg.prefill_buckets:
            if n <= b:
                return min(b, self.cfg.max_seq_len)
        return self.cfg.max_seq_len

    def _do_prefill(self, req: _Request):
        import time
        ids = req.prompt_ids
        bucket = self._bucket(len(ids))
        padded = ids + [0] * (bucket - len(ids))
        last_logits, self.cache = self._prefill_fn(
            self.params, self.cache, jnp.asarray([padded], jnp.int32),
            req.slot, len(ids))
        first = self._sample_one(last_logits[None, :], req.params)
        req.out_ids.append(int(first[0]))
        req.first_token_t = time.perf_counter()
        self.stats["prefill_dispatches"] += 1
        self.stats["tokens_out"] += 1
        from . import telemetry
        telemetry.on_first_token(self, req)

    def _sample_and_retire(self, logits, rng):
        next_tokens = self._sample_next_tokens(logits, rng)
        eos = self._eos_id()
        for slot in list(self._active):
            req = self._active[slot]
            tok = next_tokens[slot]
            req.out_ids.append(tok)
            self.stats["tokens_out"] += 1
            stop = (len(req.out_ids) >= req.params.max_tokens
                    or tok == eos or tok in req.params.stop_token_ids
                    or int(self.cache["lengths"][slot])
                    >= self.cfg.max_seq_len - 1)
            if stop:
                self._finish_request(req)
                del self._active[slot]
                self._free_slots.append(slot)
