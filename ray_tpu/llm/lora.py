"""LoRA adapters for the llama family.

Reference parity: the multi-LoRA multiplexing surface of ray.llm
(llm/_internal/serve — LoRA adapters resolved per request and multiplexed
across replicas; vLLM applies them in-kernel). TPU-first difference: XLA
pre-compiles the serving programs for fixed weight shapes, so adapters
are MERGED into a param copy at load time (W' = W + (alpha/r)·A@B) and
multiplexing picks the engine built for that merged copy — zero per-token
overhead, at the cost of one weight copy per resident adapter (bounded by
the server's adapter LRU).

Adapter format: npz with arrays ``<path>.A`` [L, d_in, r] and ``<path>.B``
[L, r, d_out] for each target in ("wq", "wk", "wv", "wo", "lm_head"),
plus scalars ``rank`` and ``alpha``.
"""
from __future__ import annotations

import io
import os
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..models import llama

# param targets: layers/* are stacked [L, ...]; lm_head is unstacked
_LAYER_TARGETS = ("wq", "wk", "wv", "wo")


def random_adapter(rng: jax.Array, cfg: llama.LlamaConfig, rank: int = 4,
                   alpha: float = 8.0,
                   targets: tuple = ("wq", "wv")) -> dict:
    """A random adapter (B≠0 so it changes outputs — tests/demos; real
    adapters come from training where B starts at zero)."""
    out = {"rank": np.int32(rank), "alpha": np.float32(alpha)}
    L = cfg.n_layers
    for t in targets:
        if t == "lm_head":
            shapes = (cfg.dim, cfg.vocab_size)
            lead = ()
        elif t in ("wk", "wv"):
            shapes = (cfg.dim, cfg.n_kv_heads * cfg.head_dim)
            lead = (L,)
        elif t == "wq":
            shapes = (cfg.dim, cfg.n_heads * cfg.head_dim)
            lead = (L,)
        elif t == "wo":
            shapes = (cfg.n_heads * cfg.head_dim, cfg.dim)
            lead = (L,)
        else:
            raise ValueError(f"unknown LoRA target {t!r}")
        rng, ka, kb = jax.random.split(rng, 3)
        out[f"{t}.A"] = np.asarray(jax.random.normal(
            ka, lead + (shapes[0], rank)) * 0.05, np.float32)
        out[f"{t}.B"] = np.asarray(jax.random.normal(
            kb, lead + (rank, shapes[1])) * 0.05, np.float32)
    return out


def save_adapter(adapter: dict, path: str) -> None:
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    np.savez(path, **adapter)


def load_adapter(path: str) -> dict:
    if not path.endswith(".npz"):
        path += ".npz"
    with np.load(path) as z:
        return {k: z[k] for k in z.files}


def adapter_to_bytes(adapter: dict) -> bytes:
    buf = io.BytesIO()
    np.savez(buf, **adapter)
    return buf.getvalue()


def adapter_from_bytes(blob: bytes) -> dict:
    with np.load(io.BytesIO(blob)) as z:
        return {k: z[k] for k in z.files}


def merge(params: dict, adapter: dict) -> dict:
    """params' = params + scale·A@B per target. Returns a NEW pytree;
    untouched leaves are shared (no copy)."""
    rank = int(adapter.get("rank", 4))
    alpha = float(adapter.get("alpha", rank))
    scale = alpha / max(rank, 1)
    out = dict(params)
    layers = dict(params["layers"])
    for t in _LAYER_TARGETS:
        a, b = adapter.get(f"{t}.A"), adapter.get(f"{t}.B")
        if a is None or b is None:
            continue
        delta = jnp.einsum("ldr,lrk->ldk", jnp.asarray(a), jnp.asarray(b))
        layers[t] = (layers[t].astype(jnp.float32)
                     + scale * delta).astype(params["layers"][t].dtype)
    out["layers"] = layers
    if "lm_head.A" in adapter:
        delta = jnp.asarray(adapter["lm_head.A"]) @ jnp.asarray(
            adapter["lm_head.B"])
        out["lm_head"] = (params["lm_head"].astype(jnp.float32)
                          + scale * delta).astype(params["lm_head"].dtype)
    return out
