"""ray_tpu.llm.multilora — many tenants, one shared paged base model.

Reference parity: ray.llm's multi-LoRA multiplexing (adapters resolved
per request, applied in-kernel by vLLM) rebuilt TPU-first: XLA wants
static shapes, so resident adapters live in a fixed-shape SLOT TABLE
(slots.py — [max_adapters, L, d, r] stacked/padded A/B per target,
slot 0 = base/no-op) and every engine dispatch carries per-row
``adapter_slot`` ids, so ONE compiled program serves a mixed-tenant
batch with zero per-tenant weight copies. Contrast llm/lora.py, which
MERGES an adapter into a full param copy (one engine per adapter —
kept as the single-tenant fast path and the parity oracle).

The production loop this package closes (ROADMAP item 4):

  train    — train.py LoRATrainer: base frozen, A/B trained on the
             Train substrate, CheckpointManager save/resume;
  publish  — registry.py AdapterRegistry: versioned adapter store on
             the WeightBroadcast slot pattern (ONE objstore put per
             publish, keep-window deletes; metadata rides the shared
             directory service — no new wire frames);
  serve    — manager.py MultiLoraManager: engine-side LRU of resident
             slots, hot-swap without engine restart, in-flight
             requests pinned to their admitted version; the serving
             layer (llm/serving.py) resolves adapter ids at admission
             and salts prefix-cache keys with (adapter_id, version) so
             warmed prefixes never leak across tenants.
"""
from .manager import MultiLoraManager
from .registry import AdapterRegistry
from .slots import AdapterSlotTable
from .train import LoRATrainConfig, LoRATrainer

__all__ = ["AdapterSlotTable", "AdapterRegistry", "MultiLoraManager",
           "LoRATrainConfig", "LoRATrainer"]
