"""Resident-adapter lifecycle: registry -> slot table, LRU, hot-swap.

One per serving replica (llm/serving.py builds it when the paged engine
has a slot table). ``resolve(adapter_id)`` is the admission-time hook:

1. the adapter's LATEST version comes from the registry's directory
   entry, TTL-cached (cfg.llm_lora_refresh_s) so the request hot path
   pays at most one dir_query per refresh window per adapter;
2. if (adapter_id, version) is already resident, the request rides its
   slot — and the slot's LRU position refreshes;
3. otherwise the payload is fetched (one store get) and installed into
   a slot: a free one, else the least-recently-used slot with ZERO
   in-flight requests (engine.adapter_slots_in_use — a live slot is
   never stolen, so in-flight requests stay pinned to their admitted
   version). All slots live -> RuntimeError, surfaced as a retryable
   overload by the serving layer.

Hot-swap is just (2)+(3) observing a newer version: the new version
lands in a DIFFERENT slot while v_old keeps serving its in-flight
requests; the old slot ages out of the LRU once they retire. No engine
restart, no dropped request.

Prefix isolation: ``prefix_salt(adapter_id, version)`` seeds the
engine's page-hash chains, so cached pages / cluster-directory entries
are keyed per (adapter_id, version) and can never cross tenants — or
versions (v2's pages must not serve a v1 request: different weights,
different K/V).
"""
from __future__ import annotations

import hashlib
import threading
import time
from collections import OrderedDict
from typing import Optional

from .registry import AdapterRegistry


def prefix_salt(adapter_id: str, version: int) -> bytes:
    """Chain seed for an adapter request's page hashes (empty for
    base). Digest-sized like the chain links, deterministic across
    processes so PD-disagg payloads and directory entries interoperate."""
    return hashlib.blake2b(
        f"lora:{adapter_id}@{version}".encode(), digest_size=16).digest()


class MultiLoraManager:
    """Maps (adapter_id, version) -> resident slot for one engine."""

    def __init__(self, engine, registry: Optional[AdapterRegistry] = None,
                 namespace: str = "default",
                 refresh_s: Optional[float] = None):
        if getattr(engine, "lora", None) is None:
            raise ValueError("engine has no adapter slot table "
                             "(PagedEngineConfig.max_adapters == 0)")
        self.engine = engine
        self.registry = registry or AdapterRegistry(namespace)
        if refresh_s is None:
            from ...core.config import cfg as rcfg
            refresh_s = rcfg.llm_lora_refresh_s
        self.refresh_s = float(refresh_s)
        self._lock = threading.Lock()
        # (adapter_id, version) -> slot            guarded by: self._lock
        self._slot_of: dict[tuple, int] = {}
        # slot -> (adapter_id, version), LRU order (oldest first)
        self._resident: "OrderedDict[int, tuple]" = OrderedDict()
        self._free = list(range(1, engine.lora.max_adapters))
        # slot -> resolve-to-submit reservation count; the eviction scan
        # treats a pinned slot exactly like a live one. Needed because
        # the engine only counts a request from submit() on, but the
        # serving layer does work (tokenize, cross-replica prefix
        # import) between resolve() and submit() — without the pin a
        # concurrent cold resolve could steal the slot in that window
        # and the request would decode with another tenant's weights.
        self._pins: dict[int, int] = {}        # guarded by: self._lock
        # adapter_id -> (expires_monotonic, version)
        self._latest_cache: dict[str, tuple] = {}
        self.stats = {"loads": 0, "evictions": 0, "swaps": 0,
                      "requests": 0, "hits": 0}

    # -- version resolution ----------------------------------------------

    def _latest(self, adapter_id: str) -> int:
        now = time.monotonic()
        hit = self._latest_cache.get(adapter_id)
        if hit is not None and hit[0] > now:
            return hit[1]
        v = self.registry.latest_version(adapter_id)
        if v is None:
            raise KeyError(
                f"adapter {adapter_id!r} is not in registry "
                f"{self.registry.namespace!r}")
        self._latest_cache[adapter_id] = (now + self.refresh_s, v)
        return v

    # -- the admission hook ----------------------------------------------

    def resolve(self, adapter_id: str, steplock=None,
                version: Optional[int] = None,
                pin: bool = False) -> tuple:
        """-> (slot, version, salt) for a request naming ``adapter_id``.
        ``steplock`` serializes a cold load's device scatter against the
        engine loop (serving passes its step lock; single-threaded
        callers may omit it). ``pin=True`` reserves the slot against
        eviction until ``unpin(slot)`` — REQUIRED for concurrent
        callers that do work between resolve and engine.submit (the
        engine's own in-flight accounting starts only at submit)."""
        if version is None:
            version = self._latest(adapter_id)
        key = (adapter_id, version)
        with self._lock:
            self.stats["requests"] += 1
            slot = self._slot_of.get(key)
            if slot is not None:
                self._resident.move_to_end(slot)
                self.stats["hits"] += 1
                if pin:
                    self._pins[slot] = self._pins.get(slot, 0) + 1
                self._telemetry()
                return slot, version, prefix_salt(adapter_id, version)
        # cold: fetch OUTSIDE the manager lock (a store get can block;
        # concurrent resolves of the same key are de-duped below)
        _, adapter = self.registry.fetch(adapter_id, version)
        with self._lock:
            raced = self._slot_of.get(key)
            if raced is not None:
                self._resident.move_to_end(raced)
                if pin:
                    self._pins[raced] = self._pins.get(raced, 0) + 1
                self._telemetry()
                return raced, version, prefix_salt(adapter_id, version)
            slot = self._claim_slot_locked()
            # the row is DIRTY from the first scatter on: unmap its old
            # resident before loading, and on a failed load clear the
            # row back to the base no-op — a partially written slot
            # must never stay addressable under any adapter's name
            prev = self._resident.pop(slot, None)
            if prev is not None:
                self._slot_of.pop(prev, None)
            try:
                if steplock is not None:
                    with steplock:
                        self.engine.load_adapter_slot(slot, adapter)
                else:
                    self.engine.load_adapter_slot(slot, adapter)
            except BaseException:
                try:
                    if steplock is not None:
                        with steplock:
                            self.engine.load_adapter_slot(slot, None)
                    else:
                        self.engine.load_adapter_slot(slot, None)
                except Exception:
                    pass  # row stays dirty but unmapped (never served)
                self._free.append(slot)
                raise
            self._slot_of[key] = slot
            self._resident[slot] = key
            if pin:
                self._pins[slot] = self._pins.get(slot, 0) + 1
            self.stats["loads"] += 1
            if any(aid == adapter_id and v != version
                   for aid, v in self._slot_of):
                # an older version is still resident (likely pinned by
                # in-flight requests): this load IS a hot-swap
                self.stats["swaps"] += 1
            self._telemetry()
            return slot, version, prefix_salt(adapter_id, version)

    def unpin(self, slot: int) -> None:
        """Drop one resolve-time reservation (call once the request has
        been submitted — the engine's in-flight count covers it from
        there — or the submit failed)."""
        with self._lock:
            n = self._pins.get(slot, 0) - 1
            if n > 0:
                self._pins[slot] = n
            else:
                self._pins.pop(slot, None)

    def _claim_slot_locked(self) -> int:
        """A slot to load into: free first, else the LRU slot with no
        in-flight requests AND no resolve-time pins. Never a live slot
        — in-flight requests are pinned to their admitted version's
        weights."""
        if self._free:
            return self._free.pop()
        live = self.engine.adapter_slots_in_use()
        for slot in self._resident:            # oldest first
            if not live.get(slot) and not self._pins.get(slot):
                self.stats["evictions"] += 1
                return slot
        raise RuntimeError(
            "overloaded: all adapter slots have in-flight requests; "
            "retry shortly (raise PagedEngineConfig.max_adapters to "
            "hold more resident adapters)")

    # -- observability ----------------------------------------------------

    def resident(self) -> dict:
        """{slot: (adapter_id, version)} currently installed."""
        with self._lock:
            return dict(self._resident)

    def _telemetry(self):
        try:
            from .. import telemetry as lt
            lt.on_lora_stats(self)
        except Exception:
            pass  # telemetry must never fail the request path
