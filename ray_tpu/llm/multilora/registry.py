"""Versioned adapter registry: train-side publish, serve-side live fetch.

The WeightBroadcast slot pattern (rl/podracer/sebulba.py) applied to
LoRA adapters: each (namespace, adapter_id) owns a DETERMINISTIC
12-byte id base, version v's payload seals under ``slot_oid(base, v)``
— ONE objstore put per publish, versions older than the keep window
deleted (lazily safe: ids are never reused, the channel invariant).
Version discovery rides the head's shared directory service
(core/directory.py dir_update/dir_query — the existing protocol v7
frames, no new wire frames): directory ``llm:lora:<namespace>`` maps
adapter_id -> {"version", "rank", "alpha", "targets", "ts"}, so a
serving replica resolves "latest" with one dir_query and fetches the
payload with one store get.

Consistency: directory entries are HINTS (last-write-wins). A fetch
of a version the keep window already reclaimed raises KeyError and the
caller re-resolves — by then the directory names a newer version.
Concurrent publishers of the SAME adapter_id race last-write-wins,
exactly like any directory key; version numbers stay monotonic because
each publisher bases v on the directory's current value.

Clusterless fallback: with no runtime (bare-engine tests, notebooks)
the registry degrades to an in-process dict store with identical
semantics, so train -> publish -> serve loops run anywhere.
"""
from __future__ import annotations

import hashlib
import threading
import time
from typing import Any, Optional

_DIR_PREFIX = "llm:lora:"


def _adapter_base(namespace: str, adapter_id: str) -> bytes:
    """Deterministic id base: publisher and consumers derive the same
    slot ids with no coordination beyond the directory entry."""
    return hashlib.blake2b(
        f"llm:lora:{namespace}:{adapter_id}".encode(),
        digest_size=12).digest()


def _slot(base: bytes, version: int):
    from ...dag.channel import slot_oid
    return slot_oid(base, version)


class _MemStore:
    """In-process store shim (clusterless mode): same put/get/delete
    surface the objstore client exposes, module-shared so a trainer and
    an engine in one process see each other's publishes."""

    def __init__(self):
        self._d: dict = {}
        self._lock = threading.Lock()

    def put(self, oid, value, is_exception: bool = False):
        with self._lock:
            self._d[bytes(oid.binary())] = value

    def get(self, oid, timeout_ms: int = -1):
        with self._lock:
            key = bytes(oid.binary())
            if key not in self._d:
                raise KeyError("object not found")
            return self._d[key]

    def delete(self, oid):
        with self._lock:
            self._d.pop(bytes(oid.binary()), None)


_mem_store = _MemStore()
# clusterless version metadata: directory analog, shared in-process
_mem_meta: dict = {}
_mem_lock = threading.Lock()


class AdapterRegistry:
    """Publish/fetch versioned LoRA adapters for one namespace (one
    served base model). Payloads are llm/lora.py adapter dicts."""

    def __init__(self, namespace: str = "default", keep: int = 4,
                 store: Optional[Any] = None):
        self.namespace = namespace
        self.dir_name = _DIR_PREFIX + namespace
        # keep >= 2: a replica that just resolved v must still be able
        # to fetch it after the trainer publishes v+1 (WeightBroadcast's
        # keep rule)
        self.keep = max(2, int(keep))
        self._store = store

    # -- plumbing --------------------------------------------------------

    def _resolve_store(self):
        if self._store is not None:
            return self._store
        from ...core import runtime as rt_mod
        rt = rt_mod.get_runtime_if_exists()
        store = getattr(rt, "store", None) if rt is not None else None
        self._store = store if store is not None else _mem_store
        return self._store

    def _clustered(self) -> bool:
        return self._resolve_store() is not _mem_store

    def _meta_lookup(self, adapter_id: Optional[str] = None) -> dict:
        """{adapter_id: meta} from the directory (or the local dict)."""
        if self._clustered():
            from ...core import directory as cdir
            got = cdir.query(self.dir_name,
                             keys=None if adapter_id is None
                             else [adapter_id])
            return (got or {}).get("entries") or {}
        with _mem_lock:
            d = _mem_meta.get(self.dir_name, {})
            if adapter_id is None:
                return dict(d)
            return ({adapter_id: d[adapter_id]}
                    if adapter_id in d else {})

    def _meta_publish(self, adapter_id: str, meta: dict) -> None:
        if self._clustered():
            from ...core import directory as cdir
            cdir.update(self.dir_name, put={adapter_id: meta})
        else:
            with _mem_lock:
                _mem_meta.setdefault(self.dir_name, {})[adapter_id] = meta

    # -- the registry surface --------------------------------------------

    def publish(self, adapter_id: str, adapter: dict,
                meta: Optional[dict] = None) -> int:
        """One store put + one directory merge; returns the new version.
        The payload is the adapter dict itself (small: two rank-r
        factors per target)."""
        store = self._resolve_store()
        base = _adapter_base(self.namespace, adapter_id)
        cur = self.latest_version(adapter_id)
        v = 0 if cur is None else cur + 1
        store.put(_slot(base, v), {"version": v, "ts": time.time(),
                                   "adapter": dict(adapter)})
        entry = {"version": v, "ts": time.time(),
                 "rank": int(adapter.get("rank", 4)),
                 "alpha": float(adapter.get("alpha", 0.0)),
                 "targets": sorted(k[:-2] for k in adapter
                                   if k.endswith(".A"))}
        if meta:
            entry.update(meta)
        self._meta_publish(adapter_id, entry)
        if v >= self.keep:
            try:
                store.delete(_slot(base, v - self.keep))
            except Exception:
                pass  # already reclaimed (store pressure / republish race)
        try:
            from .. import telemetry as lt
            lt.lora_publishes().inc(1.0, tags={"namespace": self.namespace})
        except Exception:
            pass  # telemetry must never fail a publish
        return v

    def latest_version(self, adapter_id: str) -> Optional[int]:
        entry = self._meta_lookup(adapter_id).get(adapter_id)
        return None if entry is None else int(entry["version"])

    def list(self) -> dict:
        """{adapter_id: meta} for every published adapter."""
        return self._meta_lookup()

    def fetch(self, adapter_id: str,
              version: Optional[int] = None) -> tuple:
        """-> (version, adapter dict). Raises KeyError for an unknown
        adapter or a version the keep window already reclaimed (callers
        re-resolve latest and retry — the directory names a newer one
        by then)."""
        if version is None:
            version = self.latest_version(adapter_id)
            if version is None:
                raise KeyError(
                    f"adapter {adapter_id!r} not in registry "
                    f"{self.namespace!r}")
        store = self._resolve_store()
        base = _adapter_base(self.namespace, adapter_id)
        try:
            payload = store.get(_slot(base, version), timeout_ms=5000)
        except Exception as e:
            raise KeyError(
                f"adapter {adapter_id!r} v{version} is not fetchable "
                f"(reclaimed by the keep window, or never published)"
            ) from e
        if payload is None or payload.get("version") != version:
            raise KeyError(
                f"adapter {adapter_id!r} v{version} payload missing")
        return version, payload["adapter"]
