"""Fixed-shape resident-adapter slot table for batched multi-LoRA decode.

The device side of multi-tenant serving: per LoRA target one stacked,
rank-padded pair of arrays

    "<t>.A" [S, L, in_t, R]   "<t>.B" [S, L, R, out_t]   t in wq/wk/wv/wo
    "lm_head.A" [S, d, R]     "lm_head.B" [S, R, V]
    "scale" [S] f32           (alpha / rank per slot)

where S = max_adapters and R = the table's max rank. Shapes never
depend on which adapters are resident, so the engine's jitted programs
compile ONCE and every dispatch just gathers rows by the batch's
``adapter_slot`` ids (models/llama.py _lora_add). Slot 0 is the
base-model no-op: all-zero A/B, scale 0 — padding contributes an exact
+0.0, so base rows through a lora-enabled program are bit-identical to
the plain program (and rank-r adapters padded to R are bit-identical
to their unpadded math: the extra lanes are 0·0 terms).

Loading a slot is a handful of donated in-place row scatters (the
import_prefill pattern) — callers must serialize loads against the
engine's stepping thread, exactly like cross-replica page imports.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ...models import llama

_LAYER_TARGETS = ("wq", "wk", "wv", "wo")


def _target_dims(cfg: llama.LlamaConfig, target: str) -> tuple:
    d, hd = cfg.dim, cfg.head_dim
    if target == "wq":
        return d, cfg.n_heads * hd
    if target in ("wk", "wv"):
        return d, cfg.n_kv_heads * hd
    if target == "wo":
        return cfg.n_heads * hd, d
    if target == "lm_head":
        return d, cfg.vocab_size
    raise ValueError(f"unknown LoRA target {target!r}")


class AdapterSlotTable:
    """max_adapters resident slots over one LlamaConfig; slot 0 = base."""

    def __init__(self, cfg: llama.LlamaConfig, max_adapters: int,
                 max_rank: int,
                 targets: tuple = ("wq", "wk", "wv", "wo", "lm_head")):
        if max_adapters < 2:
            raise ValueError("max_adapters must be >= 2 (slot 0 is the "
                             "reserved base/no-op slot)")
        if max_rank < 1:
            raise ValueError("max_rank must be >= 1")
        self.cfg = cfg
        self.max_adapters = int(max_adapters)
        self.max_rank = int(max_rank)
        self.targets = tuple(targets)
        S, L, R = self.max_adapters, cfg.n_layers, self.max_rank
        tree = {"scale": jnp.zeros((S,), jnp.float32)}
        for t in self.targets:
            din, dout = _target_dims(cfg, t)
            lead = () if t == "lm_head" else (L,)
            tree[f"{t}.A"] = jnp.zeros((S,) + lead + (din, R), jnp.float32)
            tree[f"{t}.B"] = jnp.zeros((S,) + lead + (R, dout), jnp.float32)
        self.tree = tree
        # donated in-place row scatter, shared across every array (the
        # jit cache keys on shapes); donation means a load never copies
        # the table — same contract as paged_engine._import_fn
        self._set_row = jax.jit(
            lambda arr, s, val: arr.at[s].set(val), donate_argnums=(0,))

    def nbytes(self) -> int:
        return sum(int(a.size) * 4 for a in self.tree.values())

    # -- mesh-parallel placement (parallel/sharding.py) --------------------

    def logical_axes(self) -> dict:
        """Logical axis names per table key, mirroring the base weights
        they add onto (models/llama.py logical_axes): each B matrix
        shards its OUTPUT dim the way the target weight shards it
        (wq→heads, wk/wv→kv_heads, wo→embed, lm_head→vocab), each A
        matrix shards its input dim, and the slot/layer/rank dims stay
        replicated — so the per-row gather + lora matmul compose with
        the sharded base matmul without moving either operand."""
        out_axis = {"wq": "heads", "wk": "kv_heads", "wv": "kv_heads",
                    "wo": "embed", "lm_head": "vocab"}
        in_axis = {"wq": "embed", "wk": "embed", "wv": "embed",
                   "wo": "heads", "lm_head": "embed"}
        axes = {"scale": (None,)}
        for t in self.targets:
            lead = (None,) if t == "lm_head" else (None, None)
            axes[f"{t}.A"] = lead + (in_axis[t], None)
            axes[f"{t}.B"] = lead + (None, out_axis[t])
        return axes

    def shard(self, mesh, shardings: dict) -> None:
        """Commit the table to ``shardings`` (a {key: NamedSharding}
        matching logical_axes()) on ``mesh`` and swap in per-key pinned
        scatter jits: a load's donated row scatter must carry
        out_shardings == in_shardings or XLA un-aliases the donated
        buffer and silently copies the whole table (the PR 12 donated-
        buffer lesson). Caller holds the same serialization contract as
        load()."""
        self.tree = jax.device_put(self.tree, shardings)
        repl = jax.sharding.NamedSharding(
            mesh, jax.sharding.PartitionSpec())
        self._set_row_fns = {
            k: jax.jit(lambda arr, s, val: arr.at[s].set(val),
                       donate_argnums=(0,),
                       in_shardings=(sh, repl, repl), out_shardings=sh)
            for k, sh in shardings.items()}

    def _row_fn(self, key: str):
        fns = getattr(self, "_set_row_fns", None)
        return self._set_row if fns is None else fns[key]

    def _padded(self, adapter: dict, target: str):
        """(A, B) padded to [.., in, R]/[.., R, out] f32, or None when
        the adapter lacks the target. Rank padding is exact: the extra
        lanes multiply 0·0 into the dot products."""
        a = adapter.get(f"{target}.A")
        if a is None:
            return None
        b = adapter[f"{target}.B"]
        a = np.asarray(a, np.float32)
        b = np.asarray(b, np.float32)
        r = a.shape[-1]
        if r > self.max_rank:
            raise ValueError(
                f"adapter rank {r} exceeds the slot table's max_rank "
                f"{self.max_rank} (target {target!r})")
        if r < self.max_rank:
            pad_a = [(0, 0)] * (a.ndim - 1) + [(0, self.max_rank - r)]
            pad_b = [(0, 0)] * (b.ndim - 2) + [(0, self.max_rank - r),
                                               (0, 0)]
            a = np.pad(a, pad_a)
            b = np.pad(b, pad_b)
        return a, b

    def load(self, slot: int, adapter: Optional[dict]) -> None:
        """Install ``adapter`` (llm/lora.py npz dict) into ``slot``;
        None clears the slot back to the base no-op. The caller must
        serialize against the engine's stepping thread (donated
        scatters invalidate the old buffers mid-dispatch otherwise)."""
        if not 0 < slot < self.max_adapters:
            raise ValueError(
                f"slot must be in [1, {self.max_adapters}); slot 0 is "
                f"the reserved base slot")
        if adapter is None:
            scale = 0.0
            per_target = {t: None for t in self.targets}
        else:
            rank = int(adapter.get("rank", 4))
            alpha = float(adapter.get("alpha", rank))
            scale = alpha / max(rank, 1)
            per_target = {t: self._padded(adapter, t)
                          for t in self.targets}
            unknown = [k[:-2] for k in adapter
                       if k.endswith(".A") and k[:-2] not in self.targets]
            if unknown:
                raise ValueError(
                    f"adapter targets {unknown} are not in this table's "
                    f"targets {self.targets}")
        t = self.tree
        for tgt, ab in per_target.items():
            ka, kb = f"{tgt}.A", f"{tgt}.B"
            if ab is None:
                zero_a = jnp.zeros(t[ka].shape[1:], jnp.float32)
                zero_b = jnp.zeros(t[kb].shape[1:], jnp.float32)
                t[ka] = self._row_fn(ka)(t[ka], slot, zero_a)
                t[kb] = self._row_fn(kb)(t[kb], slot, zero_b)
            else:
                t[ka] = self._row_fn(ka)(t[ka], slot, jnp.asarray(ab[0]))
                t[kb] = self._row_fn(kb)(t[kb], slot, jnp.asarray(ab[1]))
        t["scale"] = self._row_fn("scale")(
            t["scale"], slot, jnp.float32(scale))
