"""LoRATrainer: per-tenant fine-tuning on the Train substrate.

The train leg of the train -> publish -> serve loop (PAPERS.md:
"Fine-Tuning and Serving Gemma ... on Google Cloud TPU" — per-tenant
adapters fine-tuned on the training substrate, then served hot). Base
weights stay FROZEN; only the adapter factors A/B train (A ~ N(0, s),
B = 0, the standard LoRA init, so step 0 is exactly the base model).
The forward differentiates THROUGH llm/lora.py's merge — the identical
W + (alpha/r)·A@B math the merged serving engine runs, so a trained
adapter's serving outputs are the model the trainer optimized.

Two execution modes:

- ``scaling_config=None`` (default): the loop runs in-process — the
  CI-scale path and what notebooks want;
- with a ScalingConfig, the loop runs under train.DataParallelTrainer
  (gang scheduling, failure handling, result bus) with
  session.report()/Checkpoint per checkpoint_every steps and
  SIGKILL-safe resume via session.get_checkpoint().

Both modes checkpoint {step, adapter, opt} through train.Checkpoint
and both resume from the latest one. ``publish()`` lands the trained
adapter in the AdapterRegistry, where serving replicas' managers pick
it up live (no engine restart — the hot-swap path).
"""
from __future__ import annotations

import dataclasses
import os
from typing import Any, Callable, Optional

import numpy as np

from .registry import AdapterRegistry


@dataclasses.dataclass
class LoRATrainConfig:
    model: Any                       # llama.LlamaConfig
    rank: int = 4
    alpha: float = 8.0
    targets: tuple = ("wq", "wv")
    learning_rate: float = 5e-2
    steps: int = 40
    batch_size: int = 4
    seq_len: int = 32
    checkpoint_every: int = 10
    seed: int = 0


def _init_adapter(tcfg: LoRATrainConfig):
    """Trainable factors: A random, B zero (delta starts at exactly 0)."""
    import jax
    from ...models import llama as _llama

    out = {}
    rng = jax.random.PRNGKey(tcfg.seed)
    cfg = tcfg.model
    for t in tcfg.targets:
        if t == "lm_head":
            din, dout, lead = cfg.dim, cfg.vocab_size, ()
        elif t == "wq":
            din, dout, lead = cfg.dim, cfg.n_heads * cfg.head_dim, \
                (cfg.n_layers,)
        elif t in ("wk", "wv"):
            din, dout, lead = cfg.dim, cfg.n_kv_heads * cfg.head_dim, \
                (cfg.n_layers,)
        elif t == "wo":
            din, dout, lead = cfg.n_heads * cfg.head_dim, cfg.dim, \
                (cfg.n_layers,)
        else:
            raise ValueError(f"unknown LoRA target {t!r}")
        rng, ka = jax.random.split(rng)
        out[f"{t}.A"] = (jax.random.normal(
            ka, lead + (din, tcfg.rank)) * 0.02).astype(np.float32)
        out[f"{t}.B"] = np.zeros(lead + (tcfg.rank, dout), np.float32)
    del _llama  # shape math above needs only the config
    return out


def _default_data(tcfg: LoRATrainConfig) -> Callable:
    """Plain LM objective on random token streams (callers pass a real
    data_fn; this keeps the trainer runnable out of the box)."""
    def data_fn(step: int):
        rng = np.random.RandomState(tcfg.seed * 100003 + step)
        toks = rng.randint(1, tcfg.model.vocab_size,
                           (tcfg.batch_size, tcfg.seq_len + 1))
        return toks[:, :-1].astype(np.int32), toks[:, 1:].astype(np.int32)
    return data_fn


def _run_loop(tcfg: LoRATrainConfig, base_params, data_fn,
              state: Optional[dict], report_cb) -> dict:
    """The loop both modes share. ``state`` resumes {step, adapter,
    opt_leaves}; ``report_cb(step, loss, state_dict)`` fires every
    checkpoint_every steps and at the end. Returns the final state."""
    import jax
    import optax

    from .. import lora
    from ...models import llama

    opt = optax.adam(tcfg.learning_rate)
    if state is None:
        adapter = _init_adapter(tcfg)
        opt_state = opt.init(adapter)
        start = 0
    else:
        adapter = {k: np.asarray(v, np.float32)
                   for k, v in state["adapter"].items()}
        opt_state = jax.tree.unflatten(
            jax.tree.structure(opt.init(adapter)),
            [np.asarray(leaf) for leaf in state["opt_leaves"]])
        start = int(state["step"])
    scalars = {"rank": np.int32(tcfg.rank),
               "alpha": np.float32(tcfg.alpha)}
    mc = tcfg.model

    @jax.jit
    def step_fn(ad, opt_state, tokens, targets):
        def loss_fn(a):
            merged = lora.merge(base_params, {**a, **scalars})
            logits = llama.apply(merged, tokens, mc)
            return llama.cross_entropy_loss(logits, targets)
        loss, grads = jax.value_and_grad(loss_fn)(ad)
        updates, opt_state = opt.update(grads, opt_state, ad)
        return optax.apply_updates(ad, updates), opt_state, loss

    loss = float("nan")
    for i in range(start, tcfg.steps):
        tokens, targets = data_fn(i)
        adapter, opt_state, loss = step_fn(
            adapter, opt_state, np.asarray(tokens, np.int32),
            np.asarray(targets, np.int32))
        done = i + 1 >= tcfg.steps
        if done or (i + 1) % tcfg.checkpoint_every == 0:
            state = {"step": np.int32(i + 1),
                     "adapter": jax.device_get(adapter),
                     "opt_leaves": jax.device_get(
                         jax.tree.leaves(opt_state))}
            report_cb(i + 1, float(loss), state)
    if state is None:      # steps == 0 degenerate case
        state = {"step": np.int32(start),
                 "adapter": jax.device_get(adapter),
                 "opt_leaves": jax.device_get(jax.tree.leaves(opt_state))}
        report_cb(start, float(loss), state)
    return state


def _as_published(tcfg: LoRATrainConfig, adapter_arrays: dict) -> dict:
    """Trained factors -> the llm/lora.py npz adapter format (what the
    registry stores, the merged engine merges, and the slot table
    loads)."""
    return {"rank": np.int32(tcfg.rank), "alpha": np.float32(tcfg.alpha),
            **{k: np.asarray(v, np.float32)
               for k, v in adapter_arrays.items()}}


class LoRATrainer:
    """Fine-tune one adapter; checkpoint/resume; publish to a registry."""

    def __init__(self, tcfg: LoRATrainConfig, adapter_id: str,
                 base_params: Optional[dict] = None,
                 data_fn: Optional[Callable] = None,
                 storage_path: Optional[str] = None,
                 registry: Optional[AdapterRegistry] = None,
                 scaling_config=None, run_config=None):
        self.tcfg = tcfg
        self.adapter_id = adapter_id
        self._base_params = base_params
        self.data_fn = data_fn or _default_data(tcfg)
        self.storage_path = storage_path
        self.registry = registry or AdapterRegistry()
        self.scaling_config = scaling_config
        self.run_config = run_config
        self.adapter: Optional[dict] = None   # set by fit()
        self.last_loss: Optional[float] = None

    def _base(self):
        if self._base_params is None:
            import jax

            from ...models import llama
            self._base_params = llama.init(
                jax.random.PRNGKey(self.tcfg.seed), self.tcfg.model)
        return self._base_params

    # -- local (in-process) mode -----------------------------------------

    def _fit_local(self) -> dict:
        from ...train.checkpoint import Checkpoint, CheckpointManager
        manager = None
        state = None
        if self.storage_path:
            manager = CheckpointManager(
                os.path.join(self.storage_path, self.adapter_id,
                             "checkpoints"), num_to_keep=2)
            manager.scan_existing()
            if manager.latest is not None:
                try:
                    state = manager.latest.load_state()
                except Exception:
                    state = None   # truncated checkpoint: start over

        losses = []

        def report(step, loss, st):
            losses.append(loss)
            if manager is not None:
                manager.register(
                    Checkpoint.from_state(st, metadata={"step": step}),
                    {"step": step, "loss": loss})

        state = _run_loop(self.tcfg, self._base(), self.data_fn, state,
                          report)
        self.last_loss = losses[-1] if losses else None
        return state

    # -- Train-substrate mode --------------------------------------------

    def _fit_substrate(self) -> dict:
        import cloudpickle

        from ... import train as train_mod
        tcfg, data_fn = self.tcfg, self.data_fn
        base_blob = cloudpickle.dumps(self._base())

        def train_fn():
            import cloudpickle as _cp

            from ray_tpu import train as ts
            base = _cp.loads(base_blob)
            restored = ts.get_checkpoint()
            state = restored.load_state() if restored is not None else None

            def report(step, loss, st):
                ck = ts.Checkpoint.from_state(st, metadata={"step": step})
                ts.report({"step": step, "loss": loss}, checkpoint=ck)

            _run_loop(tcfg, base, data_fn, state, report)

        trainer = train_mod.DataParallelTrainer(
            train_fn, scaling_config=self.scaling_config,
            run_config=self.run_config)
        result = trainer.fit()
        if result.checkpoint is None:
            raise RuntimeError("LoRA training finished without a "
                               "checkpoint (steps < checkpoint_every?)")
        self.last_loss = (result.metrics or {}).get("loss")
        return result.checkpoint.load_state()

    # -- public surface ---------------------------------------------------

    def fit(self) -> dict:
        """Train (or resume) and return the adapter in llm/lora.py
        format."""
        state = (self._fit_local() if self.scaling_config is None
                 else self._fit_substrate())
        self.adapter = _as_published(self.tcfg, state["adapter"])
        return self.adapter

    def publish(self) -> int:
        """Land the trained adapter in the registry; serving replicas'
        managers observe the new version within their refresh TTL and
        hot-swap without an engine restart. Returns the version."""
        if self.adapter is None:
            raise RuntimeError("call fit() before publish()")
        return self.registry.publish(
            self.adapter_id, self.adapter,
            meta={"loss": self.last_loss, "steps": int(self.tcfg.steps)})
