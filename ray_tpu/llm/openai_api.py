"""OpenAI-compatible API router over LLM deployments.

Reference parity: the ray.llm OpenAI router
(llm/_internal/serve/deployments/routers/router.py — /v1/models,
/v1/completions, /v1/chat/completions with SSE streaming) built as a
plain Serve deployment: the HTTP proxy maps a request path like
``/llm/v1/chat/completions`` to the ingress method
``v1_chat_completions`` (see serve/proxy.py path routing), and
``"stream": true`` in the body switches the proxy to the SSE path.

    app = build_openai_app([LLMConfig(model_id="m1"), ...])
    serve.run(app, name="llm", http_port=8000)
    # curl -X POST :8000/llm/v1/chat/completions -d '{"model": "m1", ...}'
"""
from __future__ import annotations

import time
from typing import Optional

from .serving import LLMConfig, build_llm_deployment


def apply_chat_template(messages: list[dict]) -> str:
    """Minimal generic chat template (the byte tokenizer has no special
    tokens; reference models bring their own via the tokenizer)."""
    parts = []
    for m in messages:
        role = m.get("role", "user")
        parts.append(f"<|{role}|>\n{m.get('content', '')}")
    parts.append("<|assistant|>\n")
    return "\n".join(parts)


class OpenAIRouter:
    """Ingress deployment: routes by the request's ``model`` field to the
    child LLM deployment handles bound in at build time."""

    def __init__(self, model_ids: list, *handles):
        self._handles = dict(zip(model_ids, handles))

    def _handle(self, body: dict):
        model = body.get("model", "")
        base = model.split(":", 1)[0] if model else ""
        if base in self._handles:
            return self._handles[base]
        if not base and len(self._handles) == 1:
            return next(iter(self._handles.values()))
        raise ValueError(
            f"unknown model {model!r}; serving: {list(self._handles)}")

    # path-routed methods (proxy: /app/v1/models -> v1_models) ---------- #

    def v1_models(self, _body: Optional[dict] = None) -> dict:
        return {"object": "list",
                "data": [{"id": mid, "object": "model",
                          "owned_by": "ray_tpu"}
                         for mid in self._handles]}

    def v1_completions(self, body: dict):
        body = dict(body or {})
        h = self._handle(body)
        if body.get("stream"):
            return self._sse(h, body)
        out = h.options(method_name="completions").remote(body).result(
            timeout_s=300)
        out.update(id=f"cmpl-{int(time.time() * 1000)}",
                   created=int(time.time()))
        return out

    def v1_chat_completions(self, body: dict):
        body = dict(body or {})
        body["prompt"] = apply_chat_template(body.get("messages", []))
        h = self._handle(body)
        if body.get("stream"):
            return self._sse(h, body, chat=True)
        out = h.options(method_name="completions").remote(body).result(
            timeout_s=300)
        text = out["choices"][0]["text"]
        return {
            "id": f"chatcmpl-{int(time.time() * 1000)}",
            "object": "chat.completion",
            "created": int(time.time()),
            "model": out["model"],
            "choices": [{
                "index": 0,
                "message": {"role": "assistant", "content": text},
                "finish_reason": out["choices"][0]["finish_reason"],
            }],
            "usage": out["usage"],
        }

    def _sse(self, h, body: dict, chat: bool = False):
        """Generator of SSE lines (the proxy streams these verbatim)."""
        import json
        gen = h.options(method_name="completions_stream",
                        stream=True).remote(body)
        for chunk in gen:
            if chat:
                delta = chunk["choices"][0]["text"]
                chunk = {
                    "object": "chat.completion.chunk",
                    "model": chunk["model"],
                    "choices": [{
                        "index": 0,
                        "delta": {"content": delta},
                        "finish_reason": chunk["choices"][0][
                            "finish_reason"],
                    }],
                }
            yield f"data: {json.dumps(chunk)}\n\n"
        yield "data: [DONE]\n\n"


def build_openai_app(configs: list[LLMConfig], params_refs=None):
    """[LLMConfig] -> Serve Application with the OpenAI router as ingress
    (reference: build_openai_app)."""
    from .. import serve
    params_refs = params_refs or [None] * len(configs)
    children = [build_llm_deployment(cfg, ref)
                for cfg, ref in zip(configs, params_refs)]
    router = serve.deployment(OpenAIRouter, name="openai-router")
    return router.bind([c.model_id for c in configs], *children)
