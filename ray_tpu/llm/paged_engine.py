"""Paged-KV continuous-batching engine (the production serving path).

vLLM-analog re-designed for XLA (reference role:
llm/_internal/serve/deployments/llm/vllm/vllm_engine.py:180): the KV cache
is a pool of fixed-size pages shared by all sequences; each request owns a
block table of page ids, so cache capacity is bounded by TOKENS IN FLIGHT,
not max_batch x max_seq_len, and decode attention (Pallas,
ops/paged_attention.py) reads only the pages a sequence actually uses.

Two families of jitted programs with static shapes, keyed by unroll factor:
  - chunked prefill: up to `prefill_rows` page-aligned chunk-rows per
    dispatch (lax.scan carrying the caches, so consecutive rows may be
    consecutive chunks of one prompt; bounded work — a long prompt can no
    longer stall every decode slot; vLLM's chunked-prefill role);
  - windowed decode: `decode_window` tokens for every decode-ready slot
    per dispatch (lax.scan feeds each step's sampled tokens back in
    on-device; window 1 while prompts are pending keeps TTFT low).

Sampling is fused into both programs (sample_logits_batch), so one engine
step is ONE device dispatch and the only device->host traffic is the
sampled token block — dispatch latency, not math, dominates a serving step
on remote-attached accelerators. The Python loop does admission, page
allocation and retirement; all math stays compiled. Cache buffers are
donated through every program so XLA updates pages in place.
"""
from __future__ import annotations

import dataclasses
import threading
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..models import llama
from .engine import (  # noqa: F401 — SamplingParams re-exported
    SamplingParams, _EngineBase, _Request, sample_logits_batch,
)
from .tokenizer import get_tokenizer


@dataclasses.dataclass
class PagedEngineConfig:
    model: llama.LlamaConfig
    max_batch_size: int = 8
    page_size: int = 16
    num_pages: int = 512
    max_pages_per_seq: int = 64
    # prefill chunk (page multiple); up to prefill_rows chunks per step
    chunk_size: int = 128
    # dispatch batching: chunk-rows prefetched per prefill dispatch and
    # decode steps unrolled (lax.scan) per decode dispatch. Each dispatch
    # costs a host->device round trip; on remote-attached accelerators
    # that latency dominates a serving step, so both paths amortize it.
    # decode_window only applies when no prefill is pending (window 1
    # keeps TTFT low while prompts are still entering the batch).
    prefill_rows: int = 4
    decode_window: int = 8
    tokenizer: Any = None

    def __post_init__(self):
        if self.chunk_size % self.page_size:
            raise ValueError("chunk_size must be a multiple of page_size")
        if self.prefill_rows < 1 or self.decode_window < 1:
            raise ValueError("prefill_rows and decode_window must be >= 1")

    @property
    def max_seq_len(self) -> int:
        return self.max_pages_per_seq * self.page_size


class PagedInferenceEngine(_EngineBase):
    """Synchronous paged engine; serving runs it on a background thread."""

    def __init__(self, cfg: PagedEngineConfig, params: Optional[dict] = None,
                 rng_seed: int = 0, interpret: bool = False):
        self.cfg = cfg
        mc = cfg.model
        self.tokenizer = get_tokenizer(cfg.tokenizer)
        if params is None:
            params = llama.init(jax.random.PRNGKey(rng_seed), mc)
        self.params = params
        self.caches = llama.init_paged_cache(mc, cfg.num_pages,
                                             cfg.page_size)
        # page 0 is the write sink for slots that are idle during a decode
        # step (their dummy token writes land there, never attended); it is
        # never allocated to a sequence
        self._free_pages = list(range(1, cfg.num_pages))
        self._free_slots = list(range(cfg.max_batch_size))
        self._block_tables = np.zeros(
            (cfg.max_batch_size, cfg.max_pages_per_seq), np.int32)
        self._lengths = np.zeros((cfg.max_batch_size,), np.int32)
        self._active: dict[int, _Request] = {}
        self._prefilling: list[_Request] = []   # admitted, prompt not done
        self._pending: list[_Request] = []
        self._next_rid = 0
        self._rng_base = jax.random.PRNGKey(rng_seed ^ 0x5EED)
        self._rng_ctr = 0
        self._lock = threading.Lock()
        self._interpret = interpret
        # jitted programs, keyed by (static unroll factor, sampling mode):
        # unroll = decode window / prefill row count; mode = the
        # (any_sampled, any_topk) pair so all-greedy batches compile
        # without the categorical and no-top-k batches without the sort.
        # Cache pytrees are donated through every one so XLA updates
        # pages in place.
        self._decode_win_fns: dict[tuple, Any] = {}
        self._prefill_rows_fns: dict[tuple, Any] = {}

    @staticmethod
    def _sampling_mode(reqs) -> tuple:
        any_sampled = any(r.params.temperature > 0 for r in reqs)
        any_topk = any_sampled and any(
            r.params.top_k > 0 and r.params.temperature > 0 for r in reqs)
        return any_sampled, any_topk

    def _decode_window_fn(self, w: int, mode: tuple):
        """One dispatch = w decode steps for every slot: lax.scan unrolls
        decode+sample, feeding each step's sampled tokens straight back in
        on-device. Only the [B, w] token block crosses back to the host."""
        fn = self._decode_win_fns.get((w, mode))
        if fn is None:
            mc, page = self.cfg.model, self.cfg.page_size
            interpret = self._interpret
            any_sampled, any_topk = mode

            def run(p, c, tok0, bt, ln0, key, ctr, temps, top_ks):
                def body(carry, i):
                    toks, lens, caches = carry
                    logits, caches = llama.decode_paged(
                        p, toks[:, None], caches, bt, lens, mc,
                        page_size=page, interpret=interpret)
                    sub = jax.random.fold_in(
                        jax.random.fold_in(key, ctr), i)
                    nxt = sample_logits_batch(
                        logits, sub, temps, top_ks,
                        any_sampled=any_sampled, any_topk=any_topk)
                    return (nxt, lens + 1, caches), nxt

                (_, _, c), out = jax.lax.scan(
                    body, (tok0, ln0, c), jnp.arange(w))
                return out.T, c                     # [B, w]

            fn = jax.jit(run, donate_argnums=(1,))
            self._decode_win_fns[(w, mode)] = fn
        return fn

    def _prefill_rows_fn(self, r: int, mode: tuple):
        """One dispatch = r prefill chunk-rows + in-jit sampling of each
        row's last-token logits (used only for prompt-completing rows)."""
        fn = self._prefill_rows_fns.get((r, mode))
        if fn is None:
            mc, page = self.cfg.model, self.cfg.page_size
            any_sampled, any_topk = mode

            def run(p, c, chunks, bts, sps, tls, key, ctr, temps, top_ks):
                last, c = llama.prefill_paged_rows(
                    p, chunks, c, bts, sps, tls, mc, page_size=page)
                toks = sample_logits_batch(
                    last, jax.random.fold_in(key, ctr), temps, top_ks,
                    any_sampled=any_sampled, any_topk=any_topk)
                return toks, c

            fn = jax.jit(run, donate_argnums=(1,))
            self._prefill_rows_fns[(r, mode)] = fn
        return fn

    # -- public API --------------------------------------------------------

    def has_work(self) -> bool:
        return bool(self._pending or self._prefilling or self._active)

    # -- page allocation ---------------------------------------------------

    def _pages_needed(self, tokens: int) -> int:
        return (tokens + self.cfg.page_size - 1) // self.cfg.page_size

    def _ensure_pages(self, req: _Request, upto_tokens: int) -> bool:
        """Grow req's page list to cover upto_tokens; False if pool dry."""
        need = self._pages_needed(upto_tokens) - len(req.pages)
        if need <= 0:
            return True
        if len(self._free_pages) < need:
            return False
        for _ in range(need):
            req.pages.append(self._free_pages.pop())
        bt = self._block_tables[req.slot]
        bt[:len(req.pages)] = req.pages
        return True

    def _release(self, req: _Request):
        self._free_pages.extend(req.pages)
        req.pages = []
        if req.slot >= 0:
            # zero the row so nothing stale survives into the next tenant
            # (writes through leftover entries would hit recycled pages)
            self._block_tables[req.slot, :] = 0
            self._free_slots.append(req.slot)
            self._lengths[req.slot] = 0
            req.slot = -1

    # -- engine loop -------------------------------------------------------

    def step(self):
        """One iteration: admit, one prefill chunk (bounded), one decode."""
        self._admit()
        self._prefill_step()
        self._decode_step()

    def _admit(self):
        with self._lock:
            while self._pending and self._free_slots:
                # admission control: hold requests until the pool can cover
                # the whole prompt (avoids deadlocking a half-prefilled seq)
                req = self._pending[0]
                if (self._pages_needed(len(req.prompt_ids) + 1)
                        > len(self._free_pages)):
                    break
                self._pending.pop(0)
                req.slot = self._free_slots.pop(0)
                self._ensure_pages(req, len(req.prompt_ids) + 1)
                self._prefilling.append(req)

    def _prefill_step(self):
        import time
        if not self._prefilling:
            return
        cfg = self.cfg
        c, maxp = cfg.chunk_size, cfg.max_pages_per_seq
        # pack up to prefill_rows chunk-rows, queue order; a request with
        # several remaining chunks occupies consecutive rows (the scan
        # carries caches, so later rows see earlier rows' page writes)
        rows: list[tuple] = []              # (req, start, n_tokens)
        for req in self._prefilling:
            pos = req.prefill_pos
            while pos < len(req.prompt_ids) and len(rows) < cfg.prefill_rows:
                n = min(c, len(req.prompt_ids) - pos)
                rows.append((req, pos, n))
                pos += n
            if len(rows) >= cfg.prefill_rows:
                break
        # size the program to the rows actually packed (the jit cache is
        # keyed by r, at most prefill_rows variants): pad rows would be
        # correctness-safe but cost a full chunk forward each
        r = len(rows)
        chunks = np.zeros((r, c), np.int32)
        bts = np.zeros((r, maxp), np.int32)
        sps = np.zeros((r,), np.int32)
        tls = np.zeros((r,), np.int32)
        temps = np.zeros((r,), np.float32)
        topks = np.zeros((r,), np.int32)
        for i, (req, pos, n) in enumerate(rows):
            chunks[i, :n] = req.prompt_ids[pos:pos + n]
            bts[i] = self._block_tables[req.slot]
            sps[i], tls[i] = pos, n
            temps[i] = req.params.temperature
            topks[i] = req.params.top_k
        toks, self.caches = self._prefill_rows_fn(
            r, self._sampling_mode([q for q, _, _ in rows]))(
            self.params, self.caches, chunks, bts, sps, tls,
            self._rng_base, np.int32(self._rng_ctr), temps, topks)
        self._rng_ctr += 1
        toks = np.asarray(toks)
        for i, (req, pos, n) in enumerate(rows):
            req.prefill_pos = pos + n
            if req.prefill_pos < len(req.prompt_ids):
                continue
            # prompt done: the row's in-jit sampled token is the first
            # generated token
            tok = int(toks[i])
            req.out_ids.append(tok)
            req.first_token_t = time.perf_counter()
            self._lengths[req.slot] = len(req.prompt_ids)
            self._prefilling.remove(req)
            if getattr(req, "prefill_only", False):
                # disaggregated prefill: export the KV pages + first token
                # instead of decoding here (llm/pd_disagg.py)
                req.export_payload = self._export_kv_locked(req, tok)
                req.done = True
                req.event.set()
                self._release(req)
                continue
            self._active[req.slot] = req
            self._maybe_finish(req, tok)
        # NOTE: pad positions of the final chunk were written into the
        # sequence's own pages beyond its true length; decode masks
        # positions >= length so they are never attended.

    def _decode_step(self):
        if not self._active:
            return
        cfg = self.cfg
        bs, page = cfg.max_batch_size, cfg.page_size
        # full window only when no prompt is waiting: a pending prefill
        # gets interleaved every step, keeping TTFT low under bursts
        w = 1 if self._prefilling or self._pending else cfg.decode_window
        tokens = np.zeros((bs,), np.int32)
        lengths = np.zeros((bs,), np.int32)
        temps = np.zeros((bs,), np.float32)
        topks = np.zeros((bs,), np.int32)
        # slots not decoding this step get a zeroed block-table row: their
        # dummy writes go to sink page 0 instead of a live (possibly
        # reused) page
        bt = np.zeros_like(self._block_tables)
        allow: dict[int, int] = {}          # valid tokens per slot this window
        for slot, req in self._active.items():
            total = len(req.prompt_ids) + len(req.out_ids)
            # pre-allocate pages only for tokens this request can still
            # emit (window, max_tokens remainder, sequence ceiling —
            # whichever is least; over-grabbing the full window would
            # starve later slots under pool pressure). Window writes past
            # the allocation land on sink page 0 and those tokens are
            # discarded. If the pool runs dry the request keeps only the
            # tokens its allocated pages cover and finishes early.
            remaining = max(req.params.max_tokens - len(req.out_ids), 1)
            target = min(total + min(w, remaining), cfg.max_seq_len)
            if self._ensure_pages(req, target):
                allow[slot] = target - total
            else:
                allow[slot] = max(len(req.pages) * page - total, 0)
            tokens[slot] = req.out_ids[-1]
            lengths[slot] = self._lengths[slot]
            temps[slot] = req.params.temperature
            topks[slot] = req.params.top_k
            bt[slot] = self._block_tables[slot]
        out, self.caches = self._decode_window_fn(
            w, self._sampling_mode(self._active.values()))(
            self.params, self.caches, tokens, bt, lengths,
            self._rng_base, np.int32(self._rng_ctr), temps, topks)
        self._rng_ctr += 1
        out = np.asarray(out)               # [bs, w]
        for slot in list(self._active):
            req = self._active[slot]
            for j in range(w):
                if j >= allow[slot]:
                    # page pool exhausted mid-window: finish early rather
                    # than wedge (tokens past the allocation wrote to the
                    # sink page and are not trustworthy)
                    self._retire(req)
                    break
                tok = int(out[slot, j])
                req.out_ids.append(tok)
                self._lengths[slot] += 1
                if self._stop_after(req, tok):
                    self._retire(req)
                    break

    def _stop_after(self, req: _Request, tok: int) -> bool:
        """Stop condition evaluated after appending tok to req.out_ids."""
        total = len(req.prompt_ids) + len(req.out_ids)
        return (len(req.out_ids) >= req.params.max_tokens
                or tok == self._eos_id() or tok in req.params.stop_token_ids
                or total >= self.cfg.max_seq_len - 1)

    def _retire(self, req: _Request):
        req.done = True
        req.event.set()
        self._active.pop(req.slot, None)
        if req in self._prefilling:
            self._prefilling.remove(req)
        self._release(req)

    def _maybe_finish(self, req: _Request, tok: int):
        stop = self._stop_after(req, tok)
        if not stop:
            # growing by one token may need one more page
            total = len(req.prompt_ids) + len(req.out_ids)
            if not self._ensure_pages(req, total + 1):
                stop = True  # pool exhausted: finish early rather than wedge
        if stop:
            self._retire(req)

    # -- prefill/decode disaggregation (llm/pd_disagg.py; reference:
    # prefill_decode_disagg.py:64) ----------------------------------------

    def _export_kv_locked(self, req: _Request, first_token: int) -> dict:
        """Gather this request's KV pages to host arrays for transfer to a
        decode replica (the role the KV-connector plays for the reference's
        PD deployments)."""
        idx = jnp.asarray(np.asarray(req.pages, np.int32))
        pages = [{"k": np.asarray(layer["k"][idx]),
                  "v": np.asarray(layer["v"][idx])}
                 for layer in self.caches]
        return {"prompt_ids": list(req.prompt_ids),
                "first_token": int(first_token),
                "page_size": self.cfg.page_size,
                "pages": pages}

    def prefill_export(self, prompt, params: SamplingParams) -> dict:
        """Chunked-prefill `prompt` and return its exported KV payload
        (drives the engine loop until the export is ready)."""
        req = self.submit(prompt, params)
        req.prefill_only = True
        req.export_payload = None
        while req.export_payload is None and not req.done:
            self.step()
        if req.export_payload is None:
            raise RuntimeError("prefill finished without an export "
                               "(prompt rejected?)")
        return req.export_payload

    def import_prefill(self, payload: dict, params: SamplingParams,
                       ) -> _Request:
        """Seed a decode-ready sequence from an exported KV payload:
        allocate slot+pages, scatter the page data into this engine's
        pools, and place the request directly in the decode set."""
        import time
        if payload["page_size"] != self.cfg.page_size:
            raise ValueError(
                f"page_size mismatch: payload {payload['page_size']} vs "
                f"engine {self.cfg.page_size}")
        ids = list(payload["prompt_ids"])
        with self._lock:
            req = _Request(self._next_rid, ids, params)
            req.submit_t = time.perf_counter()
            self._next_rid += 1
            if not self._free_slots:
                raise RuntimeError("no free decode slot")
            req.slot = self._free_slots.pop(0)
            if not self._ensure_pages(req, len(ids) + 1):
                self._release(req)
                raise RuntimeError("page pool exhausted importing prefill")
            n_in = len(payload["pages"][0]["k"])
            if n_in != len(req.pages):
                self._release(req)
                raise ValueError(
                    f"payload covers {n_in} pages but this engine "
                    f"allocated {len(req.pages)} for the same prompt")
            idx = jnp.asarray(np.asarray(req.pages, np.int32))
            for li, layer in enumerate(self.caches):
                layer["k"] = self._import_fn(
                    layer["k"], idx, jnp.asarray(payload["pages"][li]["k"]))
                layer["v"] = self._import_fn(
                    layer["v"], idx, jnp.asarray(payload["pages"][li]["v"]))
            tok = int(payload["first_token"])
            req.out_ids.append(tok)
            req.prefill_pos = len(ids)
            req.first_token_t = time.perf_counter()
            self._lengths[req.slot] = len(ids)
            self._active[req.slot] = req
            self._maybe_finish(req, tok)
        return req

    @property
    def _import_fn(self):
        fn = getattr(self, "_import_fn_cached", None)
        if fn is None:
            # donated in-place page scatter: cache pools are not copied
            fn = jax.jit(lambda c, idx, data: c.at[idx].set(data),
                         donate_argnums=(0,))
            self._import_fn_cached = fn
        return fn

    # -- stats -------------------------------------------------------------

    def pool_stats(self) -> dict:
        return {
            "free_pages": len(self._free_pages),
            "total_pages": self.cfg.num_pages,
            "active": len(self._active),
            "prefilling": len(self._prefilling),
            "pending": len(self._pending),
        }
