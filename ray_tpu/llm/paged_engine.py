"""Paged-KV continuous-batching engine (the production serving path).

vLLM-analog re-designed for XLA (reference role:
llm/_internal/serve/deployments/llm/vllm/vllm_engine.py:180): the KV cache
is a pool of fixed-size pages shared by all sequences; each request owns a
block table of page ids, so cache capacity is bounded by TOKENS IN FLIGHT,
not max_batch x max_seq_len, and decode attention (Pallas,
ops/paged_attention.py) reads only the pages a sequence actually uses.

Two jitted programs with static shapes:
  - chunked prefill: one page-aligned chunk of one prompt per engine step
    (bounded work — a long prompt can no longer stall every decode slot;
    vLLM's chunked-prefill role);
  - batched decode: one token for every decode-ready slot.

The Python loop does admission, page allocation, sampling dispatch and
retirement; all math stays compiled. Cache buffers are donated through both
programs so XLA updates pages in place.
"""
from __future__ import annotations

import dataclasses
import threading
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..models import llama
from .engine import SamplingParams, _EngineBase, _Request  # noqa: F401 — SamplingParams re-exported
from .tokenizer import get_tokenizer


@dataclasses.dataclass
class PagedEngineConfig:
    model: llama.LlamaConfig
    max_batch_size: int = 8
    page_size: int = 16
    num_pages: int = 512
    max_pages_per_seq: int = 64
    # prefill chunk (page multiple); one chunk of one prompt per step
    chunk_size: int = 128
    tokenizer: Any = None

    def __post_init__(self):
        if self.chunk_size % self.page_size:
            raise ValueError("chunk_size must be a multiple of page_size")

    @property
    def max_seq_len(self) -> int:
        return self.max_pages_per_seq * self.page_size


class PagedInferenceEngine(_EngineBase):
    """Synchronous paged engine; serving runs it on a background thread."""

    def __init__(self, cfg: PagedEngineConfig, params: Optional[dict] = None,
                 rng_seed: int = 0, interpret: bool = False):
        self.cfg = cfg
        mc = cfg.model
        self.tokenizer = get_tokenizer(cfg.tokenizer)
        if params is None:
            params = llama.init(jax.random.PRNGKey(rng_seed), mc)
        self.params = params
        self.caches = llama.init_paged_cache(mc, cfg.num_pages,
                                             cfg.page_size)
        # page 0 is the write sink for slots that are idle during a decode
        # step (their dummy token writes land there, never attended); it is
        # never allocated to a sequence
        self._free_pages = list(range(1, cfg.num_pages))
        self._free_slots = list(range(cfg.max_batch_size))
        self._block_tables = np.zeros(
            (cfg.max_batch_size, cfg.max_pages_per_seq), np.int32)
        self._lengths = np.zeros((cfg.max_batch_size,), np.int32)
        self._active: dict[int, _Request] = {}
        self._prefilling: list[_Request] = []   # admitted, prompt not done
        self._pending: list[_Request] = []
        self._next_rid = 0
        self._rng = jax.random.PRNGKey(rng_seed)
        self._lock = threading.Lock()

        page = cfg.page_size

        # cache pytrees are donated so XLA updates pages in place
        self._decode_fn = jax.jit(
            lambda p, c, t, bt, ln: llama.decode_paged(
                p, t[:, None], c, bt, ln, mc, page_size=page,
                interpret=interpret),
            donate_argnums=(1,))
        self._prefill_fn = jax.jit(
            lambda p, c, chunk, bt, sp, tl: llama.prefill_paged_chunk(
                p, chunk[None, :], c, bt, sp, mc, page_size=page,
                true_chunk_len=tl),
            donate_argnums=(1,))

    # -- public API --------------------------------------------------------

    def has_work(self) -> bool:
        return bool(self._pending or self._prefilling or self._active)

    # -- page allocation ---------------------------------------------------

    def _pages_needed(self, tokens: int) -> int:
        return (tokens + self.cfg.page_size - 1) // self.cfg.page_size

    def _ensure_pages(self, req: _Request, upto_tokens: int) -> bool:
        """Grow req's page list to cover upto_tokens; False if pool dry."""
        need = self._pages_needed(upto_tokens) - len(req.pages)
        if need <= 0:
            return True
        if len(self._free_pages) < need:
            return False
        for _ in range(need):
            req.pages.append(self._free_pages.pop())
        bt = self._block_tables[req.slot]
        bt[:len(req.pages)] = req.pages
        return True

    def _release(self, req: _Request):
        self._free_pages.extend(req.pages)
        req.pages = []
        if req.slot >= 0:
            # zero the row so nothing stale survives into the next tenant
            # (writes through leftover entries would hit recycled pages)
            self._block_tables[req.slot, :] = 0
            self._free_slots.append(req.slot)
            self._lengths[req.slot] = 0
            req.slot = -1

    # -- engine loop -------------------------------------------------------

    def step(self):
        """One iteration: admit, one prefill chunk (bounded), one decode."""
        self._admit()
        self._prefill_step()
        self._decode_step()

    def _admit(self):
        with self._lock:
            while self._pending and self._free_slots:
                # admission control: hold requests until the pool can cover
                # the whole prompt (avoids deadlocking a half-prefilled seq)
                req = self._pending[0]
                if (self._pages_needed(len(req.prompt_ids) + 1)
                        > len(self._free_pages)):
                    break
                self._pending.pop(0)
                req.slot = self._free_slots.pop(0)
                self._ensure_pages(req, len(req.prompt_ids) + 1)
                self._prefilling.append(req)

    def _prefill_step(self):
        import time
        if not self._prefilling:
            return
        req = self._prefilling[0]
        c = self.cfg.chunk_size
        start = req.prefill_pos
        chunk_ids = req.prompt_ids[start:start + c]
        true_in_chunk = len(chunk_ids)
        chunk = np.zeros((c,), np.int32)
        chunk[:true_in_chunk] = chunk_ids
        logits, self.caches = self._prefill_fn(
            self.params, self.caches, jnp.asarray(chunk),
            jnp.asarray(self._block_tables[req.slot]),
            np.int32(start), np.int32(true_in_chunk))
        req.prefill_pos += true_in_chunk
        if req.prefill_pos >= len(req.prompt_ids):
            # prompt done: sample the first generated token
            last = jax.lax.dynamic_index_in_dim(
                logits, true_in_chunk - 1, 0, keepdims=False)
            tok = int(self._sample_one(last[None, :], req.params)[0])
            req.out_ids.append(tok)
            req.first_token_t = time.perf_counter()
            self._lengths[req.slot] = len(req.prompt_ids)
            self._prefilling.pop(0)
            if getattr(req, "prefill_only", False):
                # disaggregated prefill: export the KV pages + first token
                # instead of decoding here (llm/pd_disagg.py)
                req.export_payload = self._export_kv_locked(req, tok)
                req.done = True
                req.event.set()
                self._release(req)
                return
            self._active[req.slot] = req
            self._maybe_finish(req, tok)
        # NOTE: pad positions of the final chunk were written into the
        # sequence's own pages beyond its true length; decode masks
        # positions >= length so they are never attended.

    def _decode_step(self):
        if not self._active:
            return
        bs = self.cfg.max_batch_size
        tokens = np.zeros((bs,), np.int32)
        lengths = np.zeros((bs,), np.int32)
        # slots not decoding this step get a zeroed block-table row: their
        # dummy write goes to sink page 0 instead of a live (possibly
        # reused) page
        bt = np.zeros_like(self._block_tables)
        for slot, req in self._active.items():
            tokens[slot] = req.out_ids[-1]
            lengths[slot] = self._lengths[slot]
            bt[slot] = self._block_tables[slot]
        self._rng, sub = jax.random.split(self._rng)
        logits, self.caches = self._decode_fn(
            self.params, self.caches, jnp.asarray(tokens),
            jnp.asarray(bt), jnp.asarray(lengths))
        for slot in list(self._active):
            self._lengths[slot] += 1
        self._sample_and_retire(logits, sub)

    def _sample_and_retire(self, logits, rng):
        next_tokens = self._sample_next_tokens(logits, rng)
        for slot in list(self._active):
            req = self._active[slot]
            tok = next_tokens[slot]
            req.out_ids.append(tok)
            self._maybe_finish(req, tok)

    def _maybe_finish(self, req: _Request, tok: int):
        eos = self._eos_id()
        total = len(req.prompt_ids) + len(req.out_ids)
        stop = (len(req.out_ids) >= req.params.max_tokens
                or tok == eos or tok in req.params.stop_token_ids
                or total >= self.cfg.max_seq_len - 1)
        if not stop:
            # growing by one token may need one more page
            if not self._ensure_pages(req, total + 1):
                stop = True  # pool exhausted: finish early rather than wedge
        if stop:
            req.done = True
            req.event.set()
            self._active.pop(req.slot, None)
            if req in self._prefilling:
                self._prefilling.remove(req)
            self._release(req)

    # -- prefill/decode disaggregation (llm/pd_disagg.py; reference:
    # prefill_decode_disagg.py:64) ----------------------------------------

    def _export_kv_locked(self, req: _Request, first_token: int) -> dict:
        """Gather this request's KV pages to host arrays for transfer to a
        decode replica (the role the KV-connector plays for the reference's
        PD deployments)."""
        idx = jnp.asarray(np.asarray(req.pages, np.int32))
        pages = [{"k": np.asarray(layer["k"][idx]),
                  "v": np.asarray(layer["v"][idx])}
                 for layer in self.caches]
        return {"prompt_ids": list(req.prompt_ids),
                "first_token": int(first_token),
                "page_size": self.cfg.page_size,
                "pages": pages}

    def prefill_export(self, prompt, params: SamplingParams) -> dict:
        """Chunked-prefill `prompt` and return its exported KV payload
        (drives the engine loop until the export is ready)."""
        req = self.submit(prompt, params)
        req.prefill_only = True
        req.export_payload = None
        while req.export_payload is None and not req.done:
            self.step()
        if req.export_payload is None:
            raise RuntimeError("prefill finished without an export "
                               "(prompt rejected?)")
        return req.export_payload

    def import_prefill(self, payload: dict, params: SamplingParams,
                       ) -> _Request:
        """Seed a decode-ready sequence from an exported KV payload:
        allocate slot+pages, scatter the page data into this engine's
        pools, and place the request directly in the decode set."""
        import time
        if payload["page_size"] != self.cfg.page_size:
            raise ValueError(
                f"page_size mismatch: payload {payload['page_size']} vs "
                f"engine {self.cfg.page_size}")
        ids = list(payload["prompt_ids"])
        with self._lock:
            req = _Request(self._next_rid, ids, params)
            req.submit_t = time.perf_counter()
            self._next_rid += 1
            if not self._free_slots:
                raise RuntimeError("no free decode slot")
            req.slot = self._free_slots.pop(0)
            if not self._ensure_pages(req, len(ids) + 1):
                self._release(req)
                raise RuntimeError("page pool exhausted importing prefill")
            n_in = len(payload["pages"][0]["k"])
            if n_in != len(req.pages):
                self._release(req)
                raise ValueError(
                    f"payload covers {n_in} pages but this engine "
                    f"allocated {len(req.pages)} for the same prompt")
            idx = jnp.asarray(np.asarray(req.pages, np.int32))
            for li, layer in enumerate(self.caches):
                layer["k"] = self._import_fn(
                    layer["k"], idx, jnp.asarray(payload["pages"][li]["k"]))
                layer["v"] = self._import_fn(
                    layer["v"], idx, jnp.asarray(payload["pages"][li]["v"]))
            tok = int(payload["first_token"])
            req.out_ids.append(tok)
            req.prefill_pos = len(ids)
            req.first_token_t = time.perf_counter()
            self._lengths[req.slot] = len(ids)
            self._active[req.slot] = req
            self._maybe_finish(req, tok)
        return req

    @property
    def _import_fn(self):
        fn = getattr(self, "_import_fn_cached", None)
        if fn is None:
            # donated in-place page scatter: cache pools are not copied
            fn = jax.jit(lambda c, idx, data: c.at[idx].set(data),
                         donate_argnums=(0,))
            self._import_fn_cached = fn
        return fn

    # -- stats -------------------------------------------------------------

    def pool_stats(self) -> dict:
        return {
            "free_pages": len(self._free_pages),
            "total_pages": self.cfg.num_pages,
            "active": len(self._active),
            "prefilling": len(self._prefilling),
            "pending": len(self._pending),
        }
