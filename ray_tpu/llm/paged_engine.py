"""Paged-KV continuous-batching engine (the production serving path).

vLLM-analog re-designed for XLA (reference role:
llm/_internal/serve/deployments/llm/vllm/vllm_engine.py:180): the KV cache
is a pool of fixed-size pages shared by all sequences; each request owns a
block table of page ids, so cache capacity is bounded by TOKENS IN FLIGHT,
not max_batch x max_seq_len, and decode attention (Pallas,
ops/paged_attention.py) reads only the pages a sequence actually uses.

Two families of jitted programs with static shapes, keyed by unroll factor:
  - chunked prefill: up to `prefill_rows` page-aligned chunk-rows per
    dispatch (lax.scan carrying the caches, so consecutive rows may be
    consecutive chunks of one prompt; bounded work — a long prompt can no
    longer stall every decode slot; vLLM's chunked-prefill role);
  - windowed decode: `decode_window` tokens for every decode-ready slot
    per dispatch (lax.scan feeds each step's sampled tokens back in
    on-device; window 1 while prompts are pending keeps TTFT low).

Sampling is fused into both programs (sample_logits_batch), so one engine
step is ONE device dispatch and the only device->host traffic is the
sampled token block — dispatch latency, not math, dominates a serving step
on remote-attached accelerators. The Python loop does admission, page
allocation and retirement; all math stays compiled. Cache buffers are
donated through every program so XLA updates pages in place.
"""
from __future__ import annotations

import dataclasses
import hashlib
import threading
import time
from collections import OrderedDict, deque
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core import flight
from ..models import llama
from .engine import (  # noqa: F401 — SamplingParams re-exported
    SamplingParams, _EngineBase, _Request, sample_logits_batch,
)
from .tokenizer import get_tokenizer


@dataclasses.dataclass
class PagedEngineConfig:
    model: llama.LlamaConfig
    max_batch_size: int = 8
    page_size: int = 16
    num_pages: int = 512
    max_pages_per_seq: int = 64
    # prefill chunk (page multiple); up to prefill_rows chunks per step
    chunk_size: int = 128
    # dispatch batching: chunk-rows prefetched per prefill dispatch and
    # decode steps unrolled (lax.scan) per decode dispatch. Each dispatch
    # costs a host->device round trip; on remote-attached accelerators
    # that latency dominates a serving step, so both paths amortize it.
    # decode_window only applies when no prefill is pending (window 1
    # keeps TTFT low while prompts are still entering the batch).
    prefill_rows: int = 4
    decode_window: int = 8
    # speculative decoding (prompt-lookup n-gram drafts, greedy only):
    # propose up to spec_tokens continuation tokens by matching the last
    # spec_ngram tokens against the sequence's own history, verify them
    # all in ONE dispatch (models/llama.py verify_paged_rows) and accept
    # the longest agreeing prefix — up to spec_tokens+1 tokens per
    # dispatch on self-similar text, never a wrong token (the accept rule
    # reproduces exact greedy). It competes with the decode window: an
    # acceptance EMA falls back to windowed decode when drafts stop
    # landing (with periodic re-probes), so enabling it is never worse
    # than the window by more than the probe overhead. Worth it when
    # spec_tokens > decode_window, or on real hardware where one wide
    # verify is one model-step of compute vs w serial steps. 0 disables.
    spec_tokens: int = 0
    spec_ngram: int = 2
    # block-table page bucketing: every dispatch slices its block tables
    # to the smallest power-of-two page bucket (floor 4, clamped to
    # max_pages_per_seq) that covers the live pages PLUS the dispatch's
    # write window, so both the plain-JAX fallback's prefix gather and
    # the ragged kernel's page grid scale with TRUE sequence length
    # instead of pool capacity. Each bucket is one more static program
    # per family (same trick as the prefill-row buckets; warmup compiles
    # the whole ladder), so "auto" engages it only when
    # max_pages_per_seq >= 48 — short tables don't amortize the extra
    # programs' compiles (measured: a 40-page table loses more to the
    # extra XLA compiles than the narrower gathers win back on CI-scale
    # models). "on"/"off" force it.
    page_buckets: str = "auto"
    # batched multi-LoRA (llm/multilora): > 0 builds a fixed-shape
    # resident-adapter slot table of this many slots (slot 0 = base) and
    # threads per-row adapter_slot ids through every dispatch, so ONE
    # compiled program serves a mixed-tenant batch. Shapes are static —
    # no new program per adapter mix — and slot 0 padding is an exact
    # +0.0, so base traffic through a lora-enabled engine stays
    # bit-identical. 0 disables (no extra args traced at all).
    max_adapters: int = 0
    # rank ceiling of the slot table; lower-rank adapters zero-pad
    # (exact — padded lanes contribute 0·0 terms)
    lora_rank: int = 8
    lora_targets: tuple = ("wq", "wk", "wv", "wo", "lm_head")
    # automatic prefix caching (vLLM-style block-hash reuse): retired
    # requests park their full KV pages in a content-addressed LRU pool
    # instead of freeing them; a later request whose prompt shares a
    # page-aligned prefix maps those pages into its block table and starts
    # chunked prefill at the first uncached, chunk-aligned token. Shared
    # pages are refcounted and read-only (every write lands past the
    # cached region, so divergence copies instead of corrupting); the LRU
    # pool is reclaimed page-by-page under allocation pressure.
    enable_prefix_caching: bool = True
    # cache heat plane (llm/chainstats.py): fixed-memory per-chain stats
    # keyed by chain-head hash — hits/misses/evictions/imports per
    # prompt family, with a hard cardinality cap and an __overflow__
    # sink (à la obs/tsdb.py tsdb_max_series) so prompt diversity can
    # never grow engine memory. Pure observation: engine outputs are
    # bit-identical with the table on or off. 0 disables. top_k bounds
    # how many chains telemetry ships / the prefix directory publishes.
    chain_stats_slots: int = 256
    chain_stats_top_k: int = 8
    # tiered KV-cache (llm/tiering.py): demote an evicted refcount-0
    # cached page's KV to a host spill tier instead of freeing it, and
    # promote spilled runs back at admission time before cold prefill.
    # Heat-gated by the chain-stats table (min_hits / max_idle_s) and
    # byte-budgeted (kv_spill_max_bytes; coldest chains expire first).
    # Off by default: with kv_spill off the engine reproduces legacy
    # eviction accounting exactly — pages free, nothing is captured,
    # every spill counter stays zero.
    kv_spill: bool = False
    kv_spill_max_bytes: int = 64 << 20
    kv_spill_min_hits: int = 0
    kv_spill_max_idle_s: float = 0.0
    # mesh-parallel serving (parallel/mesh.py MeshSpec or its dict form,
    # e.g. {"tp": 4} or {"dp": 2, "tp": 2}): weights, the LoRA slot
    # table and the paged KV pool are placed with explicit NamedShardings
    # (KV over kv-heads on tp, block tables / token ids replicated) and
    # every program family compiles with in/out shardings pinned, so
    # steady-state decode moves NO bytes between devices beyond the
    # token-id inputs and sampled-token outputs (counter-verified:
    # stats["mesh_reshard_bytes"] stays 0). None = single-device engine,
    # exactly the pre-mesh traces.
    mesh: Any = None
    tokenizer: Any = None

    def __post_init__(self):
        if self.chunk_size % self.page_size:
            raise ValueError("chunk_size must be a multiple of page_size")
        if self.prefill_rows < 1 or self.decode_window < 1:
            raise ValueError("prefill_rows and decode_window must be >= 1")
        if self.page_buckets not in ("auto", "on", "off"):
            raise ValueError("page_buckets must be 'auto', 'on' or 'off'")
        if self.chain_stats_slots < 0 or self.chain_stats_top_k < 1:
            raise ValueError("chain_stats_slots must be >= 0 and "
                             "chain_stats_top_k >= 1")
        if self.kv_spill and not self.enable_prefix_caching:
            raise ValueError("kv_spill requires enable_prefix_caching "
                             "(the tier holds content-hashed pages)")
        if self.kv_spill and self.kv_spill_max_bytes <= 0:
            raise ValueError("kv_spill_max_bytes must be > 0")

    @property
    def max_seq_len(self) -> int:
        return self.max_pages_per_seq * self.page_size


class PagedInferenceEngine(_EngineBase):
    """Synchronous paged engine; serving runs it on a background thread."""

    telemetry_kind = "paged"

    def __init__(self, cfg: PagedEngineConfig, params: Optional[dict] = None,
                 rng_seed: int = 0, interpret: bool = False):
        self.cfg = cfg
        mc = cfg.model
        self.tokenizer = get_tokenizer(cfg.tokenizer)
        if params is None:
            params = llama.init(jax.random.PRNGKey(rng_seed), mc)
        self.params = params
        self.caches = llama.init_paged_cache(mc, cfg.num_pages,
                                             cfg.page_size)
        # page 0 is the write sink for slots that are idle during a decode
        # step (their dummy token writes land there, never attended); it is
        # never allocated to a sequence
        self._free_pages = list(range(1, cfg.num_pages))
        self._free_slots = deque(range(cfg.max_batch_size))
        self._block_tables = np.zeros(
            (cfg.max_batch_size, cfg.max_pages_per_seq), np.int32)
        self._lengths = np.zeros((cfg.max_batch_size,), np.int32)
        self._active: dict[int, _Request] = {}
        self._prefilling: list[_Request] = []   # admitted, prompt not done
        self._pending: deque[_Request] = deque()
        # -- prefix cache state (enable_prefix_caching) -------------------
        # Full pages are content-addressed by a chained hash
        # h_i = H(h_{i-1} || page_token_ids) — the chain makes the flat
        # dict an implicit trie: a page's key encodes its whole prefix.
        # _page_refs counts live request references per page; pages whose
        # refcount drops to zero but that hold published (hashed) content
        # park in _cached_lru (insertion order = eviction order) instead
        # of returning to _free_pages, and are reclaimed LRU-first when
        # allocation outruns the free list.
        self._prefix_on = bool(cfg.enable_prefix_caching)
        self._page_refs = np.zeros((cfg.num_pages,), np.int32)
        self._hash_to_page: dict[bytes, int] = {}
        self._page_to_hash: dict[int, bytes] = {}
        self._cached_lru: "OrderedDict[int, None]" = OrderedDict()
        # cluster prefix-directory delta tracking (serve/frontdoor):
        # hashes registered/unregistered since the last drain. Appended
        # only when track_page_publish is on (the serving layer enables
        # it), and only ever touched from the stepping thread — the
        # drain contract (drain_directory_delta) keeps it lock-free.
        self.track_page_publish = False
        self._dir_new: list[bytes] = []
        self._dir_dropped: list[bytes] = []
        # per-chain heat table (llm/chainstats.py): observation only —
        # no policy path reads it. _chain_of maps a registered page to
        # the chain slot it was published under, so evictions can be
        # attributed without re-deriving hashes; pages whose chain was
        # never learned fall to the overflow sink on eviction.
        self.chains = None
        self._chain_of: dict[int, int] = {}
        page_nbytes = sum(int(l["k"].nbytes) + int(l["v"].nbytes)
                          for l in self.caches) // max(cfg.num_pages, 1)
        if self._prefix_on and cfg.chain_stats_slots > 0:
            from .chainstats import ChainStatsTable
            self.chains = ChainStatsTable(cfg.chain_stats_slots,
                                          page_nbytes)
        # host spill tier (cfg.kv_spill, llm/tiering.py): demoted page
        # KV staged host-side / materialized to the object store by the
        # serving loop; all tier mutations happen under self._lock on
        # the same call paths that mutate the hot-cache structures
        self.spill = None
        # longest known head-rooted hash run per chain slot — what
        # proactive re-warm promotes (bounded: chain_stats_slots runs
        # of at most max_pages_per_seq 16-byte hashes)
        self._chain_runs: dict[int, list[bytes]] = {}
        if self._prefix_on and cfg.kv_spill:
            from .tiering import SpillPolicy, SpillTier
            self.spill = SpillTier(
                cfg.kv_spill_max_bytes, page_nbytes,
                SpillPolicy(min_hits=cfg.kv_spill_min_hits,
                            max_idle_s=cfg.kv_spill_max_idle_s))
            self.spill.bind_chains(self.chains)
        self._next_rid = 0
        # resident-adapter slot table (cfg.max_adapters): device arrays
        # every dispatch gathers per-row; loads are donated scatters the
        # caller serializes against stepping (serving's step lock)
        self.lora = None
        if cfg.max_adapters > 0:
            from .multilora.slots import AdapterSlotTable
            self.lora = AdapterSlotTable(mc, cfg.max_adapters,
                                         cfg.lora_rank, cfg.lora_targets)
        # mesh-parallel placement (cfg.mesh): committed NamedShardings
        # for weights / KV pool / slot table, and the pinned in/out
        # sharding tuples every program family compiles with
        self.mesh = None
        self._shardings = None
        if cfg.mesh is not None:
            self._init_mesh()
        self._rng_base = jax.random.PRNGKey(rng_seed ^ 0x5EED)
        self._rng_ctr = 0
        self._lock = threading.Lock()
        self._interpret = interpret
        # block-table width bucketing (cfg.page_buckets): "auto" engages
        # only when the table is long enough that gathering max_pages on
        # every dispatch dominates (threshold 48 pages)
        self._bucketing = cfg.page_buckets == "on" or (
            cfg.page_buckets == "auto" and cfg.max_pages_per_seq >= 48)
        # jitted programs, keyed by (static unroll factor, sampling mode,
        # block-table page bucket): unroll = decode window / prefill row
        # count; mode = the (any_sampled, any_topk) pair so all-greedy
        # batches compile without the categorical and no-top-k batches
        # without the sort; the page bucket (_page_bucket) is the table
        # width the dispatch was sliced to. Cache pytrees are donated
        # through every one so XLA updates pages in place.
        self._decode_win_fns: dict[tuple, Any] = {}
        self._prefill_rows_fns: dict[tuple, Any] = {}
        self._verify_fns: dict[tuple, Any] = {}
        # observability: dispatches per program family, spec accept stats
        self.stats = {"prefill_dispatches": 0, "decode_dispatches": 0,
                      "spec_dispatches": 0, "spec_proposed": 0,
                      "spec_accepted": 0, "tokens_out": 0,
                      # prefix cache: full prompt pages served from cache
                      # vs computed by prefill, LRU pages reclaimed under
                      # pressure, and prompt tokens whose prefill was
                      # skipped entirely
                      "prefix_hits": 0, "prefix_misses": 0,
                      "prefix_evictions": 0, "prefix_tokens_saved": 0,
                      # pages seeded from ANOTHER replica's cache via the
                      # cluster prefix directory (import_prefix), and
                      # cached pages gathered FOR a peer (export_prefix)
                      "prefix_imported_pages": 0,
                      "prefix_exported_pages": 0,
                      # spill tier (cfg.kv_spill): pages/bytes captured
                      # into the host tier, demote decisions that kept
                      # a tier copy (captures + clean re-evictions),
                      # pages promoted back into HBM (admission-time,
                      # re-warm, or cross-replica via the directory),
                      # pages expired from the tier (budget/teardown),
                      # and validate-on-promote drops (stale/corrupt
                      # tier content — cost a cold prefill, nothing
                      # else). All permanently 0 while kv_spill is off.
                      "spill_pages": 0, "spill_bytes": 0,
                      "spill_demotions": 0, "spill_promotions": 0,
                      "spill_expired": 0, "spill_drops": 0,
                      # mesh-parallel dispatch accounting (cfg.mesh):
                      # host<->device bytes a dispatch legitimately moves
                      # (token-id/table inputs, sampled-token outputs) vs
                      # bytes that would move because a committed buffer
                      # drifted off its pinned sharding. The reshard
                      # counter staying 0 IS the zero-involuntary-reshard
                      # contract; all permanently 0 while mesh is off.
                      "mesh_dispatches": 0, "mesh_input_bytes": 0,
                      "mesh_output_bytes": 0, "mesh_reshard_bytes": 0}
        # speculation controller: EMA of tokens-per-slot-per-spec-dispatch
        # (starts optimistic), plus a cooldown of windowed dispatches
        # before re-probing once the EMA drops below the window
        self._spec_gain = float(cfg.spec_tokens + 1)
        self._spec_cooldown = 0
        self._spec_cooldown_len = 8    # doubles per failed probe, to 256
        # step profiler (util/profiling.py): compile-vs-execute wall
        # split per program family; feeds profile_summary()'s MFU when
        # estimate_flops() has run
        from ..util.profiling import StepProfiler
        self.profiler = StepProfiler("paged_engine")

    # -- mesh-parallel placement (cfg.mesh) --------------------------------

    def _init_mesh(self):
        """Build the device mesh and commit weights, KV pool and the
        adapter slot table onto it with explicit NamedShardings: KV
        pages shard over kv-heads on tp, weights follow
        llama.logical_axes, block tables / token ids stay replicated.
        The pinned tuples cached here are what every program family
        compiles with (in == out for the donated caches, so page updates
        keep aliasing in place — an unconstrained output sharding breaks
        donation, the way it once did for sharded opt_state)."""
        from ..parallel import sharding as shardlib
        from ..parallel.mesh import MeshSpec, build_mesh, use_mesh
        cfg, mc = self.cfg, self.cfg.model
        spec = cfg.mesh
        if isinstance(spec, dict):
            spec = MeshSpec(**spec)
        # an engine's mesh spec names how many chips it WANTS, not how
        # many the process sees: take the leading slice so tp=2 works on
        # an 8-device host (replicas each build their own sub-mesh)
        devices = jax.devices()
        import math as _math
        want = _math.prod(
            getattr(spec, a) for a in ("pp", "dp", "fsdp", "ep", "sp", "tp"))
        if 0 < want <= len(devices):
            devices = devices[:want]
        self.mesh = build_mesh(spec, devices=devices)
        sizes = dict(zip(self.mesh.axis_names, self.mesh.devices.shape))
        tp = sizes.get("tp", 1)
        if mc.n_kv_heads % tp or mc.n_heads % tp or mc.mlp_dim % tp:
            raise ValueError(
                f"mesh tp={tp} must divide n_heads={mc.n_heads}, "
                f"n_kv_heads={mc.n_kv_heads} and mlp_dim={mc.mlp_dim}")
        # vocab shards over (tp, fsdp) — embeddings/lm_head split both ways
        vocab_ways = tp * sizes.get("fsdp", 1)
        if mc.vocab_size % vocab_ways:
            raise ValueError(
                f"mesh tp*fsdp={vocab_ways} must divide "
                f"vocab_size={mc.vocab_size}")
        with use_mesh(self.mesh):
            repl = shardlib.named_sharding(())
            pshard = shardlib.logical_sharding(llama.logical_axes(mc))
            kv = shardlib.named_sharding(
                (None, None, "kv_heads", "head_dim"))
            cshard = [{"k": kv, "v": kv} for _ in self.caches]
            lshard = repl
            if self.lora is not None:
                lshard = shardlib.logical_sharding(
                    self.lora.logical_axes())
        self.params = jax.device_put(self.params, pshard)
        self.caches = jax.device_put(self.caches, cshard)
        if self.lora is not None:
            self.lora.shard(self.mesh, lshard)
        self._shardings = {"params": pshard, "caches": cshard,
                           "lora": lshard, "repl": repl}

    def _mesh_scope(self):
        """Context manager making self.mesh the current mesh for jax work
        on this thread (dispatch, trace-time constrain() resolution,
        import scatters); a no-op nullcontext off-mesh."""
        if self.mesh is None:
            import contextlib
            return contextlib.nullcontext()
        from ..parallel.mesh import use_mesh
        return use_mesh(self.mesh)

    def _family_jit(self, run, n_plain: int):
        """jit a dispatch family with the donated caches at arg 1. With a
        mesh: every in/out sharding pinned — params/caches/lora at their
        committed placements, the n_plain host-array args (token ids,
        block tables, lengths, rng, temps) replicated, outputs (sampled
        tokens, logprobs) replicated and the cache outputs bit-matching
        their inputs so donation aliases. Pinning is what guarantees the
        compiled program never inserts an involuntary reshard of a
        committed buffer: any transfer beyond the declared host arrays
        would need an in/out sharding this signature forbids."""
        if self.mesh is None:
            return jax.jit(run, donate_argnums=(1,))
        sh = self._shardings
        ins = (sh["params"], sh["caches"]) + (sh["repl"],) * n_plain + (
            sh["lora"], sh["repl"])
        outs = (sh["repl"], sh["repl"], sh["caches"])
        return jax.jit(run, donate_argnums=(1,), in_shardings=ins,
                       out_shardings=outs)

    def _mesh_account(self, host_in: int, host_out: int):
        """Per-dispatch transfer accounting (mesh on only): declared
        host->device input bytes and device->host output bytes, plus a
        walk of every committed tree (params, caches, slot table)
        checking each leaf still sits at its pinned sharding — a leaf
        that drifted counts its full nbytes as involuntary-reshard
        traffic. Cheap (pure Python attribute compares), and the walk IS
        the counter-verification the zero-reshard contract is asserted
        against."""
        if self.mesh is None:
            return
        st = self.stats
        st["mesh_dispatches"] += 1
        st["mesh_input_bytes"] += int(host_in)
        st["mesh_output_bytes"] += int(host_out)
        sh = self._shardings
        bad = 0
        for tree, shtree in ((self.params, sh["params"]),
                             (self.caches, sh["caches"])):
            for leaf, want in zip(jax.tree.leaves(tree),
                                  jax.tree.leaves(shtree)):
                if not want.is_equivalent_to(leaf.sharding, leaf.ndim):
                    bad += int(leaf.nbytes)
        if self.lora is not None and self._shardings["lora"] is not None:
            for leaf, want in zip(jax.tree.leaves(self.lora.tree),
                                  jax.tree.leaves(sh["lora"])):
                if not want.is_equivalent_to(leaf.sharding, leaf.ndim):
                    bad += int(leaf.nbytes)
        st["mesh_reshard_bytes"] += bad

    @staticmethod
    def _sampling_mode(reqs) -> tuple:
        reqs = list(reqs)
        any_sampled = any(r.params.temperature > 0 for r in reqs)
        any_topk = any_sampled and any(
            r.params.top_k > 0 and r.params.temperature > 0 for r in reqs)
        # third static key: only batches containing a logprobs request
        # compile + pay the full-vocab log_softmax (engine.py
        # chosen_logp); everyone else runs the lean program
        want_logp = any(r.params.logprobs for r in reqs)
        return any_sampled, any_topk, want_logp

    # -- block-table page buckets -----------------------------------------

    _PAGE_BUCKET_FLOOR = 4

    def _page_bucket(self, need_pages: int) -> int:
        """Block-table width for a dispatch that must address
        ``need_pages`` logical pages (live prefix + every position the
        dispatch writes — a write past the width would CLAMP into the
        last column and clobber a live page instead of routing to the
        zero/sink entries beyond a row's allocation). Power-of-two,
        floored at 4 (tiny programs don't amortize their compile),
        clamped to max_pages_per_seq; the full width when bucketing is
        off, so every dispatch shape matches the unbucketed engine."""
        maxp = self.cfg.max_pages_per_seq
        if not self._bucketing:
            return maxp
        need = max(int(need_pages), 1)
        return min(maxp, max(self._PAGE_BUCKET_FLOOR,
                             1 << (need - 1).bit_length()))

    def _page_bucket_ladder(self) -> list[int]:
        """Every width _page_bucket can return (ascending) — what warmup
        must compile for the no-mid-burst-compiles contract to hold."""
        maxp = self.cfg.max_pages_per_seq
        if not self._bucketing:
            return [maxp]
        out = []
        b = self._PAGE_BUCKET_FLOOR
        while b < maxp:
            out.append(b)
            b <<= 1
        out.append(maxp)
        return out

    def _decode_window_fn(self, w: int, mode: tuple, pages: int):
        """One dispatch = w decode steps for every slot: lax.scan unrolls
        decode+sample, feeding each step's sampled tokens straight back in
        on-device. Only the [B, w] token block crosses back to the host.
        ``pages`` is the block-table width this program was built for
        (_page_bucket): part of the static key, like w and the mode."""
        fn = self._decode_win_fns.get((w, mode, pages))
        if fn is None:
            mc, page = self.cfg.model, self.cfg.page_size
            interpret = self._interpret
            any_sampled, any_topk, want_logp = mode

            def run(p, c, tok0, bt, ln0, key, ctr, temps, top_ks,
                    lora=None, slots=None):
                def body(carry, i):
                    toks, lens, caches = carry
                    logits, caches = llama.decode_paged(
                        p, toks[:, None], caches, bt, lens, mc,
                        page_size=page, interpret=interpret,
                        lora=lora, slots=slots)
                    sub = jax.random.fold_in(
                        jax.random.fold_in(key, ctr), i)
                    nxt, lp = sample_logits_batch(
                        logits, sub, temps, top_ks,
                        any_sampled=any_sampled, any_topk=any_topk,
                        want_logp=want_logp)
                    return (nxt, lens + 1, caches), (
                        (nxt, lp) if want_logp else nxt)

                (_, _, c), ys = jax.lax.scan(
                    body, (tok0, ln0, c), jnp.arange(w))
                if want_logp:
                    out, lps = ys
                    return out.T, lps.T, c          # [B, w] each
                return ys.T, None, c

            fn = self._family_jit(run, n_plain=7)
            self._decode_win_fns[(w, mode, pages)] = fn
        return fn

    def _prefill_rows_fn(self, r: int, mode: tuple, pages: int):
        """One dispatch = r prefill chunk-rows + in-jit sampling of each
        row's last-token logits (used only for prompt-completing rows).
        ``pages`` = block-table width (static key, see
        _decode_window_fn)."""
        fn = self._prefill_rows_fns.get((r, mode, pages))
        if fn is None:
            mc, page = self.cfg.model, self.cfg.page_size
            interpret = self._interpret
            any_sampled, any_topk, want_logp = mode

            def run(p, c, chunks, bts, sps, tls, key, ctr, temps, top_ks,
                    lora=None, slots=None):
                last, c = llama.prefill_paged_rows(
                    p, chunks, c, bts, sps, tls, mc, page_size=page,
                    interpret=interpret, lora=lora, slots=slots)
                toks, lps = sample_logits_batch(
                    last, jax.random.fold_in(key, ctr), temps, top_ks,
                    any_sampled=any_sampled, any_topk=any_topk,
                    want_logp=want_logp)
                return toks, lps, c

            fn = self._family_jit(run, n_plain=8)
            self._prefill_rows_fns[(r, mode, pages)] = fn
        return fn

    def _verify_fn(self, r: int, s1: int, pages: int,
                   want_logp: bool = False):
        """One dispatch = verify r rows of s1 = 1+drafts tokens; returns
        the model's greedy next token AT each fed position [r, s1] (and
        its log-probability when the batch asked for logprobs — a
        static key, like the sampling modes and the ``pages``
        block-table width)."""
        fn = self._verify_fns.get((r, s1, pages, want_logp))
        if fn is None:
            mc, page = self.cfg.model, self.cfg.page_size
            interpret = self._interpret

            def run(p, c, toks, bts, starts, lora=None, slots=None):
                logits, c = llama.verify_paged_rows(
                    p, toks, c, bts, starts, mc, page_size=page,
                    interpret=interpret, lora=lora, slots=slots)
                y = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                if not want_logp:
                    return y, None, c
                lp = jnp.take_along_axis(
                    jax.nn.log_softmax(logits, axis=-1), y[..., None],
                    axis=-1)[..., 0]
                return y, lp, c

            fn = self._family_jit(run, n_plain=3)
            self._verify_fns[(r, s1, pages, want_logp)] = fn
        return fn

    # -- multi-LoRA slot plumbing (cfg.max_adapters; llm/multilora) --------

    def _lora_args(self, slots) -> tuple:
        """Trailing (lora_tree, slots) args for a dispatch: (None, None)
        when the table is disabled — the jitted programs then trace the
        exact pre-LoRA math."""
        if self.lora is None:
            return (None, None)
        return (self.lora.tree, np.asarray(slots, np.int32))

    def load_adapter_slot(self, slot: int, adapter) -> None:
        """Install an adapter into a slot table row (None clears it).
        CALLER must serialize against the stepping thread (serving.py's
        step lock): the donated row scatters invalidate the old table
        buffers, same contract as import_prefix."""
        if self.lora is None:
            raise ValueError(
                "engine built without a slot table "
                "(PagedEngineConfig.max_adapters == 0)")
        self.lora.load(slot, adapter)

    def adapter_slots_in_use(self) -> dict:
        """{slot: live request count} over pending+prefilling+active —
        what the manager's LRU must NOT evict (a resident adapter with
        in-flight requests is pinned to its admitted version)."""
        with self._lock:
            counts: dict[int, int] = {}
            for req in (list(self._pending) + list(self._prefilling)
                        + list(self._active.values())):
                s = getattr(req, "adapter_slot", 0)
                if s:
                    counts[s] = counts.get(s, 0) + 1
            return counts

    # -- public API --------------------------------------------------------

    def warmup(self, sample_modes=((False, False),),
               families=("prefill", "decode", "verify")) -> float:
        """Compile every program family this engine dispatches, BEFORE
        serving traffic; returns seconds spent.

        The reference's serving engine does the same at deployment time
        (vLLM profiles and captures its execution graphs during engine
        init, before the server admits requests — vllm_engine.py:180's
        engine start path). Here the stakes are higher: one mid-burst XLA
        compile on a remote-attached TPU is tens of requests' worth of
        latency, landing exactly when the first burst does.

        Families: prefill rows over the power-of-two buckets, decode
        windows {1, decode_window}, and — when speculation is on — the
        verify-row buckets; every family crossed with the block-table
        page-bucket ladder when cfg.page_buckets engages (a dispatch's
        table width is a static program key exactly like its row
        count). ``families`` narrows the set for replicas that only
        ever run one side (a P/D prefill replica never decodes; a
        decode replica never prefills — compiling the other side would
        double deploy-time for nothing). Dummy dispatches carry zero
        block tables and zero true_lens, so every write routes to sink
        page 0 and no visible engine state is touched; the donated
        caches round-trip through each program.
        """
        import time as _time
        with self._mesh_scope():
            return self._warmup_traced(sample_modes, families,
                                       _time.perf_counter())

    def _warmup_traced(self, sample_modes, families, t0) -> float:
        import time as _time
        cfg = self.cfg
        bs, c = cfg.max_batch_size, cfg.chunk_size
        key, ctr = self._rng_base, np.int32(0)
        modes = [tuple(m) + (False,) * (3 - len(m)) for m in sample_modes]
        buckets = self._page_bucket_ladder()
        for mode in modes:
            for maxp in (buckets if "prefill" in families else ()):
                rb = 1
                while True:
                    rb = min(rb, cfg.prefill_rows)
                    tw = _time.perf_counter()
                    toks, _lps, self.caches = self._prefill_rows_fn(
                        rb, mode, maxp)(
                        self.params, self.caches,
                        np.zeros((rb, c), np.int32),
                        np.zeros((rb, maxp), np.int32),
                        np.zeros((rb,), np.int32), np.zeros((rb,), np.int32),
                        key, ctr, np.zeros((rb,), np.float32),
                        np.zeros((rb,), np.int32),
                        *self._lora_args(np.zeros((rb,), np.int32)))
                    np.asarray(toks)
                    # book as compile (and mark the key warm) so the first
                    # REAL dispatch after warmup counts as execute time
                    self.profiler.record_compile(
                        _time.perf_counter() - tw, "prefill",
                        (rb, mode, maxp))
                    if rb >= cfg.prefill_rows:
                        break
                    rb <<= 1
            for maxp in (buckets if "decode" in families else ()):
                for w in sorted({1, cfg.decode_window}):
                    tw = _time.perf_counter()
                    out, _lps, self.caches = self._decode_window_fn(
                        w, mode, maxp)(
                        self.params, self.caches, np.zeros((bs,), np.int32),
                        np.zeros((bs, maxp), np.int32),
                        np.zeros((bs,), np.int32), key, ctr,
                        np.zeros((bs,), np.float32),
                        np.zeros((bs,), np.int32),
                        *self._lora_args(np.zeros((bs,), np.int32)))
                    np.asarray(out)
                    self.profiler.record_compile(
                        _time.perf_counter() - tw, "decode", (w, mode, maxp))
        if cfg.spec_tokens > 0 and "verify" in families:
            s1 = cfg.spec_tokens + 1
            for maxp in buckets:
                rb = 1
                while True:
                    rb = min(rb, bs)
                    tw = _time.perf_counter()
                    y, _ylp, self.caches = self._verify_fn(rb, s1, maxp)(
                        self.params, self.caches,
                        np.zeros((rb, s1), np.int32),
                        np.zeros((rb, maxp), np.int32),
                        np.zeros((rb,), np.int32),
                        *self._lora_args(np.zeros((rb,), np.int32)))
                    np.asarray(y)
                    # mark warm like prefill/decode: the first REAL spec
                    # dispatch must book as execute, not compile
                    self.profiler.record_compile(
                        _time.perf_counter() - tw, "verify",
                        (rb, s1, maxp, False))
                    if rb >= bs:
                        break
                    rb <<= 1
        return _time.perf_counter() - t0

    def has_work(self) -> bool:
        return bool(self._pending or self._prefilling or self._active)

    # -- page allocation ---------------------------------------------------

    def _pages_needed(self, tokens: int) -> int:
        return (tokens + self.cfg.page_size - 1) // self.cfg.page_size

    def _pages_avail(self) -> int:
        """Pages allocatable right now: truly free + LRU-reclaimable."""
        return len(self._free_pages) + len(self._cached_lru)

    def _pop_free_page(self) -> int:
        """One allocatable page; evicts the least-recently-used
        unreferenced cached page when the free list is dry. Never touches
        a page with live references — only refcount-0 pages sit in the
        LRU. Callers must check _pages_avail() first."""
        if self._free_pages:
            return self._free_pages.pop()
        pid, _ = self._cached_lru.popitem(last=False)
        if self.spill is not None:
            # demote hook: capture the page's KV for the host tier
            # BEFORE _unregister drops the hash mapping and the page id
            # is handed back (the device page gets overwritten by its
            # next owner)
            self._maybe_demote(pid)
        self._unregister(pid)
        self.stats["prefix_evictions"] += 1
        return pid

    def _maybe_demote(self, pid: int):
        h = self._page_to_hash.get(pid)
        if h is None or self._hash_to_page.get(h) != pid:
            return      # unpublished page: nothing content-addressed
        if self.spill.has(h):
            # content already in the tier (promoted or re-computed,
            # then evicted again): a clean eviction — refresh recency,
            # copy nothing
            self.spill.touch(h)
            self.stats["spill_demotions"] += 1
            return
        slot = self._chain_of.get(pid)
        now = time.monotonic()
        if not self.spill.policy.admit(self.chains, slot, now):
            return      # heat-gated: not worth tier residence — free
        ks = [np.asarray(layer["k"][pid]) for layer in self.caches]
        vs = [np.asarray(layer["v"][pid]) for layer in self.caches]
        chain = slot if slot is not None else 0
        expired = self.spill.add(h, chain, ks, vs, now)
        captured = self.spill.has(h)
        if captured:
            self.stats["spill_demotions"] += 1
            self.stats["spill_pages"] += 1
            self.stats["spill_bytes"] += self.spill.page_nbytes
            if self.chains is not None:
                self.chains.spilled_add(chain)
        self._spill_expired(expired, skip_accounted=not captured)

    def _spill_expired(self, removed, skip_accounted: bool = False):
        """Account tier entries expired under the byte budget (or
        refused entry outright, skip_accounted — never counted in)."""
        for _h, chain in removed:
            if skip_accounted:
                skip_accounted = False
                continue    # the refused page itself: was never added
            self.stats["spill_expired"] += 1
            if self.chains is not None:
                self.chains.spilled_sub(chain)

    def _spill_dropped(self, removed):
        """Account validate-on-promote failures: stale/corrupt tier
        content purged — costs this request a cold prefill, nothing
        else (the module failure model, llm/tiering.py)."""
        for _h, chain in removed:
            self.stats["spill_drops"] += 1
            if self.chains is not None:
                self.chains.spilled_sub(chain)

    def _unregister(self, pid: int):
        h = self._page_to_hash.pop(pid, None)
        if h is not None and self._hash_to_page.get(h) == pid:
            del self._hash_to_page[h]
            if self.track_page_publish:
                self._dir_dropped.append(h)
                if len(self._dir_dropped) > 4 * self.cfg.num_pages:
                    # publisher not draining (no directory attached):
                    # drop the log — un-dropped stale entries are hints
                    # the importer validates anyway
                    del self._dir_dropped[:]
        if self.chains is not None:
            # heat attribution: pages whose chain was never learned fold
            # to the overflow sink, so per-chain eviction totals always
            # sum to the aggregate prefix_evictions counter
            slot = self._chain_of.pop(pid, None)
            if slot is None:
                slot = 0
            else:
                self.chains.resident_sub(slot)
            self.chains.evict(slot)
            flight.evt(flight.PREFIX_EVICT, pid, slot)

    def _incref(self, pid: int):
        """Pin a page for a request; a cached (refcount-0) page leaves
        the eviction pool."""
        if self._page_refs[pid] == 0:
            self._cached_lru.pop(pid, None)
        self._page_refs[pid] += 1

    def _decref(self, pid: int):
        """Drop one reference; at zero the page parks in the cached LRU
        (published content, reusable) or returns to the free list."""
        self._page_refs[pid] -= 1
        if self._page_refs[pid] > 0:
            return
        if pid in self._page_to_hash:
            self._cached_lru[pid] = None    # most-recently-released last
        else:
            self._free_pages.append(pid)

    def _claim_pages(self, matched: list[int],
                     n_pages: int) -> Optional[list[int]]:
        """Assemble a page list: pin `matched` (a cached prefix run), then
        allocate fresh pages up to n_pages. Returns None — with NO side
        effects — when the pool cannot cover the remainder. Matches are
        pinned BEFORE any fresh allocation (an allocation could otherwise
        evict a still-unpinned match), and claiming an unreferenced LRU
        page removes an eviction candidate, so those count against
        availability. Shared by admission and PD import so their pool
        accounting can never diverge."""
        need = n_pages - len(matched)
        if need > self._pages_avail() - sum(
                1 for p in matched if self._page_refs[p] == 0):
            return None
        for pid in matched:
            self._incref(pid)
        pages = list(matched)
        for _ in range(need):
            pid = self._pop_free_page()
            self._page_refs[pid] = 1
            pages.append(pid)
        return pages

    def _ensure_pages(self, req: _Request, upto_tokens: int) -> bool:
        """Grow req's page list to cover upto_tokens; False if pool dry."""
        need = self._pages_needed(upto_tokens) - len(req.pages)
        if need <= 0:
            return True
        if self._pages_avail() < need:
            return False
        for _ in range(need):
            pid = self._pop_free_page()
            self._page_refs[pid] = 1
            req.pages.append(pid)
        bt = self._block_tables[req.slot]
        bt[:len(req.pages)] = req.pages
        return True

    def _release(self, req: _Request):
        if self._prefix_on:
            self._register_request_pages(req)
        for pid in req.pages:
            self._decref(pid)
        req.pages = []
        if req.slot >= 0:
            # zero the row so nothing stale survives into the next tenant
            # (writes through leftover entries would hit recycled pages)
            self._block_tables[req.slot, :] = 0
            self._free_slots.append(req.slot)
            self._lengths[req.slot] = 0
            req.slot = -1

    # -- prefix cache (enable_prefix_caching) ------------------------------

    def _hash_chain(self, tokens, prev: bytes = b"") -> list[bytes]:
        """Chained content hashes of `tokens`' FULL pages: each full page
        is keyed by H(parent_digest || page_token_ids), so equal keys
        imply equal whole prefixes (the flat index is an implicit trie).
        blake2b over the raw int32 bytes: stable across processes, so
        PD-disagg payloads can carry the hashes verbatim."""
        page = self.cfg.page_size
        arr = np.asarray(tokens, np.int32)
        out = []
        for i in range(len(arr) // page):
            prev = hashlib.blake2b(
                prev + arr[i * page:(i + 1) * page].tobytes(),
                digest_size=16).digest()
            out.append(prev)
        return out

    def _prompt_hashes(self, req: _Request) -> list[bytes]:
        if req.page_hashes is None:
            # the chain SEED is the request's prefix salt (empty for the
            # base model): adapter requests hash into a disjoint key
            # space per (adapter_id, version), so cached/directory pages
            # can never match across tenants — required for correctness
            # (different adapters write different K/V for equal tokens),
            # and what keeps warmed prefixes tenant-private
            req.page_hashes = self._hash_chain(req.prompt_ids,
                                               prev=req.prefix_salt)
        return req.page_hashes

    def _reuse_limit(self, req: _Request) -> int:
        """Most prompt tokens admissible from cache: chunk-aligned (so
        prefill resumes on a chunk boundary) and strictly short of the
        prompt, so at least one token always prefills — the request's
        first generated token is sampled from real last-position logits."""
        c = self.cfg.chunk_size
        return ((len(req.prompt_ids) - 1) // c) * c

    def _match_prefix(self, req: _Request) -> list[int]:
        """Longest cached page run covering the prompt's head, truncated
        to whole chunks and to _reuse_limit. Pure lookup — no pinning."""
        if not self._prefix_on:
            return []
        limit = self._reuse_limit(req)
        if limit <= 0:
            return []
        page = self.cfg.page_size
        hashes = self._prompt_hashes(req)
        pages: list[int] = []
        for i in range(limit // page):
            pid = self._hash_to_page.get(hashes[i])
            if pid is None:
                break
            pages.append(pid)
        per_chunk = self.cfg.chunk_size // page
        return pages[:(len(pages) // per_chunk) * per_chunk]

    def _try_reuse(self, req: _Request):
        """Mid-prefill reuse: jump req.prefill_pos over chunks whose pages
        another request has published since this one was admitted (an
        identical-prompt burst: the first request prefills, the rest map
        its pages in as they land). Swapped-out private pages go straight
        back to the free list."""
        if not self._prefix_on:
            return
        c, page = self.cfg.chunk_size, self.cfg.page_size
        pos = req.prefill_pos
        if pos % c:
            return
        limit = self._reuse_limit(req)
        hashes = self._prompt_hashes(req)
        while pos < limit:
            idxs = range(pos // page, (pos + c) // page)
            pids = [self._hash_to_page.get(hashes[i]) for i in idxs]
            if any(p is None for p in pids):
                break
            for i, pid in zip(idxs, pids):
                old = req.pages[i]
                if old == pid:
                    continue
                self._incref(pid)
                req.pages[i] = pid
                self._decref(old)
            pos += c
            self.stats["prefix_hits"] += len(pids)
            self.stats["prefix_tokens_saved"] += c
            if self.chains is not None and req.chain_slot >= 0:
                self.chains.hit(req.chain_slot, len(pids), c)
        if pos != req.prefill_pos:
            req.prefill_pos = pos
            self._block_tables[req.slot, :len(req.pages)] = req.pages

    def _register_page(self, pid: int, h: bytes, chain: int = -1):
        if pid in self._page_to_hash or h in self._hash_to_page:
            return      # already published, or duplicate content elsewhere
        self._page_to_hash[pid] = h
        self._hash_to_page[h] = pid
        if self.chains is not None and chain >= 0:
            self._chain_of[pid] = chain
            self.chains.resident_add(chain)
        if self.track_page_publish:
            self._dir_new.append(h)
            if len(self._dir_new) > 4 * self.cfg.num_pages:
                # publisher not draining: the delta log is redundant
                # with the index itself — compress to a full resync so
                # an undrained engine's memory stays bounded
                self._dir_new = list(self._hash_to_page)

    def _register_request_pages(self, req: _Request):
        """Publish req's full, KV-materialized pages into the content
        index (retirement path). KV is materialized for the prompt plus
        every generated token except the last — a sampled token's K/V is
        only written when it is fed back on the next dispatch — so pages
        holding generated text become reusable for multi-turn follow-ups
        whose prompt embeds this request's output."""
        page = self.cfg.page_size
        n_tok = len(req.prompt_ids) + max(len(req.out_ids) - 1, 0)
        if req.prefill_pos < len(req.prompt_ids):
            # released mid-prefill (e.g. a future cancel path): only
            # positions < prefill_pos hold computed KV — publishing
            # further pages would serve garbage to matching prompts
            n_tok = req.prefill_pos
        n_full = min(n_tok // page, len(req.pages))
        if n_full <= 0:
            return
        hashes = self._prompt_hashes(req)
        if n_full > len(hashes):
            tokens = (req.prompt_ids + req.out_ids)[
                len(hashes) * page:n_full * page]
            hashes = hashes + self._hash_chain(
                tokens, prev=hashes[-1] if hashes else req.prefix_salt)
        if self.chains is not None and req.chain_slot < 0 and hashes:
            # short prompts never visited the admission-time chain
            # assignment; learn the chain here so the published pages'
            # evictions attribute to it instead of the overflow sink
            req.chain_slot = self.chains.slot_for(hashes[0],
                                                  req.prefix_salt)
        for i in range(n_full):
            self._register_page(req.pages[i], hashes[i],
                                chain=req.chain_slot)

    # -- engine loop -------------------------------------------------------

    def step(self):
        """One iteration: admit, one prefill chunk (bounded), one decode."""
        self._admit()
        # the mesh scope pins trace-time constrain() resolution for any
        # program a dispatch compiles below (a no-op off-mesh)
        with self._mesh_scope():
            self._prefill_step()
            self._decode_step()
        from . import telemetry
        telemetry.on_step(self)

    def _admit(self):
        with self._lock:
            while self._pending and self._free_slots:
                # admission control: hold requests until the pool can cover
                # the whole prompt (avoids deadlocking a half-prefilled seq)
                req = self._pending[0]
                matched = self._match_prefix(req)
                if self.spill is not None and \
                        self._promote_for_locked(req, len(matched)) > 0:
                    # promoted pages registered + LRU-parked: re-walk
                    # so the match (and the hit accounting below) sees
                    # them exactly like never-evicted pages
                    matched = self._match_prefix(req)
                pages = self._claim_pages(
                    matched, self._pages_needed(len(req.prompt_ids) + 1))
                if pages is None:
                    break
                self._pending.popleft()
                req.slot = self._free_slots.popleft()
                req.pages = pages
                self._block_tables[req.slot, :len(pages)] = pages
                if self.chains is not None:
                    hs = self._prompt_hashes(req)
                    if hs:
                        req.chain_slot = self.chains.slot_for(
                            hs[0], req.prefix_salt)
                        if self.spill is not None and req.chain_slot > 0:
                            # remember the chain's longest head-rooted
                            # hash run — what proactive re-warm promotes
                            prev = self._chain_runs.get(req.chain_slot)
                            if prev is None or len(hs) > len(prev):
                                self._chain_runs[req.chain_slot] = \
                                    list(hs[:self.cfg.max_pages_per_seq])
                if matched:
                    # chunked prefill starts at the first uncached chunk
                    # boundary
                    req.prefill_pos = len(matched) * self.cfg.page_size
                    self.stats["prefix_hits"] += len(matched)
                    self.stats["prefix_tokens_saved"] += req.prefill_pos
                    if self.chains is not None:
                        self.chains.hit(req.chain_slot, len(matched),
                                        req.prefill_pos)
                self._prefilling.append(req)
                from . import telemetry
                telemetry.on_admit(self, req)

    def _prefill_step(self):
        import time
        if not self._prefilling:
            return
        cfg = self.cfg
        c = cfg.chunk_size
        # pack up to prefill_rows chunk-rows, queue order; a request with
        # several remaining chunks occupies consecutive rows (the scan
        # carries caches, so later rows see earlier rows' page writes)
        rows: list[tuple] = []              # (req, start, n_tokens)
        for req in self._prefilling:
            # skip ahead over chunks published since the last step (an
            # identical-prefix burst: request 1 computes, the rest map)
            self._try_reuse(req)
            pos = req.prefill_pos
            while pos < len(req.prompt_ids) and len(rows) < cfg.prefill_rows:
                n = min(c, len(req.prompt_ids) - pos)
                rows.append((req, pos, n))
                pos += n
            if len(rows) >= cfg.prefill_rows:
                break
        # bucket the row count to a power of two (same trick as
        # _spec_step): the jit cache holds O(log prefill_rows) prefill
        # programs instead of one per packed-row count. Pad rows carry
        # true_len 0, so the kernel routes all their writes to sink page
        # 0 (prefill_paged_rows docstring) — they cost compute but no
        # fresh XLA compile, and a mid-burst compile costs tens of
        # requests' worth of latency on a remote-attached accelerator.
        r = len(rows)
        rb = min(1 << max(r - 1, 0).bit_length(), cfg.prefill_rows)
        # block-table width bucket: widest logical page any row reads or
        # writes this dispatch (prefix + chunk = pos + n tokens)
        pg = cfg.page_size
        W = self._page_bucket(max(
            (pos + n + pg - 1) // pg for _, pos, n in rows))
        chunks = np.zeros((rb, c), np.int32)
        bts = np.zeros((rb, W), np.int32)
        sps = np.zeros((rb,), np.int32)
        tls = np.zeros((rb,), np.int32)
        temps = np.zeros((rb,), np.float32)
        topks = np.zeros((rb,), np.int32)
        lslots = np.zeros((rb,), np.int32)
        for i, (req, pos, n) in enumerate(rows):
            chunks[i, :n] = req.prompt_ids[pos:pos + n]
            bts[i] = self._block_tables[req.slot][:W]
            sps[i], tls[i] = pos, n
            temps[i] = req.params.temperature
            topks[i] = req.params.top_k
            lslots[i] = req.adapter_slot
        mode = self._sampling_mode([q for q, _, _ in rows])
        with self.profiler.step("prefill", (rb, mode, W)):
            toks, lps, self.caches = self._prefill_rows_fn(rb, mode, W)(
                self.params, self.caches, chunks, bts, sps, tls,
                self._rng_base, np.int32(self._rng_ctr), temps, topks,
                *self._lora_args(lslots))
            toks = np.asarray(toks)     # block: the step must measure
            lps = None if lps is None else np.asarray(lps)
        self._rng_ctr += 1
        self.stats["prefill_dispatches"] += 1
        self._mesh_account(
            chunks.nbytes + bts.nbytes + sps.nbytes + tls.nbytes
            + temps.nbytes + topks.nbytes + lslots.nbytes,
            toks.nbytes + (0 if lps is None else lps.nbytes))
        if self._prefix_on:
            page = cfg.page_size
            for req, pos, n in rows:
                # full prompt pages this row computed are misses; publish
                # them immediately so the rest of the burst can reuse
                # (their K/V is fully written once this dispatch returns)
                lo, hi = pos // page, (pos + n) // page
                self.stats["prefix_misses"] += hi - lo
                if self.chains is not None and hi > lo \
                        and req.chain_slot >= 0:
                    self.chains.miss(req.chain_slot, hi - lo)
                hashes = self._prompt_hashes(req)
                for j in range(lo, hi):
                    self._register_page(req.pages[j], hashes[j],
                                        chain=req.chain_slot)
        for i, (req, pos, n) in enumerate(rows):
            req.prefill_pos = pos + n
            if req.prefill_pos < len(req.prompt_ids):
                continue
            # prompt done: the row's in-jit sampled token is the first
            # generated token
            tok = int(toks[i])
            req.out_ids.append(tok)
            if lps is not None:
                req.out_logps.append(float(lps[i]))
            self.stats["tokens_out"] += 1
            req.first_token_t = time.perf_counter()
            from . import telemetry
            telemetry.on_first_token(self, req)
            self._lengths[req.slot] = len(req.prompt_ids)
            self._prefilling.remove(req)
            if getattr(req, "prefill_only", False):
                # disaggregated prefill: export the KV pages + first token
                # instead of decoding here (llm/pd_disagg.py). Under the
                # pool lock: _release mutates _free_slots/_page_refs,
                # which a concurrent submit/import_prefill (replica
                # threads) also touches — and the export must not observe
                # a cache swap mid-gather. _finish_request stays OUTSIDE
                # it: the span emit can write a pipe, and blocking I/O
                # under the admission lock stalls every replica thread
                # (the GL002 bug class).
                with self._lock:
                    req.export_payload = self._export_kv_locked(req, tok)
                    self._release(req)
                self._finish_request(req, "export")
                continue
            self._active[req.slot] = req
            self._maybe_finish(req, tok)
        # NOTE: pad positions of the final chunk were written into the
        # sequence's own pages beyond its true length; decode masks
        # positions >= length so they are never attended.

    @staticmethod
    def _propose_draft(ctx: np.ndarray, n: int, s: int) -> list[int]:
        """Prompt-lookup draft: find the most recent earlier occurrence of
        the context's final n-gram and propose the s tokens that followed
        it (reference role: vLLM's prompt-lookup speculative proposer)."""
        m = len(ctx) - n                   # candidate match positions 0..m-1
        if m <= 0 or s <= 0:
            return []
        tail = ctx[-n:]
        hits = np.flatnonzero(np.all(
            np.stack([ctx[i:m + i] for i in range(n)]) == tail[:, None],
            axis=0))
        if len(hits) == 0:
            return []
        # most recent occurrence that still has a FULL s-token
        # continuation (on constant/periodic runs the newest hit sits at
        # the end of the run with almost nothing after it); fall back to
        # the earliest hit, whose continuation is the longest available
        viable = hits[hits + n + s <= len(ctx)]
        start = int(viable[-1] if len(viable) else hits[0]) + n
        return [int(t) for t in ctx[start:start + s]]

    def _spec_step(self) -> bool:
        """One speculative verify dispatch over every active slot. Only
        runs when every slot is greedy (the accept rule reproduces exact
        greedy; sampled rows fall back to the windowed path) and at least
        one slot has a draft. Returns False to fall through."""
        cfg = self.cfg
        s, page = cfg.spec_tokens, cfg.page_size
        slots = sorted(self._active)
        drafts = {}
        for slot in slots:
            req = self._active[slot]
            ctx = np.asarray(req.prompt_ids + req.out_ids, np.int32)
            drafts[slot] = self._propose_draft(ctx, cfg.spec_ngram, s)
        # every slot must carry a draft: in a spec dispatch a draft-less
        # slot emits exactly ONE token, strictly worse than its share of
        # a decode window. A no-draft round costs the same backed-off
        # cooldown as a failed probe, so non-repetitive text doesn't pay
        # the O(context) n-gram scan on every step.
        if not all(drafts.values()):
            self._spec_cooldown = self._spec_cooldown_len
            self._spec_cooldown_len = min(self._spec_cooldown_len * 2, 256)
            return False
        # bucket the row count to a power of two so the jit cache holds
        # O(log max_batch) verify programs, not one per active-set size;
        # pad rows write only to sink page 0 and are discarded
        r, s1 = len(slots), s + 1
        rb = min(1 << max(r - 1, 0).bit_length(), cfg.max_batch_size)
        # table-width bucket: every row writes positions start..start+s1-1,
        # so the width must cover their pages (beyond-allocation writes
        # then hit the row's zero entries = sink page, never a clamp)
        W = self._page_bucket(max(
            (self._lengths[sl] + s1 - 1) // page + 1 for sl in slots))
        toks = np.zeros((rb, s1), np.int32)
        bts = np.zeros((rb, W), np.int32)
        starts = np.zeros((rb,), np.int32)
        lslots = np.zeros((rb,), np.int32)
        allow: dict[int, int] = {}
        for i, slot in enumerate(slots):
            req = self._active[slot]
            allow[slot] = self._reserve(req, s1)
            toks[i, 0] = req.out_ids[-1]
            toks[i, 1:1 + len(drafts[slot])] = drafts[slot]
            bts[i] = self._block_tables[slot][:W]
            starts[i] = self._lengths[slot]
            lslots[i] = req.adapter_slot
        want_lp = any(self._active[sl].params.logprobs for sl in slots)
        with self.profiler.step("verify", (rb, s1, W, want_lp)):
            y, ylp, self.caches = self._verify_fn(rb, s1, W, want_lp)(
                self.params, self.caches, toks, bts, starts,
                *self._lora_args(lslots))
            y = np.asarray(y)               # [r, s1]; block: measure
            ylp = None if ylp is None else np.asarray(ylp)
        self.stats["spec_dispatches"] += 1
        self._mesh_account(
            toks.nbytes + bts.nbytes + starts.nbytes + lslots.nbytes,
            y.nbytes + (0 if ylp is None else ylp.nbytes))
        emitted = 0
        for i, slot in enumerate(slots):
            req = self._active[slot]
            d = drafts[slot]
            self.stats["spec_proposed"] += len(d)
            # accept: token j's prediction y[i, j] is the true next token
            # only while every earlier draft matched the model's choice
            def _lp(row, col):
                return None if ylp is None else float(ylp[row, col])
            out = [(int(y[i, 0]), _lp(i, 0))]
            for j in range(len(d)):
                if d[j] != out[-1][0]:
                    break
                out.append((int(y[i, j + 1]), _lp(i, j + 1)))
                self.stats["spec_accepted"] += 1
            consumed = 0
            for tok, lp in out:
                if consumed >= allow[slot]:
                    from . import telemetry
                    telemetry.on_preempted(self)
                    self._retire(req)
                    break
                req.out_ids.append(tok)
                if lp is not None:
                    req.out_logps.append(lp)
                self._lengths[slot] += 1
                consumed += 1
                self.stats["tokens_out"] += 1
                if self._stop_after(req, tok):
                    self._retire(req)
                    break
            emitted += consumed
        # controller: keep speculating only while it beats the window;
        # on fallback, re-probe optimistically after a cooldown that
        # doubles per consecutive failed probe (text that never accepts
        # pays a vanishing probe tax, text that turns repetitive is
        # rediscovered within ~cooldown windows)
        self._spec_gain = 0.5 * self._spec_gain + 0.5 * (emitted / r)
        if self._spec_gain <= self.cfg.decode_window and \
                self.cfg.decode_window > 1:
            self._spec_cooldown = self._spec_cooldown_len
            self._spec_cooldown_len = min(self._spec_cooldown_len * 2, 256)
            self._spec_gain = float(s + 1)
        else:
            self._spec_cooldown_len = 8
        return True

    def _decode_step(self):
        if not self._active:
            return
        cfg = self.cfg
        bs, page = cfg.max_batch_size, cfg.page_size
        quiet = not (self._prefilling or self._pending)
        if cfg.spec_tokens > 0 and quiet and \
                self._sampling_mode(
                    self._active.values())[:2] == (False, False):
            if self._spec_cooldown > 0:
                self._spec_cooldown -= 1
            elif self._spec_step():
                return
        # full window only when no prompt is waiting: a pending prefill
        # gets interleaved every step, keeping TTFT low under bursts
        w = 1 if not quiet else cfg.decode_window
        # table-width bucket: the window writes positions len..len+w-1
        # per slot, so the width covers every such page (beyond-allocation
        # writes then hit zero entries = sink page, never a clamp)
        W = self._page_bucket(max(
            (self._lengths[sl] + w - 1) // page + 1 for sl in self._active))
        tokens = np.zeros((bs,), np.int32)
        lengths = np.zeros((bs,), np.int32)
        temps = np.zeros((bs,), np.float32)
        topks = np.zeros((bs,), np.int32)
        lslots = np.zeros((bs,), np.int32)
        # slots not decoding this step get a zeroed block-table row: their
        # dummy writes go to sink page 0 instead of a live (possibly
        # reused) page
        bt = np.zeros((bs, W), np.int32)
        allow: dict[int, int] = {}          # valid tokens per slot this window
        for slot, req in self._active.items():
            allow[slot] = self._reserve(req, w)
            tokens[slot] = req.out_ids[-1]
            lengths[slot] = self._lengths[slot]
            temps[slot] = req.params.temperature
            topks[slot] = req.params.top_k
            bt[slot] = self._block_tables[slot][:W]
            lslots[slot] = req.adapter_slot
        mode = self._sampling_mode(self._active.values())
        with self.profiler.step("decode", (w, mode, W)):
            out, lps, self.caches = self._decode_window_fn(w, mode, W)(
                self.params, self.caches, tokens, bt, lengths,
                self._rng_base, np.int32(self._rng_ctr), temps, topks,
                *self._lora_args(lslots))
            out = np.asarray(out)           # [bs, w]; block to measure
            lps = None if lps is None else np.asarray(lps)
        self._rng_ctr += 1
        self.stats["decode_dispatches"] += 1
        self._mesh_account(
            tokens.nbytes + bt.nbytes + lengths.nbytes + temps.nbytes
            + topks.nbytes + lslots.nbytes,
            out.nbytes + (0 if lps is None else lps.nbytes))
        for slot in list(self._active):
            req = self._active[slot]
            for j in range(w):
                if j >= allow[slot]:
                    # page pool exhausted mid-window: finish early rather
                    # than wedge (tokens past the allocation wrote to the
                    # sink page and are not trustworthy)
                    from . import telemetry
                    telemetry.on_preempted(self)
                    self._retire(req)
                    break
                tok = int(out[slot, j])
                req.out_ids.append(tok)
                if lps is not None:
                    req.out_logps.append(float(lps[slot, j]))
                self._lengths[slot] += 1
                self.stats["tokens_out"] += 1
                if self._stop_after(req, tok):
                    self._retire(req)
                    break

    def _reserve(self, req: _Request, width: int) -> int:
        """Pre-allocate pages for up to `width` new tokens and return how
        many of the dispatch's tokens are VALID for this request.

        Pages are grabbed only for tokens the request can still emit
        (width, max_tokens remainder, sequence ceiling — whichever is
        least; over-grabbing would starve later slots under pool
        pressure). Device writes past the allocation land on sink page 0
        and those tokens are discarded; if the pool runs dry the request
        keeps only the tokens its allocated pages cover and finishes
        early. Shared by the windowed-decode and speculative paths so
        their page budgeting can never diverge."""
        total = len(req.prompt_ids) + len(req.out_ids)
        remaining = max(req.params.max_tokens - len(req.out_ids), 1)
        target = min(total + min(width, remaining), self.cfg.max_seq_len)
        if self._ensure_pages(req, target):
            return target - total
        return max(len(req.pages) * self.cfg.page_size - total, 0)

    def _stop_after(self, req: _Request, tok: int) -> bool:
        """Stop condition evaluated after appending tok to req.out_ids."""
        total = len(req.prompt_ids) + len(req.out_ids)
        return (len(req.out_ids) >= req.params.max_tokens
                or tok == self._eos_id() or tok in req.params.stop_token_ids
                or total >= self.cfg.max_seq_len - 1)

    def _retire(self, req: _Request):
        self._finish_request(req)
        self._active.pop(req.slot, None)
        if req in self._prefilling:
            self._prefilling.remove(req)
        self._release(req)

    def _maybe_finish(self, req: _Request, tok: int):
        stop = self._stop_after(req, tok)
        if not stop:
            # growing by one token may need one more page
            total = len(req.prompt_ids) + len(req.out_ids)
            if not self._ensure_pages(req, total + 1):
                stop = True  # pool exhausted: finish early rather than wedge
                from . import telemetry
                telemetry.on_preempted(self)
        if stop:
            self._retire(req)

    # -- prefill/decode disaggregation (llm/pd_disagg.py; reference:
    # prefill_decode_disagg.py:64) ----------------------------------------

    def _export_kv_locked(self, req: _Request, first_token: int) -> dict:
        """Gather this request's KV pages to host arrays for transfer to a
        decode replica (the role the KV-connector plays for the reference's
        PD deployments)."""
        idx = jnp.asarray(np.asarray(req.pages, np.int32))
        pages = [{"k": np.asarray(layer["k"][idx]),
                  "v": np.asarray(layer["v"][idx])}
                 for layer in self.caches]
        return {"prompt_ids": list(req.prompt_ids),
                "first_token": int(first_token),
                "page_size": self.cfg.page_size,
                # chained content hashes of the FULL prompt pages, in page
                # order: the decode side dedupes payload pages it already
                # holds instead of re-allocating and re-scattering them
                "page_hashes": list(self._prompt_hashes(req)),
                # the chain's seed, so the decode side's request hashes
                # land in the same (tenant-scoped) key space
                "prefix_salt": req.prefix_salt,
                "pages": pages}

    def prefill_export(self, prompt, params: SamplingParams) -> dict:
        """Chunked-prefill `prompt` and return its exported KV payload
        (drives the engine loop until the export is ready)."""
        req = self.submit(prompt, params)
        req.prefill_only = True
        req.export_payload = None
        while req.export_payload is None and not req.done:
            self.step()
        if req.export_payload is None:
            raise RuntimeError("prefill finished without an export "
                               "(prompt rejected?)")
        return req.export_payload

    def import_prefill(self, payload: dict, params: SamplingParams,
                       ) -> _Request:
        """Seed a decode-ready sequence from an exported KV payload:
        allocate slot+pages, scatter the page data into this engine's
        pools, and place the request directly in the decode set."""
        import time
        if payload["page_size"] != self.cfg.page_size:
            raise ValueError(
                f"page_size mismatch: payload {payload['page_size']} vs "
                f"engine {self.cfg.page_size}")
        ids = list(payload["prompt_ids"])
        with self._lock:
            req = _Request(self._next_rid, ids, params)
            req.prefix_salt = payload.get("prefix_salt", b"")
            req.submit_t = time.perf_counter()
            req.admit_t = req.submit_t
            from . import telemetry
            telemetry.on_submit(self, req)
            self._next_rid += 1
            if not self._free_slots:
                raise RuntimeError("no free decode slot")
            req.slot = self._free_slots.popleft()
            n_pages = self._pages_needed(len(ids) + 1)
            n_in = len(payload["pages"][0]["k"])
            if n_in != n_pages:
                self._release(req)
                raise ValueError(
                    f"payload covers {n_in} pages but this engine "
                    f"needs {n_pages} for the same prompt")
            # dedupe: payload pages whose content hash this engine already
            # holds are mapped (and pinned) instead of re-scattered — a
            # decode replica serving many same-system-prompt imports keeps
            # one copy of the shared prefix. The chain property means the
            # reusable run is a prefix of the page list. Full pages only;
            # the partial tail page is always private (decode writes into
            # it at position len(ids)).
            hashes = payload.get("page_hashes")
            if hashes is None and self._prefix_on:
                hashes = self._hash_chain(ids, prev=req.prefix_salt)
            matched: list[int] = []
            if self._prefix_on and hashes:
                for h in hashes:      # chain property: a prefix run
                    pid = self._hash_to_page.get(h)
                    if pid is None:
                        break
                    matched.append(pid)
            pages = self._claim_pages(matched, n_pages)
            if pages is None:
                self._release(req)
                raise RuntimeError("page pool exhausted importing prefill")
            fresh = list(range(len(matched), n_pages))
            req.pages = pages
            self._block_tables[req.slot, :n_pages] = pages
            if self._prefix_on:
                # hits/misses track page-level cache efficacy; deduped
                # imports save scatter/transfer, NOT prefill compute (the
                # prefill replica already counted any skipped prefill), so
                # tokens_saved deliberately stays untouched here — fleet
                # sums would otherwise double-count
                self.stats["prefix_hits"] += len(matched)
                nf = len(ids) // self.cfg.page_size  # full prompt pages
                self.stats["prefix_misses"] += nf - len(matched)
                if self.chains is not None and hashes:
                    req.chain_slot = self.chains.slot_for(
                        hashes[0], req.prefix_salt)
                    if matched:
                        self.chains.hit(req.chain_slot, len(matched))
                    if nf > len(matched):
                        self.chains.miss(req.chain_slot,
                                         nf - len(matched))
            if fresh:
                idx = jnp.asarray(np.asarray(
                    [pages[i] for i in fresh], np.int32))
                sel = np.asarray(fresh)
                for li, layer in enumerate(self.caches):
                    layer["k"] = self._import_fn(
                        layer["k"], idx,
                        jnp.asarray(payload["pages"][li]["k"][sel]))
                    layer["v"] = self._import_fn(
                        layer["v"], idx,
                        jnp.asarray(payload["pages"][li]["v"][sel]))
                if self._prefix_on and hashes:
                    for i in fresh:
                        if i < len(hashes):
                            self._register_page(pages[i], hashes[i],
                                                chain=req.chain_slot)
            tok = int(payload["first_token"])
            req.out_ids.append(tok)
            self.stats["tokens_out"] += 1
            req.prefill_pos = len(ids)
            req.first_token_t = time.perf_counter()
            self._lengths[req.slot] = len(ids)
            self._active[req.slot] = req
            self._maybe_finish(req, tok)
        return req

    @property
    def _import_fn(self):
        fn = getattr(self, "_import_fn_cached", None)
        if fn is None:
            # donated in-place page scatter: cache pools are not copied
            if self.mesh is None:
                fn = jax.jit(lambda c, idx, data: c.at[idx].set(data),
                             donate_argnums=(0,))
            else:
                # pinned shardings keep the donated pool usable in place
                # (out == in) and land the host payload replicated-then-
                # scattered without resharding the pool itself
                kv = self._shardings["caches"][0]["k"]
                repl = self._shardings["repl"]
                fn = jax.jit(lambda c, idx, data: c.at[idx].set(data),
                             donate_argnums=(0,),
                             in_shardings=(kv, repl, repl),
                             out_shardings=kv)
            self._import_fn_cached = fn
        return fn

    # -- cluster prefix-cache directory hooks (serve/frontdoor/prefix.py;
    # cross-replica page import extends the import_prefill contract:
    # same chained content hashes, same _import_fn scatter, but the
    # imported pages seed the CACHE — refcount 0, LRU-parked — instead
    # of a decode-ready request) ------------------------------------------

    def hash_prompt(self, prompt, salt: bytes = b"") -> list[bytes]:
        """Chained hashes of the prompt's admission-reusable pages: the
        whole full pages inside the chunk-aligned _reuse_limit, exactly
        the run _match_prefix can admit from cache. ``salt`` must match
        the prefix_salt the request will submit with (tenant-scoped
        chains — _prompt_hashes). Pure computation — no lock, no
        state."""
        ids = (self.tokenizer.encode(prompt) if isinstance(prompt, str)
               else list(prompt))
        c = self.cfg.chunk_size
        limit = ((len(ids) - 1) // c) * c
        if limit <= 0:
            return []
        return self._hash_chain(ids[:limit], prev=salt)

    def cached_prefix_len(self, hashes) -> int:
        """How many of `hashes` (a chain run) this engine's cache already
        covers, walking from the head until the first miss."""
        with self._lock:
            n = 0
            for h in hashes:
                if h not in self._hash_to_page:
                    break
                n += 1
            return n

    def export_prefix(self, hashes) -> Optional[dict]:
        """Gather the cached pages for a chain run of hashes to host
        arrays — the cross-replica analog of _export_kv_locked, keyed by
        content instead of by request. Returns the longest covered
        prefix run (None when even the first page is gone: entries in
        the cluster directory are hints and this engine may have evicted
        since publishing). CALLER must serialize against the stepping
        thread (serving.py's step lock): dispatches donate self.caches,
        so a concurrent step would invalidate the buffers mid-gather."""
        with self._lock:
            pids: list[int] = []
            for h in hashes:
                pid = self._hash_to_page.get(h)
                if pid is None:
                    break
                pids.append(pid)
            if not pids:
                return None
            idx = jnp.asarray(np.asarray(pids, np.int32))
            pages = [{"k": np.asarray(layer["k"][idx]),
                      "v": np.asarray(layer["v"][idx])}
                     for layer in self.caches]
            self.stats["prefix_exported_pages"] += len(pids)
            if self.chains is not None:
                # peek, never assign: an export targets pages this
                # engine already registered, so the chain (or the
                # overflow sink) exists
                self.chains.exported(self.chains.peek(hashes[0]),
                                     len(pids))
            return {"page_size": self.cfg.page_size,
                    "page_hashes": list(hashes[:len(pids)]),
                    "pages": pages}

    def import_prefix(self, payload: Optional[dict],
                      reserve_pages: Optional[int] = None) -> int:
        """Seed this engine's prefix cache with another replica's
        exported pages: allocate, scatter (donated, in place), register
        under the payload's chain hashes, and park refcount-0 in the
        cached LRU — the next _match_prefix/_try_reuse admits them like
        locally computed pages. Imports stop once the pool would drop
        below `reserve_pages` allocatable pages (default one page per
        slot) so a warm import can never starve active requests.
        Returns pages imported. CALLER must serialize against the
        stepping thread (same contract as export_prefix/import_prefill:
        _import_fn donates the cache pools)."""
        if payload is None or not self._prefix_on:
            return 0
        if payload["page_size"] != self.cfg.page_size:
            raise ValueError(
                f"page_size mismatch: payload {payload['page_size']} vs "
                f"engine {self.cfg.page_size}")
        with self._lock:
            if reserve_pages is None:
                reserve_pages = self.cfg.max_batch_size
            return self._import_payload_locked(payload,
                                               int(reserve_pages))

    def _import_payload_locked(self, payload: dict, reserve_pages: int,
                               chain: Optional[int] = None) -> int:
        """The shared allocate/scatter/register/LRU-park core behind
        import_prefix (cross-replica) and the spill-tier promote paths
        (same payload format — a promoted page is bit-identical to a
        never-evicted one by construction). ``chain`` pins the heat
        attribution (promotes know their chain from the tier entry);
        None means cross-replica import accounting: slot from the
        payload's head hash, imported_pages counters, flight event.
        Caller holds self._lock and serializes against stepping."""
        hashes = payload["page_hashes"]
        take_idx: list[int] = []
        take_pids: list[int] = []
        budget = self._pages_avail() - reserve_pages
        for i, h in enumerate(hashes):
            if h in self._hash_to_page:
                continue    # already cached locally (either source)
            if budget <= 0:
                break
            pid = self._pop_free_page()
            self._page_refs[pid] = 0
            take_idx.append(i)
            take_pids.append(pid)
            budget -= 1
        if not take_pids:
            return 0
        idx = jnp.asarray(np.asarray(take_pids, np.int32))
        sel = np.asarray(take_idx)
        for li, layer in enumerate(self.caches):
            layer["k"] = self._import_fn(
                layer["k"], idx,
                jnp.asarray(payload["pages"][li]["k"][sel]))
            layer["v"] = self._import_fn(
                layer["v"], idx,
                jnp.asarray(payload["pages"][li]["v"][sel]))
        slot = -1
        if chain is not None:
            slot = chain
        elif self.chains is not None:
            # the exporter's chain-head hash carries the tenant salt
            # inside the digest; the salt arg only labels a freshly
            # minted slot, and cross-replica imports are keyed by
            # content alone
            slot = self.chains.slot_for(hashes[0])
        if chain is None and self.chains is not None:
            self.chains.imported(slot, len(take_pids))
            flight.evt(flight.PREFIX_IMPORT, len(take_pids), slot)
        for i, pid in zip(take_idx, take_pids):
            self._register_page(pid, hashes[i], chain=slot)
            self._cached_lru[pid] = None
        if chain is None:
            self.stats["prefix_imported_pages"] += len(take_pids)
        return len(take_pids)

    # -- spill tier (cfg.kv_spill, llm/tiering.py) -------------------------

    def _promote_for_locked(self, req: _Request, have: int) -> int:
        """Admission-time promote: when the hot cache's longest-prefix
        match ends but the spill tier holds the next consecutive pages
        of the request's chain, scatter them back into HBM BEFORE cold
        prefill. Runs under self._lock on the stepping thread (called
        from _admit). Returns pages promoted; the caller re-matches."""
        page = self.cfg.page_size
        limit = self._reuse_limit(req) // page
        if have >= limit:
            return 0
        need = self._pages_needed(len(req.prompt_ids) + 1)
        if self._pages_avail() < need:
            return 0    # admission would stall regardless: no churn
        hashes = self._prompt_hashes(req)
        run = self.spill.covered_run(hashes[have:limit])
        if run <= 0:
            return 0
        want = hashes[have:have + run]
        chain = self.spill.chain_of(want[0])
        payload, dropped = self.spill.payload_for(want, page)
        if dropped:
            self._spill_dropped(dropped)
        if payload is None:
            return 0
        n = self._import_payload_locked(payload, 0, chain=chain)
        if n > 0:
            self.stats["spill_promotions"] += n
            if self.chains is not None:
                self.chains.promoted(chain, n)
        return n

    def maybe_rewarm(self, max_pages: Optional[int] = None) -> int:
        """Proactive re-warm: promote the hottest spilled chain's known
        head run back into HBM while the pool has idle headroom — the
        policy's rewarm gate (SpillPolicy.rewarm_slot). Called by the
        serving layer's engine loop between steps (same serialization
        as import_prefix: the scatter donates the cache pools); safe to
        call any time, a no-op without headroom. Returns pages
        promoted."""
        if self.spill is None or self.chains is None:
            return 0
        with self._lock:
            pool = self.cfg.num_pages - 1
            free_frac = len(self._free_pages) / max(pool, 1)
            slot = self.spill.policy.rewarm_slot(
                self.chains, self.spill.spilled_slots(), free_frac)
            if slot is None:
                return 0
            run = self._chain_runs.get(slot)
            if not run:
                return 0
            # the head-rooted usable run: pages already hot pass
            # through (the scatter skips them), tier-resident pages
            # promote, the first page in neither tier ends the run
            want: list[bytes] = []
            for h in run:
                if h in self._hash_to_page:
                    want.append(h)
                elif self.spill.has(h):
                    want.append(h)
                else:
                    break
            want = [h for h in want if h not in self._hash_to_page]
            if max_pages is not None:
                want = want[:max(int(max_pages), 0)]
            if not want:
                return 0
            payload, dropped = self.spill.payload_for(
                want, self.cfg.page_size)
            if dropped:
                self._spill_dropped(dropped)
            if payload is None:
                return 0
            n = self._import_payload_locked(
                payload, self.cfg.max_batch_size, chain=slot)
            if n > 0:
                self.stats["spill_promotions"] += n
                self.chains.promoted(slot, n)
            return n

    def note_spill_promotion(self, head: bytes, pages: int) -> None:
        """Cross-replica promote accounting (serve/frontdoor/prefix.py):
        pages seeded via a ``spill:`` directory entry's store payload
        count as spill promotions HERE (the tier recovered them for
        this engine) on top of the imported_pages the scatter already
        counted."""
        with self._lock:
            self.stats["spill_promotions"] += int(pages)
            if self.chains is not None:
                self.chains.promoted(self.chains.peek(head), int(pages))

    def note_spill_drops(self, n: int) -> None:
        """Cross-replica validate-on-promote failure accounting: a
        stale/corrupt ``spill:`` entry cost a cold prefill."""
        with self._lock:
            self.stats["spill_drops"] += int(n)

    def spill_teardown(self) -> int:
        """Drop every tier entry — and with them every store segment
        ref — so the host object store drains to exact baseline on
        engine teardown (replica death gets the same result from the
        owner sweep). Returns entries dropped."""
        if self.spill is None:
            return 0
        with self._lock:
            removed = self.spill.clear()
            self._spill_expired(removed)
            return len(removed)

    def drain_directory_delta(self) -> tuple:
        """-> (new_hashes, dropped_hashes) accumulated since the last
        drain, filtered against current cache state so a
        publish-then-evict (or evict-then-republish) nets out to the
        truth. Only meaningful with track_page_publish on; must be
        called serialized with stepping (the serving layer's engine
        loop), which is also what bounds the lists."""
        if not self._dir_new and not self._dir_dropped:
            return (), ()
        new, self._dir_new = self._dir_new, []
        dropped, self._dir_dropped = self._dir_dropped, []
        with self._lock:
            new = [h for h in dict.fromkeys(new)
                   if h in self._hash_to_page]
            dropped = [h for h in dict.fromkeys(dropped)
                       if h not in self._hash_to_page]
        return new, dropped

    # -- stats -------------------------------------------------------------

    def estimate_flops(self) -> dict:
        """FLOPs per dispatch for the program families via XLA
        cost_analysis (one extra out-of-band compile per estimated
        program — run once, after traffic or warmup, not per step).

        Length-aware: estimates are taken PER static program key —
        (rows/window, sampling mode, block-table page bucket) — for
        every key the profiler has executed steps under, so a dispatch
        that ran at a short page bucket is credited its true
        bucket-proportional attention FLOPs instead of a
        max_pages-sized estimate (which would leave short-sequence
        steps uncredited and profile_summary() MFU understating).
        Before any traffic, falls back to the full-width greedy decode
        and prefill programs. Returns {family: {key: flops}}."""
        from ..util.profiling import compiled_flops
        cfg = self.cfg
        bs, maxp = cfg.max_batch_size, cfg.max_pages_per_seq
        mode = (False, False, False)
        tags = [t for t in self.profiler.executed_tags()
                if t[0] in ("prefill", "decode", "verify")]
        if not tags:
            tags = [("decode", (cfg.decode_window, mode, maxp)),
                    ("prefill", (cfg.prefill_rows, mode, maxp))]
        out: dict[str, dict] = {}
        for kind, k in tags:
            fl = compiled_flops(*self._dispatch_for_key(kind, k))
            if fl:
                out.setdefault(kind, {})[k] = fl
                # credited only to steps at this EXACT static key:
                # dispatches at other shapes/modes stay uncredited
                # (MFU must understate, never inflate)
                self.profiler.attach_flops(kind, fl, key=k)
        return out

    def _dispatch_for_key(self, kind: str, key: tuple):
        """(fn, *dummy_args) reproducing the static shapes of the
        program behind a profiler step tag — used by estimate_flops to
        cost exactly the programs that dispatched."""
        cfg = self.cfg
        bs, c = cfg.max_batch_size, cfg.chunk_size
        rkey, ctr = self._rng_base, np.int32(0)
        if kind == "decode":
            w, mode, W = key
            return (self._decode_window_fn(w, mode, W),
                    self.params, self.caches, np.zeros((bs,), np.int32),
                    np.zeros((bs, W), np.int32), np.zeros((bs,), np.int32),
                    rkey, ctr, np.zeros((bs,), np.float32),
                    np.zeros((bs,), np.int32),
                    *self._lora_args(np.zeros((bs,), np.int32)))
        if kind == "prefill":
            rb, mode, W = key
            return (self._prefill_rows_fn(rb, mode, W),
                    self.params, self.caches, np.zeros((rb, c), np.int32),
                    np.zeros((rb, W), np.int32), np.zeros((rb,), np.int32),
                    np.zeros((rb,), np.int32), rkey, ctr,
                    np.zeros((rb,), np.float32), np.zeros((rb,), np.int32),
                    *self._lora_args(np.zeros((rb,), np.int32)))
        rb, s1, W, want_lp = key                      # verify
        return (self._verify_fn(rb, s1, W, want_lp),
                self.params, self.caches, np.zeros((rb, s1), np.int32),
                np.zeros((rb, W), np.int32), np.zeros((rb,), np.int32),
                *self._lora_args(np.zeros((rb,), np.int32)))

    def profile_summary(self) -> dict:
        """Step-profiler view (util/profiling.py): compile/execute wall
        split, per-step wall, and MFU when estimate_flops() has run."""
        return {**self.profiler.summary(), "dispatches": {
            "prefill": self.stats["prefill_dispatches"],
            "decode": self.stats["decode_dispatches"],
            "spec": self.stats["spec_dispatches"]}}

    def prefix_accounting(self) -> dict:
        """THE accounting source for prefix-cache counters. pool_stats(),
        the telemetry gauges (llm/telemetry.py) and the fleet rollup
        (serve.metrics_summary()["prefix_cache"]) all derive from this
        one snapshot, so the surfaces can never drift from each other —
        tests/test_cache_heat.py asserts the parity."""
        hits = self.stats["prefix_hits"]
        misses = self.stats["prefix_misses"]
        return {
            "hits": hits,
            "misses": misses,
            "evictions": self.stats["prefix_evictions"],
            "tokens_saved": self.stats["prefix_tokens_saved"],
            "imported_pages": self.stats["prefix_imported_pages"],
            "exported_pages": self.stats["prefix_exported_pages"],
            "cached_pages": len(self._cached_lru),
            "hit_rate": round(hits / (hits + misses), 4)
            if hits + misses else 0.0,
            # spill tier (cfg.kv_spill): cumulative counters + current
            # tier residence — all zero while the tier is off, so the
            # accounting schema is uniform across configurations
            "spill_pages": self.stats["spill_pages"],
            "spill_bytes": self.stats["spill_bytes"],
            "spill_demotions": self.stats["spill_demotions"],
            "spill_promotions": self.stats["spill_promotions"],
            "spill_expired": self.stats["spill_expired"],
            "spill_drops": self.stats["spill_drops"],
            "spill_resident_pages": self.spill.resident_pages()
            if self.spill is not None else 0,
            "spill_resident_bytes": self.spill.resident_bytes
            if self.spill is not None else 0,
        }

    def pool_stats(self) -> dict:
        acct = self.prefix_accounting()
        return {
            # free + cached together are the allocatable pool: cached
            # pages hold reusable prefix KV but evict on demand, so a
            # "full" pool with a deep cache is warm, not saturated
            "free_pages": len(self._free_pages),
            "cached_pages": acct["cached_pages"],
            "total_pages": self.cfg.num_pages,
            "prefix_hit_rate": acct["hit_rate"],
            "active": len(self._active),
            "prefilling": len(self._prefilling),
            "pending": len(self._pending),
            **self.stats,
        }

    def chain_stats_report(self, top_k: Optional[int] = None) -> dict:
        """Heat-plane snapshot: bounded-table stats, whole-table totals
        (== the matching prefix_accounting() aggregates), and the top-K
        hot chains. Empty dict when the table is disabled."""
        if self.chains is None:
            return {}
        if top_k is None:
            top_k = self.cfg.chain_stats_top_k
        return self.chains.report(top_k)
