"""Prefill/decode disaggregation for the paged serving engine.

Reference parity: llm/_internal/serve/deployments/prefill_decode_disagg/
prefill_decode_disagg.py:64 (PDProxyServer — routes each request to a
prefill instance, then streams tokens from a decode instance once the KV
transferred) and :160 (build_app wiring the two replica groups behind one
proxy).

TPU-first shape: prefill replicas run ONLY chunked prefill (compute-bound,
MXU-heavy, long sequences), decode replicas run ONLY batched paged decode
(memory-bandwidth-bound, latency-sensitive). The prefilled KV pages move
between replicas as plain objects on the data plane (shared store on one
host, the object-transfer service across hosts) — the role NIXL/KV-connector
plays for the reference. Disaggregation exists to protect decode TTFT/ITL
from long-prompt prefill stalls; colocating both phases in one engine forces
them to share one compiled-step budget.

Usage:
    proxy = build_pd_proxy(n_prefill=1, n_decode=1, engine_cfg=cfg)
    text = ray_tpu.get(proxy.generate.remote("hello", SamplingParams()))
"""
from __future__ import annotations

import dataclasses
from typing import Any, Optional

from .engine import SamplingParams


class PrefillReplica:
    """Owns a paged engine used exclusively for prefill; returns the KV
    payload (pages + first sampled token) instead of decoding."""

    def __init__(self, engine_cfg, params=None, rng_seed: int = 0):
        from .paged_engine import PagedInferenceEngine
        self.engine = PagedInferenceEngine(engine_cfg, params=params,
                                           rng_seed=rng_seed)

    def prefill(self, prompt, params: Optional[SamplingParams] = None):
        """Run chunked prefill; returns the exported KV payload dict
        {prompt_ids, pages: per-layer {k,v} host arrays, first_token,
        ttft_partial_s}."""
        return self.engine.prefill_export(prompt, params or SamplingParams())


class DecodeReplica:
    """Owns a paged engine that only ever decodes externally-prefilled
    sequences."""

    def __init__(self, engine_cfg, params=None, rng_seed: int = 0):
        from .paged_engine import PagedInferenceEngine
        self.engine = PagedInferenceEngine(engine_cfg, params=params,
                                           rng_seed=rng_seed)

    def decode(self, payload, params: Optional[SamplingParams] = None):
        """Import a prefilled KV payload and decode to completion; returns
        the engine's result dict {text, token_ids, ...}."""
        req = self.engine.import_prefill(payload,
                                         params or SamplingParams())
        self.engine.run_until_done([req])
        return self.engine._result(req)


@dataclasses.dataclass
class _PDStats:
    requests: int = 0
    prefill_rr: int = 0
    decode_rr: int = 0


class PDProxy:
    """Routes generate() calls: prefill on one replica group, decode on the
    other, round-robin (reference PDProxyServer:64 — its router also
    round-robins pow-2 within each group)."""

    def __init__(self, prefill_handles: list, decode_handles: list):
        import threading
        if not prefill_handles or not decode_handles:
            raise ValueError("need at least one prefill and one decode "
                             "replica")
        self.prefill = list(prefill_handles)
        self.decode = list(decode_handles)
        self.stats = _PDStats()
        # generate() runs on max_concurrency threads: counters need a lock
        self._lock = threading.Lock()

    def generate(self, prompt, params: Optional[SamplingParams] = None):
        import ray_tpu
        s = self.stats
        with self._lock:
            s.requests += 1
            p = self.prefill[s.prefill_rr % len(self.prefill)]
            d = self.decode[s.decode_rr % len(self.decode)]
            s.prefill_rr += 1
            s.decode_rr += 1
        # the payload ObjectRef flows straight into the decode call — the
        # KV bytes move store-to-store, never through this proxy
        payload_ref = p.prefill.remote(prompt, params)
        return ray_tpu.get(d.decode.remote(payload_ref, params),
                           timeout=600)

    def proxy_stats(self) -> dict:
        with self._lock:
            return dataclasses.asdict(self.stats)


def build_pd_proxy(n_prefill: int, n_decode: int, engine_cfg,
                   params=None, rng_seed: int = 0,
                   prefill_options: Optional[dict] = None,
                   decode_options: Optional[dict] = None):
    """Actor-graph wiring (reference build_app:160): N prefill + M decode
    replica actors behind one PDProxy actor. Returns the proxy handle."""
    import ray_tpu
    popts = prefill_options or {}
    dopts = decode_options or {}
    Pre = ray_tpu.remote(PrefillReplica)
    Dec = ray_tpu.remote(DecodeReplica)
    prefills = [Pre.options(**popts).remote(engine_cfg, params, rng_seed)
                for _ in range(n_prefill)]
    decodes = [Dec.options(**dopts).remote(engine_cfg, params, rng_seed)
               for _ in range(n_decode)]
    Proxy = ray_tpu.remote(PDProxy)
    return Proxy.options(max_concurrency=16).remote(prefills, decodes)
