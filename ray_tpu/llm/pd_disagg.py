"""Prefill/decode disaggregation for the paged serving engine.

Reference parity: llm/_internal/serve/deployments/prefill_decode_disagg/
prefill_decode_disagg.py:64 (PDProxyServer — routes each request to a
prefill instance, then streams tokens from a decode instance once the KV
transferred) and :160 (build_app wiring the two replica groups behind one
proxy).

TPU-first shape: prefill replicas run ONLY chunked prefill (compute-bound,
MXU-heavy, long sequences), decode replicas run ONLY batched paged decode
(memory-bandwidth-bound, latency-sensitive). The prefilled KV pages move
between replicas as plain objects on the data plane (shared store on one
host, the object-transfer service across hosts) — the role NIXL/KV-connector
plays for the reference. Disaggregation exists to protect decode TTFT/ITL
from long-prompt prefill stalls; colocating both phases in one engine forces
them to share one compiled-step budget.

Usage:
    proxy = build_pd_proxy(n_prefill=1, n_decode=1, engine_cfg=cfg)
    text = ray_tpu.get(proxy.generate.remote("hello", SamplingParams()))
"""
from __future__ import annotations

import dataclasses
from typing import Any, Optional

from .engine import SamplingParams


class PrefillReplica:
    """Owns a paged engine used exclusively for prefill; returns the KV
    payload (pages + first sampled token) instead of decoding."""

    def __init__(self, engine_cfg, params=None, rng_seed: int = 0,
                 warmup: bool = True):
        from .paged_engine import PagedInferenceEngine
        self.engine = PagedInferenceEngine(engine_cfg, params=params,
                                           rng_seed=rng_seed)
        if warmup:
            # prefill-only replica: never dispatches decode/verify
            self.engine.warmup(families=("prefill",))

    def prefill(self, prompt, params: Optional[SamplingParams] = None):
        """Run chunked prefill; returns the exported KV payload dict
        {prompt_ids, pages: per-layer {k,v} host arrays, first_token,
        ttft_partial_s}."""
        return self.engine.prefill_export(prompt, params or SamplingParams())

    def prefill_ref(self, prompt, params: Optional[SamplingParams] = None):
        """Like prefill(), but parks the payload in the object store and
        returns only its ObjectRef — the KV bytes then move store-to-store
        to whichever decode replica receives the ref (the data-plane role
        NIXL plays for the reference's PD deployments)."""
        import ray_tpu
        return ray_tpu.put(self.prefill(prompt, params))

    def check_health(self):
        return True


class DecodeReplica:
    """Owns a paged engine that only ever decodes externally-prefilled
    sequences. A background thread steps the engine so imported requests
    decode continuously; callers either block (`decode`) or stream
    (`start` + `poll`, the replica-side half of the proxy's async token
    stream — reference `_predict`'s async generator,
    prefill_decode_disagg.py:98)."""

    def __init__(self, engine_cfg, params=None, rng_seed: int = 0,
                 warmup: bool = True):
        import threading
        from .paged_engine import PagedInferenceEngine
        self.engine = PagedInferenceEngine(engine_cfg, params=params,
                                           rng_seed=rng_seed)
        if warmup:
            # decode-only replica: imported KV pages, no prefill programs
            self.engine.warmup(families=("decode", "verify"))
        self._reqs: dict[int, Any] = {}
        self._next_rid = 0
        # serializes import_prefill against the stepping thread (the
        # engine's own _lock only guards admission, not the decode step)
        self._steplock = threading.Lock()
        self._wake = threading.Event()
        self._stop = False
        self._error: Optional[BaseException] = None
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self):
        try:
            while not self._stop:
                with self._steplock:
                    worked = self.engine.has_work()
                    if worked:
                        self.engine.step()
                if not worked:
                    self._wake.wait(timeout=0.02)
                    self._wake.clear()
        except BaseException as e:  # noqa: BLE001 — engine died: fail fast
            self._error = e
            for req in list(self._reqs.values()):
                req.event.set()

    def start(self, payload, params: Optional[SamplingParams] = None) -> int:
        """Import a prefilled KV payload into the decode pool; returns a
        request id for poll()/wait()."""
        if self._error is not None:
            raise RuntimeError("decode engine died") from self._error
        from ..core.ref import ObjectRef
        if isinstance(payload, ObjectRef):
            # prefill_ref hands out a ref-to-the-payload: the KV bytes
            # cross store-to-store here, on the decode replica, never
            # through the proxy
            import ray_tpu
            payload = ray_tpu.get(payload, timeout=300)
        with self._steplock:
            req = self.engine.import_prefill(payload,
                                             params or SamplingParams())
        rid = self._next_rid
        self._next_rid += 1
        self._reqs[rid] = req
        self._wake.set()
        return rid

    def poll(self, rid: int) -> dict:
        """Non-blocking progress read: {text, n_tokens, done,
        finish_reason} for a started request. The proxy's streaming
        generator turns successive polls into SSE deltas."""
        if self._error is not None:
            raise RuntimeError("decode engine died") from self._error
        req = self._reqs[rid]
        out = {
            "text": self.engine.tokenizer.decode(list(req.out_ids)),
            "n_tokens": len(req.out_ids),
            "done": req.done,
            "finish_reason": None,
        }
        if req.done:
            res = self.engine._result(req)
            out["text"] = res["text"]
            out["finish_reason"] = res["finish_reason"]
            out["prompt_tokens"] = res["prompt_tokens"]
            self._reqs.pop(rid, None)
        return out

    def wait(self, rid: int, timeout: float = 600.0) -> dict:
        """Block until the request finishes; returns the engine's result
        dict (the non-streaming completion path)."""
        import time as _time
        req = self._reqs[rid]
        deadline = _time.monotonic() + timeout
        while not req.event.wait(timeout=0.5):
            if self._error is not None:
                raise RuntimeError("decode engine died") from self._error
            if _time.monotonic() > deadline:
                raise TimeoutError(f"decode of request {rid} timed out")
        self._reqs.pop(rid, None)
        return self.engine._result(req)

    def decode(self, payload, params: Optional[SamplingParams] = None):
        """Import a prefilled KV payload and decode to completion; returns
        the engine's result dict {text, token_ids, ...}."""
        return self.wait(self.start(payload, params))

    def decode_stream(self, payload,
                      params: Optional[SamplingParams] = None):
        """Generator: import the KV payload and yield progress dicts
        ({text, n_tokens, done, finish_reason}) as tokens land. One
        streaming call carries the whole request, so a serve streaming
        handle stays pinned to THIS replica (stream_next goes to the
        retaining replica) — no cross-replica request-id routing. The
        request entry is dropped even when the consumer abandons the
        stream mid-way (client disconnect)."""
        import time as _time
        rid = self.start(payload, params)
        req = self._reqs[rid]
        sent = 0
        try:
            while True:
                if self._error is not None:
                    raise RuntimeError(
                        "decode engine died") from self._error
                n = len(req.out_ids)
                if req.done:
                    res = self.engine._result(req)
                    yield {"text": res["text"], "n_tokens": n,
                           "done": True,
                           "finish_reason": res["finish_reason"],
                           "prompt_tokens": res["prompt_tokens"]}
                    return
                if n > sent:
                    sent = n
                    yield {"text": self.engine.tokenizer.decode(
                               list(req.out_ids)),
                           "n_tokens": n, "done": False,
                           "finish_reason": None}
                else:
                    _time.sleep(0.01)
        finally:
            self._reqs.pop(rid, None)

    def check_health(self):
        if self._error is not None or not self._thread.is_alive():
            raise RuntimeError("decode engine loop died") from self._error
        return True


@dataclasses.dataclass
class _PDStats:
    requests: int = 0
    prefill_rr: int = 0
    decode_rr: int = 0


class PDProxy:
    """Routes generate() calls: prefill on one replica group, decode on the
    other, round-robin (reference PDProxyServer:64 — its router also
    round-robins pow-2 within each group)."""

    def __init__(self, prefill_handles: list, decode_handles: list):
        import threading
        if not prefill_handles or not decode_handles:
            raise ValueError("need at least one prefill and one decode "
                             "replica")
        self.prefill = list(prefill_handles)
        self.decode = list(decode_handles)
        self.stats = _PDStats()
        # generate() runs on max_concurrency threads: counters need a lock
        self._lock = threading.Lock()

    def generate(self, prompt, params: Optional[SamplingParams] = None):
        import ray_tpu
        s = self.stats
        with self._lock:
            s.requests += 1
            p = self.prefill[s.prefill_rr % len(self.prefill)]
            d = self.decode[s.decode_rr % len(self.decode)]
            s.prefill_rr += 1
            s.decode_rr += 1
        # the payload ObjectRef flows straight into the decode call — the
        # KV bytes move store-to-store, never through this proxy
        payload_ref = p.prefill.remote(prompt, params)
        return ray_tpu.get(d.decode.remote(payload_ref, params),
                           timeout=600)

    def proxy_stats(self) -> dict:
        with self._lock:
            return dataclasses.asdict(self.stats)


def _params_from_request(request: dict) -> SamplingParams:
    return SamplingParams(
        max_tokens=int(request.get("max_tokens", 64)),
        temperature=float(request.get("temperature", 0.0)),
        top_k=int(request.get("top_k", 0)),
    )


class PDServer:
    """Disaggregated drop-in for LLMServer behind the OpenAI ingress
    (reference: PDProxyServer subclasses the LLM server,
    prefill_decode_disagg.py:64, streaming `_predict` :98): speaks the
    same completions/completions_stream surface, but each request
    prefills on one replica group and decodes on the other. The KV
    payload crosses as an ObjectRef — store-to-store on the data plane,
    never through this proxy."""

    def __init__(self, model_id: str, prefill_handle, decode_handle):
        from ..core.usage import record_library_usage
        record_library_usage("llm")
        self.model_id = model_id
        self.prefill = prefill_handle
        self.decode = decode_handle

    def _prefill_ref(self, request: dict):
        """Run prefill on one replica; returns (payload ObjectRef,
        SamplingParams). The decode side receives only the ref — KV bytes
        move store-to-store."""
        sp = _params_from_request(request)
        return self.prefill.options(
            method_name="prefill_ref").remote(
                request.get("prompt", ""), sp).result(timeout_s=300), sp

    def completions(self, request: dict) -> dict:
        # one unary call per request: the serve handle picks a decode
        # replica once and the whole decode happens there (no
        # cross-replica request-id routing to get wrong)
        payload_ref, sp = self._prefill_ref(request or {})
        out = self.decode.options(method_name="decode").remote(
            payload_ref, sp).result(timeout_s=600)
        return {
            "object": "text_completion",
            "model": self.model_id,
            "choices": [{
                "text": out["text"],
                "finish_reason": out["finish_reason"],
                "index": 0,
            }],
            "usage": {
                "prompt_tokens": out["prompt_tokens"],
                "completion_tokens": len(out["token_ids"]),
            },
        }

    def completions_stream(self, request: dict):
        """Generator of token-delta chunks: ONE streaming call to a decode
        replica (the generator stays replica-pinned) re-emitted as OpenAI
        chunks (the role of the reference's router StreamingResponse over
        `_predict`, router.py:259-264)."""
        payload_ref, sp = self._prefill_ref(request or {})
        gen = self.decode.options(method_name="decode_stream",
                                  stream=True).remote(payload_ref, sp)
        emitted = ""
        for out in gen:
            text = out["text"]
            if out["done"]:
                # on prefix divergence (multi-byte fallback spanning more
                # than the withheld window) emit from the boundary anyway:
                # a few garbled chars beat re-sending the whole completion
                tail = text[len(emitted):]
                yield {"object": "text_completion.chunk",
                       "model": self.model_id,
                       "choices": [{"text": tail, "index": 0,
                                    "finish_reason": out["finish_reason"]}]}
                return
            # withhold the last few chars: a partial multi-byte token
            # sequence decodes to replacement chars that the next token
            # may rewrite — emit only the stable prefix
            stable = text[:max(0, len(text) - 4)]
            if stable.startswith(emitted) and len(stable) > len(emitted):
                delta = stable[len(emitted):]
                emitted = stable
                yield {"object": "text_completion.chunk",
                       "model": self.model_id,
                       "choices": [{"text": delta, "index": 0,
                                    "finish_reason": None}]}

    def __call__(self, request: dict) -> dict:
        return self.completions(request or {})

    def check_health(self):
        return True


def build_pd_openai_app(model_id: str, n_prefill: int, n_decode: int,
                        engine_cfg, params=None, rng_seed: int = 0):
    """Disaggregated OpenAI app (reference build_app,
    prefill_decode_disagg.py:160): prefill and decode replica groups as
    Serve deployments, a PDServer deployment routing between them, and
    the OpenAI router as ingress — /v1/completions with stream=true
    crosses the prefill->decode handoff and streams SSE out the HTTP
    proxy."""
    from .. import serve
    from .openai_api import OpenAIRouter
    pre = serve.deployment(
        PrefillReplica, name=f"pd-prefill:{model_id}",
        num_replicas=n_prefill).bind(engine_cfg, params, rng_seed)
    dec = serve.deployment(
        DecodeReplica, name=f"pd-decode:{model_id}",
        num_replicas=n_decode).bind(engine_cfg, params, rng_seed)
    pd = serve.deployment(
        PDServer, name=f"pd:{model_id}").bind(model_id, pre, dec)
    router = serve.deployment(OpenAIRouter, name="openai-router")
    return router.bind([model_id], pd)


def build_pd_proxy(n_prefill: int, n_decode: int, engine_cfg,
                   params=None, rng_seed: int = 0,
                   prefill_options: Optional[dict] = None,
                   decode_options: Optional[dict] = None):
    """Actor-graph wiring (reference build_app:160): N prefill + M decode
    replica actors behind one PDProxy actor. Returns the proxy handle."""
    import ray_tpu
    popts = prefill_options or {}
    dopts = decode_options or {}
    Pre = ray_tpu.remote(PrefillReplica)
    Dec = ray_tpu.remote(DecodeReplica)
    prefills = [Pre.options(**popts).remote(engine_cfg, params, rng_seed)
                for _ in range(n_prefill)]
    decodes = [Dec.options(**dopts).remote(engine_cfg, params, rng_seed)
               for _ in range(n_decode)]
    Proxy = ray_tpu.remote(PDProxy)
    return Proxy.options(max_concurrency=16).remote(prefills, decodes)
