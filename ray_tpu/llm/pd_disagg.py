"""Prefill/decode disaggregation for the paged serving engine.

Reference parity: llm/_internal/serve/deployments/prefill_decode_disagg/
prefill_decode_disagg.py:64 (PDProxyServer — routes each request to a
prefill instance, then streams tokens from a decode instance once the KV
transferred) and :160 (build_app wiring the two replica groups behind one
proxy).

TPU-first shape: prefill replicas run ONLY chunked prefill (compute-bound,
MXU-heavy, long sequences), decode replicas run ONLY batched paged decode
(memory-bandwidth-bound, latency-sensitive). The prefilled KV pages move
between replicas as plain objects on the data plane (shared store on one
host, the object-transfer service across hosts) — the role NIXL/KV-connector
plays for the reference. Disaggregation exists to protect decode TTFT/ITL
from long-prompt prefill stalls; colocating both phases in one engine forces
them to share one compiled-step budget.

Usage:
    proxy = build_pd_proxy(n_prefill=1, n_decode=1, engine_cfg=cfg)
    text = ray_tpu.get(proxy.generate.remote("hello", SamplingParams()))
"""
from __future__ import annotations

import dataclasses
from typing import Any, Optional

from .engine import SamplingParams


def _chan_counter(name: str, desc: str):
    from ..util.metrics import Counter, cached_metric
    return cached_metric(Counter, name, desc)


def _shared_store():
    """The process's shared object store, or None when sealed channels
    can't engage (no runtime, local mode, or an own-store node that
    cannot share rings with its peers — same gate as the serve stream
    channel, controller._start_stream_channel)."""
    import os
    if os.environ.get("RTPU_OWN_STORE") == "1":
        return None
    from ..core import runtime as rt_mod
    rt = rt_mod.get_runtime_if_exists()
    return getattr(rt, "store", None)


class PrefillReplica:
    """Owns a paged engine used exclusively for prefill; returns the KV
    payload (pages + first sampled token) instead of decoding."""

    def __init__(self, engine_cfg, params=None, rng_seed: int = 0,
                 warmup: bool = True):
        from .paged_engine import PagedInferenceEngine
        self.engine = PagedInferenceEngine(engine_cfg, params=params,
                                           rng_seed=rng_seed)
        if warmup:
            # prefill-only replica: never dispatches decode/verify
            self.engine.warmup(families=("prefill",))
        self._kv_writer = None

    def prefill(self, prompt, params: Optional[SamplingParams] = None):
        """Run chunked prefill; returns the exported KV payload dict
        {prompt_ids, pages: per-layer {k,v} host arrays, first_token,
        ttft_partial_s}."""
        return self.engine.prefill_export(prompt, params or SamplingParams())

    def prefill_ref(self, prompt, params: Optional[SamplingParams] = None):
        """Like prefill(), but parks the payload in the object store and
        returns only its ObjectRef — the KV bytes then move store-to-store
        to whichever decode replica receives the ref (the data-plane role
        NIXL plays for the reference's PD deployments)."""
        import ray_tpu
        return ray_tpu.put(self.prefill(prompt, params))

    # -- sealed-channel KV handoff (dag/channel.py ring; the replica is
    # the ring's single sequential producer) -------------------------------

    def connect_kv_channel(self, spec: dict) -> bool:
        """Attach this replica as the producer of a paired decode
        replica's KV ring (spec from DecodeReplica.open_kv_channel).
        After this, prefill_chan() hands finished KV payloads over with
        ZERO control dispatches — the payload is sealed into shm and the
        decode replica's drain thread imports it. Returns False when no
        shared store is available (caller falls back to actor-call
        handoff)."""
        store = _shared_store()
        if store is None or not spec:
            return False
        from ..core.ids import ObjectID
        from ..dag.channel import RingWriter
        self._kv_writer = RingWriter(store, spec["base"],
                                     ObjectID(spec["stop"]),
                                     int(spec["ring"]))
        return True

    def prefill_chan(self, prompt, cid,
                     params: Optional[SamplingParams] = None) -> Any:
        """Chunked-prefill `prompt` and stream its KV payload to the
        paired decode replica over the sealed ring; `cid` is the
        caller's correlation id (results surface on the decode side
        keyed by it). Credit backpressure runs BEFORE prefill: when the
        decoder's ring is full, admission parks here — a slow decoder
        throttles prefill instead of ballooning the store with payloads
        nobody is importing yet."""
        import time as _time
        w = self._kv_writer
        if w is None:
            raise RuntimeError("connect_kv_channel() first")
        from ..dag.channel import ChannelClosed
        stalls = _chan_counter(
            "rtpu_llm_pd_chan_credit_stalls_total",
            "prefill admissions parked on decode-ring credit")
        while not w.credit_ready():
            if w.closed():
                raise ChannelClosed("decode replica closed the KV ring")
            stalls.inc(1.0)
            _time.sleep(0.005)
        payload = self.engine.prefill_export(
            prompt, params or SamplingParams())
        w.write(("kv", {"cid": cid, "payload": payload,
                        "params": params}))
        _chan_counter("rtpu_llm_pd_chan_kv_writes_total",
                      "KV payloads sealed into decode rings").inc(1.0)
        return cid

    def has_kv_channel(self) -> bool:
        """Capability probe for serve-path callers: True once the
        controller (or proxy) has paired this replica with a decode
        ring — the signal that prefill_chan() routing can engage."""
        return self._kv_writer is not None

    def close_kv_channel(self) -> None:
        """End the stream: the sentinel retires the decode-side drain
        thread, which sweeps the ring (reader.retire()) so the channel
        leaves zero store objects behind."""
        w, self._kv_writer = self._kv_writer, None
        if w is None:
            return
        from ..dag.channel import ChannelClosed
        try:
            w.write(("e", None))
        except ChannelClosed:
            pass  # consumer already cancelled: ring swept on its side

    def check_health(self):
        return True


class DecodeReplica:
    """Owns a paged engine that only ever decodes externally-prefilled
    sequences. A background thread steps the engine so imported requests
    decode continuously; callers either block (`decode`) or stream
    (`start` + `poll`, the replica-side half of the proxy's async token
    stream — reference `_predict`'s async generator,
    prefill_decode_disagg.py:98)."""

    def __init__(self, engine_cfg, params=None, rng_seed: int = 0,
                 warmup: bool = True):
        import threading
        from .paged_engine import PagedInferenceEngine
        self.engine = PagedInferenceEngine(engine_cfg, params=params,
                                           rng_seed=rng_seed)
        if warmup:
            # decode-only replica: imported KV pages, no prefill programs
            self.engine.warmup(families=("decode", "verify"))
        self._reqs: dict[int, Any] = {}
        self._next_rid = 0
        # serializes import_prefill against the stepping thread (the
        # engine's own _lock only guards admission, not the decode step)
        self._steplock = threading.Lock()
        self._wake = threading.Event()
        self._stop = False
        self._error: Optional[BaseException] = None
        # sealed-channel handoff state: correlation id -> rid for KV
        # payloads that arrived over a ring instead of an actor call
        self._cids: dict[Any, int] = {}
        self._cid_cv = threading.Condition()
        self._chan_threads: list = []
        # ONE result ring per replica (a ring has one sequential
        # producer): every KV drain thread funnels finished decodes
        # through this shared flusher state
        self._res_writer = None
        self._res_pending: list = []
        self._res_cv = threading.Condition()
        self._kv_rings_open = 0
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self):
        try:
            while not self._stop:
                with self._steplock:
                    worked = self.engine.has_work()
                    if worked:
                        self.engine.step()
                if not worked:
                    self._wake.wait(timeout=0.02)
                    self._wake.clear()
        except BaseException as e:  # noqa: BLE001 — engine died: fail fast
            self._error = e
            for req in list(self._reqs.values()):
                req.event.set()

    def start(self, payload, params: Optional[SamplingParams] = None) -> int:
        """Import a prefilled KV payload into the decode pool; returns a
        request id for poll()/wait()."""
        if self._error is not None:
            raise RuntimeError("decode engine died") from self._error
        from ..core.ref import ObjectRef
        if isinstance(payload, ObjectRef):
            # prefill_ref hands out a ref-to-the-payload: the KV bytes
            # cross store-to-store here, on the decode replica, never
            # through the proxy
            import ray_tpu
            payload = ray_tpu.get(payload, timeout=300)
        with self._steplock:
            req = self.engine.import_prefill(payload,
                                             params or SamplingParams())
        rid = self._next_rid
        self._next_rid += 1
        self._reqs[rid] = req
        self._wake.set()
        return rid

    def poll(self, rid: int) -> dict:
        """Non-blocking progress read: {text, n_tokens, done,
        finish_reason} for a started request. The proxy's streaming
        generator turns successive polls into SSE deltas."""
        if self._error is not None:
            raise RuntimeError("decode engine died") from self._error
        req = self._reqs[rid]
        out = {
            "text": self.engine.tokenizer.decode(list(req.out_ids)),
            "n_tokens": len(req.out_ids),
            "done": req.done,
            "finish_reason": None,
        }
        if req.done:
            res = self.engine._result(req)
            out["text"] = res["text"]
            out["finish_reason"] = res["finish_reason"]
            out["prompt_tokens"] = res["prompt_tokens"]
            self._reqs.pop(rid, None)
        return out

    def wait(self, rid: int, timeout: float = 600.0) -> dict:
        """Block until the request finishes; returns the engine's result
        dict (the non-streaming completion path)."""
        import time as _time
        req = self._reqs[rid]
        deadline = _time.monotonic() + timeout
        while not req.event.wait(timeout=0.5):
            if self._error is not None:
                raise RuntimeError("decode engine died") from self._error
            if _time.monotonic() > deadline:
                raise TimeoutError(f"decode of request {rid} timed out")
        self._reqs.pop(rid, None)
        return self.engine._result(req)

    def decode(self, payload, params: Optional[SamplingParams] = None):
        """Import a prefilled KV payload and decode to completion; returns
        the engine's result dict {text, token_ids, ...}."""
        return self.wait(self.start(payload, params))

    def decode_stream(self, payload,
                      params: Optional[SamplingParams] = None):
        """Generator: import the KV payload and yield progress dicts
        ({text, n_tokens, done, finish_reason}) as tokens land. One
        streaming call carries the whole request, so a serve streaming
        handle stays pinned to THIS replica (stream_next goes to the
        retaining replica) — no cross-replica request-id routing. The
        request entry is dropped even when the consumer abandons the
        stream mid-way (client disconnect)."""
        import time as _time
        rid = self.start(payload, params)
        req = self._reqs[rid]
        sent = 0
        try:
            while True:
                if self._error is not None:
                    raise RuntimeError(
                        "decode engine died") from self._error
                n = len(req.out_ids)
                if req.done:
                    res = self.engine._result(req)
                    yield {"text": res["text"], "n_tokens": n,
                           "done": True,
                           "finish_reason": res["finish_reason"],
                           "prompt_tokens": res["prompt_tokens"]}
                    return
                if n > sent:
                    sent = n
                    yield {"text": self.engine.tokenizer.decode(
                               list(req.out_ids)),
                           "n_tokens": n, "done": False,
                           "finish_reason": None}
                else:
                    _time.sleep(0.01)
        finally:
            self._reqs.pop(rid, None)

    # -- sealed-channel KV handoff (consumer side) -------------------------

    def open_kv_channel(self, ring: int = 4,
                        result_chan: Optional[dict] = None) -> dict:
        """Mint a KV-handoff ring this replica consumes and start its
        drain thread; returns the channel spec the paired prefill
        replica connects to (empty dict = no shared store, caller falls
        back to actor-call handoff). Each paired prefill replica gets
        its OWN ring — a ring has exactly one sequential producer.

        ``result_chan`` (optional, same spec shape) makes finished
        decodes flow back the same way: a writer this replica produces
        into, carrying ("res", {cid, result}) — so in steady state a
        request's handoff AND its completion cross zero control
        dispatches, exactly the serve stream-channel economics."""
        import os
        import threading
        store = _shared_store()
        if store is None:
            return {}
        from ..core.ids import ObjectID
        from ..dag.channel import ChannelClosed, RingReader, RingWriter
        spec = {"base": os.urandom(16), "stop": os.urandom(16),
                "ring": max(2, int(ring))}
        reader = RingReader(store, spec["base"], ObjectID(spec["stop"]),
                            spec["ring"])
        want_results = bool(result_chan)
        with self._res_cv:
            self._kv_rings_open += 1
            if want_results and self._res_writer is None:
                self._res_writer = RingWriter(
                    store, result_chan["base"],
                    ObjectID(result_chan["stop"]),
                    int(result_chan["ring"]))
                tf = threading.Thread(target=self._flush_results,
                                      daemon=True,
                                      name="pd-kv-chan-results")
                tf.start()
                self._chan_threads.append(tf)

        def drain():
            try:
                while True:
                    try:
                        kind, item = reader.read(timeout_s=None)
                    except ChannelClosed:
                        reader.retire()
                        break
                    if kind != "kv":            # ("e", None) sentinel
                        reader.retire()
                        break
                    rid = self.start(item["payload"], item["params"])
                    _chan_counter(
                        "rtpu_llm_pd_chan_kv_imports_total",
                        "KV payloads imported from sealed rings").inc(1.0)
                    with self._cid_cv:
                        self._cids[item["cid"]] = rid
                        self._cid_cv.notify_all()
                    if want_results:
                        with self._res_cv:
                            self._res_pending.append((item["cid"], rid))
                            self._res_cv.notify_all()
            except BaseException as e:  # noqa: BLE001 — surface via health
                if self._error is None:
                    self._error = e
            finally:
                with self._res_cv:
                    self._kv_rings_open -= 1
                    self._res_cv.notify_all()

        t = threading.Thread(target=drain, daemon=True,
                             name="pd-kv-chan-drain")
        t.start()
        self._chan_threads.append(t)
        return spec

    def _flush_results(self):
        """Seal finished decodes into the replica's ONE result ring
        (completion order within the in-flight window); the EOS
        sentinel trails the last result — after every KV ring closed —
        so the consumer's retire() leaves zero store objects."""
        import time as _time
        from ..dag.channel import ChannelClosed
        live: list = []
        try:
            while True:
                with self._res_cv:
                    if not self._res_pending and not live \
                            and self._kv_rings_open > 0:
                        self._res_cv.wait(timeout=0.5)
                    live.extend(self._res_pending)
                    self._res_pending.clear()
                    rings_open = self._kv_rings_open
                progressed = False
                for cid, rid in list(live):
                    req = self._reqs.get(rid)
                    if req is not None and not req.done:
                        continue
                    live.remove((cid, rid))
                    progressed = True
                    res = self.wait(rid, timeout=600.0)
                    with self._cid_cv:
                        self._cids.pop(cid, None)
                    self._res_writer.write(("res", {"cid": cid,
                                                    "result": res}))
                    _chan_counter(
                        "rtpu_llm_pd_chan_results_total",
                        "decode results sealed into result rings").inc(1.0)
                if rings_open == 0 and not live:
                    with self._res_cv:
                        if not self._res_pending:
                            self._res_writer.write(("e", None))
                            return
                elif live and not progressed:
                    _time.sleep(0.005)
        except ChannelClosed:
            pass  # result consumer cancelled: ring swept on its side
        except BaseException as e:  # noqa: BLE001 — surface via health
            if self._error is None:
                self._error = e

    def wait_cid(self, cid, timeout: float = 600.0) -> dict:
        """Block until the request handed off under correlation id
        ``cid`` (prefill_chan) finishes; returns the engine result dict.
        The serve PD path uses this when no result ring is wired: the
        KV handoff itself still crossed zero dispatches."""
        import time as _time
        deadline = _time.monotonic() + timeout
        with self._cid_cv:
            while cid not in self._cids:
                if self._error is not None:
                    raise RuntimeError(
                        "decode engine died") from self._error
                if not self._cid_cv.wait(timeout=min(
                        0.5, max(deadline - _time.monotonic(), 0.001))):
                    if _time.monotonic() > deadline:
                        raise TimeoutError(
                            f"KV payload for cid {cid!r} never arrived")
            rid = self._cids.pop(cid)
        return self.wait(rid, timeout=max(deadline - _time.monotonic(),
                                          0.001))

    def check_health(self):
        if self._error is not None or not self._thread.is_alive():
            raise RuntimeError("decode engine loop died") from self._error
        return True


@dataclasses.dataclass
class _PDStats:
    requests: int = 0
    prefill_rr: int = 0
    decode_rr: int = 0


class PDProxy:
    """Routes generate() calls: prefill on one replica group, decode on the
    other, round-robin (reference PDProxyServer:64 — its router also
    round-robins pow-2 within each group)."""

    def __init__(self, prefill_handles: list, decode_handles: list,
                 use_channels: bool = False):
        import threading
        if not prefill_handles or not decode_handles:
            raise ValueError("need at least one prefill and one decode "
                             "replica")
        self.prefill = list(prefill_handles)
        self.decode = list(decode_handles)
        self.stats = _PDStats()
        # generate() runs on max_concurrency threads: counters need a lock
        self._lock = threading.Lock()
        self._chan = False
        self._next_cid = 0
        self._futures: dict[int, list] = {}   # cid -> [Event, result]
        if use_channels:
            self._chan = self._wire_channels()

    def _wire_channels(self) -> bool:
        """Sealed-channel pipeline: prefill i produces into a KV ring
        its paired decode replica (i mod n_decode) consumes; every
        decode replica produces finished results into ONE result ring
        this proxy consumes. Steady-state per request: one admission
        call to the prefill replica, then the KV handoff AND the result
        cross zero control dispatches (the decode-plan economics applied
        to the PD handoff). Wiring costs O(replicas) dispatches ONCE."""
        import os
        import threading
        import ray_tpu
        store = _shared_store()
        if store is None:
            return False
        from ..core.ids import ObjectID
        from ..dag.channel import ChannelClosed, RingReader
        self._res_readers = []
        res_spec = {di: {"base": os.urandom(16), "stop": os.urandom(16),
                         "ring": 8} for di in range(len(self.decode))}
        res_handed = []
        kv_specs = {}
        for pi in range(len(self.prefill)):
            di = pi % len(self.decode)
            rs = res_spec.pop(di, None)     # one result ring per decode
            spec = ray_tpu.get(self.decode[di].open_kv_channel.remote(
                4, rs), timeout=60)
            if not spec:
                return False
            if rs is not None:
                res_handed.append(rs)
            kv_specs[pi] = spec
        for pi, p in enumerate(self.prefill):
            if not ray_tpu.get(p.connect_kv_channel.remote(kv_specs[pi]),
                               timeout=60):
                return False

        def drain(spec):
            reader = RingReader(store, spec["base"],
                                ObjectID(spec["stop"]), int(spec["ring"]))
            try:
                while True:
                    try:
                        kind, item = reader.read(timeout_s=None)
                    except ChannelClosed:
                        reader.retire()
                        return
                    if kind != "res":           # ("e", None) sentinel
                        reader.retire()
                        return
                    fut = self._futures.get(item["cid"])
                    if fut is not None:
                        fut[1] = item["result"]
                        fut[0].set()
            except Exception:
                import traceback
                traceback.print_exc()

        for spec in res_handed:
            t = threading.Thread(target=drain, args=(spec,), daemon=True,
                                 name="pd-proxy-results")
            t.start()
            self._res_readers.append(t)
        return True

    def generate(self, prompt, params: Optional[SamplingParams] = None):
        import ray_tpu
        s = self.stats
        with self._lock:
            s.requests += 1
            p = self.prefill[s.prefill_rr % len(self.prefill)]
            d = self.decode[s.decode_rr % len(self.decode)]
            s.prefill_rr += 1
            s.decode_rr += 1
        if self._chan:
            import threading
            with self._lock:
                cid = self._next_cid
                self._next_cid += 1
                fut = self._futures[cid] = [threading.Event(), None]
            # admission is the ONLY control dispatch: the KV payload
            # rides the sealed ring to the paired decode replica and
            # the result rides the result ring back
            admit = p.prefill_chan.remote(prompt, cid, params)
            if not fut[0].wait(timeout=600):
                raise TimeoutError(f"PD channel request {cid} timed out")
            with self._lock:
                self._futures.pop(cid, None)
            ray_tpu.get(admit, timeout=60)  # reclaim the admission ref
            return fut[1]
        # the payload ObjectRef flows straight into the decode call — the
        # KV bytes move store-to-store, never through this proxy
        payload_ref = p.prefill.remote(prompt, params)
        return ray_tpu.get(d.decode.remote(payload_ref, params),
                           timeout=600)

    def shutdown_channels(self, timeout: float = 60.0) -> None:
        """Teardown: close every KV ring (sentinel -> decode drains
        retire -> result rings EOS -> proxy drains retire). After this,
        the channels hold zero store objects."""
        if not self._chan:
            return
        import ray_tpu
        ray_tpu.get([p.close_kv_channel.remote() for p in self.prefill],
                    timeout=timeout)
        for t in self._res_readers:
            t.join(timeout=timeout)
        self._chan = False

    def proxy_stats(self) -> dict:
        with self._lock:
            st = dataclasses.asdict(self.stats)
        st["channels"] = self._chan
        return st


def _params_from_request(request: dict) -> SamplingParams:
    return SamplingParams(
        max_tokens=int(request.get("max_tokens", 64)),
        temperature=float(request.get("temperature", 0.0)),
        top_k=int(request.get("top_k", 0)),
    )


class PDServer:
    """Disaggregated drop-in for LLMServer behind the OpenAI ingress
    (reference: PDProxyServer subclasses the LLM server,
    prefill_decode_disagg.py:64, streaming `_predict` :98): speaks the
    same completions/completions_stream surface, but each request
    prefills on one replica group and decodes on the other. The KV
    payload crosses as an ObjectRef — store-to-store on the data plane,
    never through this proxy."""

    def __init__(self, model_id: str, prefill_handle, decode_handle,
                 use_channels: bool = False):
        import threading
        from ..core.usage import record_library_usage
        record_library_usage("llm")
        self.model_id = model_id
        self.prefill = prefill_handle
        self.decode = decode_handle
        self._chan = bool(use_channels)
        self._chan_ok: Optional[bool] = None  # lazy capability probe
        self._n_pre = 0
        self._n_dec = 0
        self._rr = 0
        self._rr_lock = threading.Lock()

    def _chan_ready(self) -> bool:
        """Probe (once) whether the sealed-channel handoff is wired:
        the controller pairs role=prefill replicas to decode KV rings
        asynchronously after deploy, so the first request that finds
        the pairing incomplete settles the server onto the ref-based
        path for good — routing stays deterministic per process."""
        if not self._chan:
            return False
        if self._chan_ok is None:
            try:
                self._n_pre = self.prefill.num_replicas()
                self._n_dec = self.decode.num_replicas()
                ok = self.prefill.options(
                    method_name="has_kv_channel",
                    replica_index=0).remote().result(timeout_s=30)
                self._chan_ok = bool(ok) and \
                    self._n_pre > 0 and self._n_dec > 0
            except Exception:
                self._chan_ok = False
        return self._chan_ok

    def _chan_completion(self, request: dict) -> dict:
        """Channel-path unary completion: prefill_chan seals the KV
        payload straight into the paired decode replica's ring (zero
        handoff dispatches — the two control calls here are admission
        and result collection, same count as the ref path, but the KV
        bytes never surface as an ObjectRef). Replica indices follow
        the controller's pairing rule (prefill i -> decode i % n_dec),
        so the wait lands on the replica that imports the payload."""
        import os
        sp = _params_from_request(request)
        with self._rr_lock:
            i_pre = self._rr % self._n_pre
            self._rr += 1
        i_dec = i_pre % self._n_dec
        cid = os.urandom(8).hex()
        admit = self.prefill.options(
            method_name="prefill_chan", replica_index=i_pre).remote(
                request.get("prompt", ""), cid, sp)
        admit.result(timeout_s=300)  # surfaces prefill/ring errors
        out = self.decode.options(
            method_name="wait_cid", replica_index=i_dec).remote(
                cid).result(timeout_s=600)
        return {
            "object": "text_completion",
            "model": self.model_id,
            "choices": [{
                "text": out["text"],
                "finish_reason": out["finish_reason"],
                "index": 0,
            }],
            "usage": {
                "prompt_tokens": out["prompt_tokens"],
                "completion_tokens": len(out["token_ids"]),
            },
        }

    def _prefill_ref(self, request: dict):
        """Run prefill on one replica; returns (payload ObjectRef,
        SamplingParams). The decode side receives only the ref — KV bytes
        move store-to-store."""
        sp = _params_from_request(request)
        return self.prefill.options(
            method_name="prefill_ref").remote(
                request.get("prompt", ""), sp).result(timeout_s=300), sp

    def completions(self, request: dict) -> dict:
        if self._chan_ready():
            return self._chan_completion(request or {})
        # one unary call per request: the serve handle picks a decode
        # replica once and the whole decode happens there (no
        # cross-replica request-id routing to get wrong)
        payload_ref, sp = self._prefill_ref(request or {})
        out = self.decode.options(method_name="decode").remote(
            payload_ref, sp).result(timeout_s=600)
        return {
            "object": "text_completion",
            "model": self.model_id,
            "choices": [{
                "text": out["text"],
                "finish_reason": out["finish_reason"],
                "index": 0,
            }],
            "usage": {
                "prompt_tokens": out["prompt_tokens"],
                "completion_tokens": len(out["token_ids"]),
            },
        }

    def completions_stream(self, request: dict):
        """Generator of token-delta chunks: ONE streaming call to a decode
        replica (the generator stays replica-pinned) re-emitted as OpenAI
        chunks (the role of the reference's router StreamingResponse over
        `_predict`, router.py:259-264)."""
        payload_ref, sp = self._prefill_ref(request or {})
        gen = self.decode.options(method_name="decode_stream",
                                  stream=True).remote(payload_ref, sp)
        emitted = ""
        for out in gen:
            text = out["text"]
            if out["done"]:
                # on prefix divergence (multi-byte fallback spanning more
                # than the withheld window) emit from the boundary anyway:
                # a few garbled chars beat re-sending the whole completion
                tail = text[len(emitted):]
                yield {"object": "text_completion.chunk",
                       "model": self.model_id,
                       "choices": [{"text": tail, "index": 0,
                                    "finish_reason": out["finish_reason"]}]}
                return
            # withhold the last few chars: a partial multi-byte token
            # sequence decodes to replacement chars that the next token
            # may rewrite — emit only the stable prefix
            stable = text[:max(0, len(text) - 4)]
            if stable.startswith(emitted) and len(stable) > len(emitted):
                delta = stable[len(emitted):]
                emitted = stable
                yield {"object": "text_completion.chunk",
                       "model": self.model_id,
                       "choices": [{"text": delta, "index": 0,
                                    "finish_reason": None}]}

    def __call__(self, request: dict) -> dict:
        return self.completions(request or {})

    def check_health(self):
        return True


def build_pd_openai_app(model_id: str, n_prefill: int, n_decode: int,
                        engine_cfg, params=None, rng_seed: int = 0,
                        use_channels: bool = False):
    """Disaggregated OpenAI app (reference build_app,
    prefill_decode_disagg.py:160): prefill and decode replica groups as
    Serve deployments, a PDServer deployment routing between them, and
    the OpenAI router as ingress — /v1/completions with stream=true
    crosses the prefill->decode handoff and streams SSE out the HTTP
    proxy. The role tags let the controller pair each prefill replica
    with a decode KV ring; with ``use_channels`` the PDServer routes
    unary completions over that sealed handoff once pairing lands."""
    from .. import serve
    from .openai_api import OpenAIRouter
    pre = serve.deployment(
        PrefillReplica, name=f"pd-prefill:{model_id}",
        num_replicas=n_prefill,
        role="prefill").bind(engine_cfg, params, rng_seed)
    dec = serve.deployment(
        DecodeReplica, name=f"pd-decode:{model_id}",
        num_replicas=n_decode,
        role="decode").bind(engine_cfg, params, rng_seed)
    pd = serve.deployment(
        PDServer, name=f"pd:{model_id}").bind(
            model_id, pre, dec, use_channels)
    router = serve.deployment(OpenAIRouter, name="openai-router")
    return router.bind([model_id], pd)


def build_pd_proxy(n_prefill: int, n_decode: int, engine_cfg,
                   params=None, rng_seed: int = 0,
                   prefill_options: Optional[dict] = None,
                   decode_options: Optional[dict] = None,
                   use_channels: bool = False):
    """Actor-graph wiring (reference build_app:160): N prefill + M decode
    replica actors behind one PDProxy actor. Returns the proxy handle.
    With ``use_channels`` the proxy wires the sealed-ring KV handoff at
    construction (falls back to actor-call handoff when no shared store
    is available)."""
    import ray_tpu
    popts = prefill_options or {}
    dopts = decode_options or {}
    Pre = ray_tpu.remote(PrefillReplica)
    Dec = ray_tpu.remote(DecodeReplica)
    prefills = [Pre.options(**popts).remote(engine_cfg, params, rng_seed)
                for _ in range(n_prefill)]
    decodes = [Dec.options(**dopts).remote(engine_cfg, params, rng_seed)
               for _ in range(n_decode)]
    Proxy = ray_tpu.remote(PDProxy)
    return Proxy.options(max_concurrency=16).remote(
        prefills, decodes, use_channels)
