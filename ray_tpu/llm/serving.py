"""serve.llm analog: the engine behind a Serve deployment.

Reference parity: llm/_internal/serve/deployments/llm/llm_server.py:409
(LLMServer — async request intake feeding the engine loop) and :704
(LLMDeployment — the Serve wrapper); router surface matches the OpenAI
completions shape the reference's router exposes.

TPU note (reference analog: LLMConfig -> PG bundles for TP×PP workers,
configs/server_models.py:391-415): the engine's model runs under the current
process's mesh; multi-chip TP serving shards the same jitted programs over a
tp axis — replicas gang-schedule via the deployment's ray_actor_options
TPU resources.
"""
from __future__ import annotations

import dataclasses
import threading
import time as _time
from typing import Any, Optional

from .engine import EngineConfig, InferenceEngine, SamplingParams
from .paged_engine import PagedEngineConfig, PagedInferenceEngine


@dataclasses.dataclass
class LLMConfig:
    """(reference: llm/_internal/serve/configs/server_models.py LLMConfig)

    `engine` may be an EngineConfig (dense slot cache) or a
    PagedEngineConfig (paged-KV continuous batching — the production path);
    the default is paged.

    LoRA, two modes:

    - **batched multi-LoRA** (production multi-tenant path): a
      PagedEngineConfig with ``max_adapters > 0`` serves every adapter
      from ONE engine — a request carrying ``"lora": "<id>"`` (or
      ``model="<model_id>:<id>"``) resolves the adapter's latest
      version in the AdapterRegistry (namespace ``lora_namespace``,
      default the model_id) at admission, rides a resident slot-table
      row, and shares the decode dispatch with every other tenant.
      Hot-swap: a newly published version starts serving within
      cfg.llm_lora_refresh_s, in-flight requests finish on their
      admitted version. Prefix-cache keys are salted per
      (adapter_id, version), so warmed prefixes never cross tenants.
    - **merged engines** (legacy / single-tenant): ``lora_dir`` holds
      ``<adapter_id>.npz`` adapters (llm/lora.py format) merged into a
      full param copy each, one engine per resident adapter, LRU up to
      ``max_loras``. Also the parity oracle for the batched path."""
    model_id: str = "llama-tiny"
    engine: Optional[EngineConfig | PagedEngineConfig] = None
    num_replicas: int = 1
    max_ongoing_requests: int = 64
    tpus_per_replica: float = 0.0
    lora_dir: Optional[str] = None
    max_loras: int = 2
    # registry namespace for batched multi-LoRA (None -> model_id)
    lora_namespace: Optional[str] = None
    # compile every engine program family at replica init, before the
    # replica reports ready (vLLM-style deploy-time graph capture) —
    # keeps the first request burst from paying mid-burst XLA compiles.
    # Sampled + top-k modes are warmed too when True.
    warmup: bool = True
    warmup_sampled: bool = False


class LLMServer:
    """Deployment callable: background engine thread + request futures
    (reference: llm_server.py:409)."""

    def __init__(self, cfg: LLMConfig, params_ref=None):
        from collections import OrderedDict

        from ..core.usage import record_library_usage
        record_library_usage("llm")

        from ..models import llama
        self.cfg = cfg
        self.engine_cfg = cfg.engine or PagedEngineConfig(
            model=llama.llama_tiny())
        params = None
        if params_ref is not None:
            import ray_tpu
            params = ray_tpu.get(params_ref)
        self.engine = self._build_engine(params)
        self.base_params = self.engine.params
        self.model_id = cfg.model_id
        # adapter-id -> engine over merged weights (lora.py docstring);
        # OrderedDict is the LRU. _lora_lock guards every mutation AND the
        # loop's snapshot: request threads (max_concurrency) race the
        # engine thread here
        self._lora_engines: "OrderedDict[str, Any]" = OrderedDict()
        self._lora_lock = threading.Lock()
        self._wake = threading.Event()
        self._stop = False
        self._last_rewarm = 0.0   # spill-tier re-warm cadence (loop)
        self._error: Optional[BaseException] = None
        # serializes engine stepping against cross-replica page
        # import/export (the dispatches donate engine.caches, so a
        # concurrent scatter/gather would read deleted buffers — same
        # contract as pd_disagg's _steplock around import_prefill)
        self._steplock = threading.Lock()
        # cluster prefix directory (serve/frontdoor/prefix.py): base
        # paged engine only — LoRA-merged engines produce different KV
        # for the same tokens and must stay out of the shared-by-model
        # directory. The controller injects this replica's own handle
        # via set_replica_handle; publishing starts then.
        self._prefix_dir = None
        from ..core.config import cfg as rcfg
        if rcfg.serve_prefix_directory and \
                getattr(self.engine, "_prefix_on", False):
            from ..serve.frontdoor.prefix import PrefixDirectoryClient
            self._prefix_dir = PrefixDirectoryClient(cfg.model_id)
            self.engine.track_page_publish = True
        # batched multi-LoRA (llm/multilora): one engine, many tenants.
        # The manager resolves adapter ids to resident slot-table rows
        # at admission; version pinning, LRU and hot-swap live there.
        self._multilora = None
        if getattr(self.engine, "lora", None) is not None:
            from .multilora import AdapterRegistry, MultiLoraManager
            self._multilora = MultiLoraManager(
                self.engine,
                AdapterRegistry(cfg.lora_namespace or cfg.model_id))
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _build_engine(self, params):
        if isinstance(self.engine_cfg, PagedEngineConfig):
            eng = PagedInferenceEngine(self.engine_cfg, params)
            if self.cfg.warmup:
                modes = [(False, False)]
                if self.cfg.warmup_sampled:
                    modes += [(True, False), (True, True)]
                eng.warmup(sample_modes=tuple(modes))
            return eng
        return InferenceEngine(self.engine_cfg, params)

    def _engines(self):
        with self._lora_lock:
            return [self.engine, *self._lora_engines.values()]

    @staticmethod
    def _lora_id(request: dict) -> Optional[str]:
        lora_id = request.get("lora")
        model = request.get("model", "")
        if not lora_id and ":" in model:
            lora_id = model.split(":", 1)[1]
        return lora_id or None

    def _engine_for(self, request: dict):
        """Pick the engine for a request's LoRA id (None -> base)."""
        lora_id = self._lora_id(request)
        if not lora_id:
            return self.engine
        with self._lora_lock:
            eng = self._lora_engines.get(lora_id)
            if eng is not None:
                self._lora_engines.move_to_end(lora_id)
                return eng
        if not self.cfg.lora_dir:
            raise ValueError(
                f"request names LoRA {lora_id!r} but this deployment has "
                f"no lora_dir configured")
        import os

        from . import lora
        path = os.path.join(self.cfg.lora_dir, lora_id)
        adapter = lora.load_adapter(path)
        merged = lora.merge(self.base_params, adapter)
        eng = self._build_engine(merged)
        with self._lora_lock:
            raced = self._lora_engines.get(lora_id)
            if raced is not None:  # another thread built it concurrently
                return raced
            self._lora_engines[lora_id] = eng
            # evict only IDLE engines: evicting one with in-flight
            # requests would orphan them (their events never fire); if
            # everything is busy, temporarily exceed the cap and retry on
            # the next load
            if len(self._lora_engines) > self.cfg.max_loras:
                for lid in list(self._lora_engines):
                    if lid == lora_id:
                        continue
                    if not self._lora_engines[lid].has_work():
                        del self._lora_engines[lid]  # KV pool freed
                        if len(self._lora_engines) <= self.cfg.max_loras:
                            break
        return eng

    def _loop(self):
        try:
            while not self._stop:
                worked = False
                for eng in self._engines():
                    if eng.has_work():
                        with self._steplock:
                            eng.step()
                        worked = True
                if self._prefix_dir is not None:
                    # drain newly published/evicted page hashes to the
                    # cluster directory (rate-limited inside; this IS
                    # the stepping thread, per the drain contract)
                    self._prefix_dir.maybe_publish(self.engine)
                if getattr(self.engine, "spill", None) is not None:
                    now = _time.monotonic()
                    if now - self._last_rewarm >= 0.25:
                        # proactive promote of the hottest spilled
                        # chain into idle pool headroom; bounded pages
                        # per tick so the scatter never stalls a step.
                        # Under the steplock: the scatter donates the
                        # cache pools (import_prefix contract).
                        self._last_rewarm = now
                        with self._steplock:
                            self.engine.maybe_rewarm(max_pages=32)
                if not worked:
                    self._wake.wait(timeout=0.05)
                    self._wake.clear()
        except BaseException as e:  # noqa: BLE001 — engine died: fail fast
            self._error = e
            # unblock every waiter; completions() re-raises the error, and
            # check_health makes the controller replace this replica
            for eng in self._engines():
                for req in (list(eng._active.values())
                            + list(eng._pending)
                            + list(getattr(eng, "_prefilling", []))):
                    req.event.set()

    # -- OpenAI-ish surface ------------------------------------------------

    def _submit(self, request: dict):
        prompt = request.get("prompt", "")
        sp = SamplingParams(
            max_tokens=int(request.get("max_tokens", 64)),
            temperature=float(request.get("temperature", 0.0)),
            top_k=int(request.get("top_k", 0)),
            logprobs=int(request.get("logprobs") or 0),
        )
        lora_id = self._lora_id(request)
        if lora_id and self._multilora is not None:
            # batched multi-LoRA: resolve the adapter's latest version
            # at ADMISSION (in-flight requests stay pinned to it), ride
            # a slot-table row on the shared engine, and salt every
            # prefix-cache key with (adapter_id, version). pin=True
            # holds the slot against eviction across the tokenize +
            # prefix-import window below — the engine's own in-flight
            # accounting starts only at submit(). Errors stay TYPED:
            # unknown adapter -> ValueError (client error), all slots
            # live -> RuntimeError("overloaded: ...") the proxy turns
            # into a retryable 503, never a bare 500.
            try:
                slot, _version, salt = self._multilora.resolve(
                    lora_id, self._steplock, pin=True)
            except KeyError as e:
                raise ValueError(
                    f"unknown LoRA adapter {lora_id!r} for model "
                    f"{self.model_id!r}: {e}") from e
            eng = self.engine
            try:
                prompt = (eng.tokenizer.encode(prompt)
                          if isinstance(prompt, str) else list(prompt))
                if self._prefix_dir is not None:
                    # tenant-salted hashes: directory entries for this
                    # (adapter_id, version) can only match its own pages
                    self._prefix_dir.maybe_import(eng, self._steplock,
                                                  prompt, salt=salt)
                req = eng.submit(prompt, sp, adapter_slot=slot,
                                 prefix_salt=salt)
            finally:
                self._multilora.unpin(slot)
            self._wake.set()
            return eng, req
        eng = self._engine_for(request)
        # tokenize ONCE: the prefix-directory lookup and submit share
        # the ids (a second encode of a long system prompt would tax
        # exactly the workloads the directory accelerates)
        prompt = (eng.tokenizer.encode(prompt)
                  if isinstance(prompt, str) else list(prompt))
        if self._prefix_dir is not None and eng is self.engine:
            # cluster prefix directory: admission-match a prefix warmed
            # on ANY replica by importing its KV pages before submit —
            # best effort, a miss/failure just means a cold prefill
            self._prefix_dir.maybe_import(eng, self._steplock, prompt)
        if sp.logprobs and not hasattr(eng, "_prefill_rows_fns"):
            # dense InferenceEngine never fills out_logps: refuse loudly
            # instead of returning a well-formed response missing the
            # requested field (paged engine is the production path)
            raise ValueError(
                "logprobs requires the paged engine "
                "(LLMConfig(engine=PagedEngineConfig(...)))")
        # submit UNDER the lora lock: eviction (also lock-guarded) only
        # removes idle engines, so once submit lands the engine has work
        # and cannot be evicted out from under this request; re-insert if
        # an eviction won the race between selection and here
        with self._lora_lock:
            if eng is not self.engine:
                lora_id = next((lid for lid, e in self._lora_engines.items()
                                if e is eng), None)
                if lora_id is None:
                    rid = request.get("lora") or request.get(
                        "model", ":").split(":", 1)[1]
                    self._lora_engines[rid] = eng
            req = eng.submit(prompt, sp)
        self._wake.set()
        return eng, req

    def completions(self, request: dict) -> dict:
        """{"prompt": str, "max_tokens": int, "temperature": float,
        "lora": str, ...} -> completions response."""
        eng, req = self._submit(request)
        while not req.event.wait(timeout=1.0):
            if self._error is not None:
                raise RuntimeError("llm engine loop died") from self._error
        if self._error is not None and not req.done:
            raise RuntimeError("llm engine loop died") from self._error
        out = eng._result(req)
        text = out["text"]
        if request.get("echo"):
            # OpenAI echo: the completion text is prompt + generation
            prompt = request.get("prompt", "")
            text = (prompt if isinstance(prompt, str)
                    else eng.tokenizer.decode(list(prompt))) + text
        choice = {
            "text": text,
            "finish_reason": out["finish_reason"],
            "index": 0,
        }
        if out.get("logprobs") is not None:
            # chosen-token logprobs (top-N alternatives not reported —
            # SamplingParams.logprobs docstring)
            choice["logprobs"] = {
                "tokens": [eng.tokenizer.decode([t])
                           for t in out["token_ids"]],
                "token_logprobs": out["logprobs"],
                "top_logprobs": None,
            }
        return {
            "object": "text_completion",
            "model": self.model_id,
            "choices": [choice],
            "usage": {
                "prompt_tokens": out["prompt_tokens"],
                "completion_tokens": len(out["token_ids"]),
            },
        }

    def completions_stream(self, request: dict):
        """Generator of token-delta dicts while the engine decodes
        (reference: the streaming response path of llm_server.py; pairs
        with handle.options(stream=True) / the SSE proxy path)."""
        import time as _time
        eng, req = self._submit(request)
        sent = 0
        last_text = ""
        while True:
            if self._error is not None and not req.done:
                raise RuntimeError("llm engine loop died") from self._error
            n = len(req.out_ids)
            if n > sent:
                text = eng.tokenizer.decode(list(req.out_ids))
                delta, last_text = text[len(last_text):], text
                sent = n
                if delta:
                    yield {"object": "text_completion.chunk",
                           "model": self.model_id,
                           "choices": [{"text": delta, "index": 0,
                                        "finish_reason": None}]}
            if req.done:
                break
            req.event.wait(timeout=0.02)
        out = eng._result(req)
        tail = out["text"][len(last_text):]
        yield {"object": "text_completion.chunk", "model": self.model_id,
               "choices": [{"text": tail, "index": 0,
                            "finish_reason": out["finish_reason"]}]}

    def set_replica_handle(self, handle) -> None:
        """Controller-injected handle to THIS replica's actor: the value
        every prefix-directory entry carries, so peer replicas can call
        export_prefix on the owner."""
        if self._prefix_dir is not None:
            self._prefix_dir.set_replica_handle(handle)

    def export_prefix(self, hashes):
        """Serve a peer replica's cross-replica prefix import: gather
        the cached KV pages for `hashes` (a chain run) to host arrays.
        None when nothing is cached any more — the caller treats the
        directory entry as stale and prefills cold."""
        if not getattr(self.engine, "_prefix_on", False):
            return None
        with self._steplock:
            return self.engine.export_prefix(list(hashes))

    def engine_stats(self) -> dict:
        """Counter snapshot for ops introspection: the base engine's
        stats dict plus the resolved mesh axis sizes (None single-chip).
        On a mesh, ``mesh_reshard_bytes`` staying 0 IS the steady-state
        zero-involuntary-reshard invariant — a nonzero value means some
        dispatch committed a buffer off its pinned sharding."""
        st = dict(getattr(self.engine, "stats", {}) or {})
        mesh = getattr(self.engine, "mesh", None)
        st["mesh"] = None if mesh is None else {
            k: int(v) for k, v in mesh.shape.items()}
        return st

    def loaded_loras(self) -> list:
        """Resident adapters: merged-engine ids plus the slot table's
        (adapter_id, version) pairs."""
        out = list(self._lora_engines)
        if self._multilora is not None:
            out.extend(f"{aid}@{v}" for aid, v in
                       self._multilora.resident().values())
        return out

    def __call__(self, request: dict) -> dict:
        return self.completions(request or {})

    def check_health(self):
        if self._error is not None or not self._thread.is_alive():
            raise RuntimeError("engine loop died") from self._error


def build_llm_deployment(cfg: LLMConfig, params_ref=None):
    """LLMConfig -> a Serve Application (reference:
    build_openai_app / LLMDeployment, llm_server.py:704)."""
    from .. import serve
    dep = serve.deployment(
        LLMServer,
        name=f"llm:{cfg.model_id}",
        num_replicas=cfg.num_replicas,
        max_ongoing_requests=cfg.max_ongoing_requests,
        ray_actor_options=(
            {"num_tpus": cfg.tpus_per_replica}
            if cfg.tpus_per_replica else {}),
    )
    return dep.bind(cfg, params_ref)
