"""serve.llm analog: the engine behind a Serve deployment.

Reference parity: llm/_internal/serve/deployments/llm/llm_server.py:409
(LLMServer — async request intake feeding the engine loop) and :704
(LLMDeployment — the Serve wrapper); router surface matches the OpenAI
completions shape the reference's router exposes.

TPU note (reference analog: LLMConfig -> PG bundles for TP×PP workers,
configs/server_models.py:391-415): the engine's model runs under the current
process's mesh; multi-chip TP serving shards the same jitted programs over a
tp axis — replicas gang-schedule via the deployment's ray_actor_options
TPU resources.
"""
from __future__ import annotations

import dataclasses
import threading
from typing import Any, Optional

from .engine import EngineConfig, InferenceEngine, SamplingParams
from .paged_engine import PagedEngineConfig, PagedInferenceEngine


@dataclasses.dataclass
class LLMConfig:
    """(reference: llm/_internal/serve/configs/server_models.py LLMConfig)

    `engine` may be an EngineConfig (dense slot cache) or a
    PagedEngineConfig (paged-KV continuous batching — the production path);
    the default is paged."""
    model_id: str = "llama-tiny"
    engine: Optional[EngineConfig | PagedEngineConfig] = None
    num_replicas: int = 1
    max_ongoing_requests: int = 64
    tpus_per_replica: float = 0.0


class LLMServer:
    """Deployment callable: background engine thread + request futures
    (reference: llm_server.py:409)."""

    def __init__(self, cfg: LLMConfig, params_ref=None):
        from ..models import llama
        engine_cfg = cfg.engine or PagedEngineConfig(
            model=llama.llama_tiny())
        params = None
        if params_ref is not None:
            import ray_tpu
            params = ray_tpu.get(params_ref)
        if isinstance(engine_cfg, PagedEngineConfig):
            self.engine = PagedInferenceEngine(engine_cfg, params)
        else:
            self.engine = InferenceEngine(engine_cfg, params)
        self.model_id = cfg.model_id
        self._wake = threading.Event()
        self._stop = False
        self._error: Optional[BaseException] = None
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self):
        try:
            while not self._stop:
                if self.engine.has_work():
                    self.engine.step()
                else:
                    self._wake.wait(timeout=0.05)
                    self._wake.clear()
        except BaseException as e:  # noqa: BLE001 — engine died: fail fast
            self._error = e
            # unblock every waiter; completions() re-raises the error, and
            # check_health makes the controller replace this replica
            for req in (list(self.engine._active.values())
                        + list(self.engine._pending)
                        + list(getattr(self.engine, "_prefilling", []))):
                req.event.set()

    # -- OpenAI-ish surface ------------------------------------------------

    def completions(self, request: dict) -> dict:
        """{"prompt": str, "max_tokens": int, "temperature": float, ...}
        -> completions response."""
        prompt = request.get("prompt", "")
        sp = SamplingParams(
            max_tokens=int(request.get("max_tokens", 64)),
            temperature=float(request.get("temperature", 0.0)),
            top_k=int(request.get("top_k", 0)),
        )
        req = self.engine.submit(prompt, sp)
        self._wake.set()
        while not req.event.wait(timeout=1.0):
            if self._error is not None:
                raise RuntimeError("llm engine loop died") from self._error
        if self._error is not None and not req.done:
            raise RuntimeError("llm engine loop died") from self._error
        out = self.engine._result(req)
        return {
            "object": "text_completion",
            "model": self.model_id,
            "choices": [{
                "text": out["text"],
                "finish_reason": out["finish_reason"],
                "index": 0,
            }],
            "usage": {
                "prompt_tokens": out["prompt_tokens"],
                "completion_tokens": len(out["token_ids"]),
            },
        }

    def __call__(self, request: dict) -> dict:
        return self.completions(request or {})

    def check_health(self):
        if self._error is not None or not self._thread.is_alive():
            raise RuntimeError("engine loop died") from self._error


def build_llm_deployment(cfg: LLMConfig, params_ref=None):
    """LLMConfig -> a Serve Application (reference:
    build_openai_app / LLMDeployment, llm_server.py:704)."""
    from .. import serve
    dep = serve.deployment(
        LLMServer,
        name=f"llm:{cfg.model_id}",
        num_replicas=cfg.num_replicas,
        max_ongoing_requests=cfg.max_ongoing_requests,
        ray_actor_options=(
            {"num_tpus": cfg.tpus_per_replica}
            if cfg.tpus_per_replica else {}),
    )
    return dep.bind(cfg, params_ref)
