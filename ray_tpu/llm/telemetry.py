"""Engine telemetry: the serving hot path rendered measurable.

Reference role: vLLM's Stats/StatLogger pipeline (engine-loop iteration
stats feeding Prometheus) and the reference serve deployments' per-request
metrics. Orca/vLLM-class continuous-batching systems are tuned almost
entirely off TTFT / inter-token-latency / KV-utilization telemetry; these
hooks put those series on the head's `/metrics` via the existing
util/metrics.py delta-flush — zero new transport, and a no-op overhead of
a few dict updates per engine step.

Every metric carries an ``engine`` label ("paged" / "dense") so mixed
deployments stay separable; gauges additionally carry a ``proc``
(host:pid) label because they are last-write-wins on the head — without
it, replicas of the same engine kind would overwrite each other. When tracing is enabled each request also
emits one ``llm.request`` span parented to whatever span submitted it
(the serve replica's task span when the request came through Serve), so
a proxy -> replica -> engine request renders as one stitched tree in
``ray_tpu.timeline()``.

Metric names (all prefixed ``rtpu_llm_``):
  ttft_seconds           histogram  submit -> first generated token
  inter_token_seconds    histogram  mean gap between generated tokens
  queue_wait_seconds     histogram  submit -> admission into the batch
  e2e_seconds            histogram  submit -> request retired
  batch_occupancy        gauge      active slots / max_batch_size
  kv_utilization         gauge      KV pages in use / pool size (paged)
  pending_requests       gauge      submitted, not yet admitted
  prefilling_requests    gauge      admitted, prompt not fully prefilled
  decoding_requests      gauge      in the decode set
  tokens_generated_total counter    generated tokens
  requests_total         counter    retired requests, by finish label
  preemptions_total      counter    requests finished early (KV pool dry)
  spec_proposed_total    counter    speculative tokens proposed
  spec_accepted_total    counter    speculative tokens accepted
  dispatches_total       counter    device dispatches, by program family
  prefix_cache_hits_total      counter  full prompt pages served from cache
  prefix_cache_misses_total    counter  full prompt pages computed by prefill
  prefix_cache_evictions_total counter  cached pages reclaimed under pressure
  prefix_cache_tokens_saved_total counter  prompt tokens whose prefill was
      skipped via cached pages
  prefix_cached_pages    gauge      unreferenced pages retained for reuse
  prefix_cache_hit_rate  gauge      hits / (hits + misses), cumulative
  prefix_cache_imported_pages_total counter  pages seeded from another
      replica's export (cross-replica prefix sharing)
  prefix_cache_exported_pages_total counter  cached pages gathered to host
      for another replica's import

Cache heat plane (llm/chainstats.py) — per-chain series, bounded to the
engine's top-K chains plus the ``__overflow__`` sink so label
cardinality can never follow prompt diversity:
  prefix_chain_hits         gauge  cumulative page hits, per hot chain
  prefix_chain_tokens_saved gauge  prompt tokens skipped, per hot chain
  prefix_chain_resident_pages gauge  pages of the chain now in HBM
  prefix_chain_last_hit_age_s gauge  seconds since the chain last hit
  prefix_chain_tracked      gauge  chains with dedicated slots (rollup)

The prefix gauges and the fleet rollup both read
``engine.prefix_accounting()`` — the single accounting source shared
with ``pool_stats()`` — so surfaces cannot drift apart.
"""
from __future__ import annotations

import functools
import os
import time
from typing import Optional

from ..util.metrics import (LATENCY_BUCKETS, Counter, Gauge, Histogram,
                            cached_metric)


def _hist(name, desc, boundaries=LATENCY_BUCKETS):
    return cached_metric(Histogram, name, desc, boundaries=boundaries,
                         tag_keys=("engine",))


def _gauge(name, desc):
    # gauges carry a per-process label: they are last-write-wins on the
    # head, so two replicas of the same engine kind flushing under one
    # key would mask each other (a saturated replica's kv_utilization
    # hidden by an idle one). Counters/histograms sum deltas and stay
    # engine-keyed.
    return cached_metric(Gauge, name, desc, tag_keys=("engine", "proc"))


_proc_pid = None
_proc_label = ""


def _proc() -> str:
    """host:pid, re-derived after fork so a worker never inherits the
    parent's identity."""
    global _proc_pid, _proc_label
    pid = os.getpid()
    if pid != _proc_pid:
        import socket
        _proc_pid = pid
        _proc_label = f"{socket.gethostname()}:{pid}"
    return _proc_label


def _counter(name, desc, tag_keys=("engine",)):
    return cached_metric(Counter, name, desc, tag_keys=tag_keys)


def zero_proc_gauges() -> None:
    """Exit-path hook (core/worker.py): zero this process's per-proc
    gauge series before the final flush, so a downscaled replica's last
    values don't pin /metrics and metrics_summary()'s max aggregation
    forever. Best-effort — a SIGKILLed replica skips it."""
    try:
        from ..util import metrics as um
        um.zero_gauges(("proc", _proc()))
    except Exception:
        pass  # lost telemetry on exit is acceptable


def _never_raise(fn):
    """These hooks sit inside the engine step loop and submit path; an
    exception here (e.g. a user metric registered under a colliding
    name) must degrade to lost telemetry, never kill the engine thread
    and strand every in-flight request."""
    @functools.wraps(fn)
    def wrapped(*args, **kw):
        try:
            return fn(*args, **kw)
        except Exception:
            pass  # contract: degrade to lost telemetry
    return wrapped


# --------------------------------------------------------------------- #
# hooks (called by engine.py / paged_engine.py)
# --------------------------------------------------------------------- #

@_never_raise
def on_submit(engine, req) -> None:
    """Stamp trace/request identity on the request at intake. Runs on the
    submitter's thread (inside the replica's activated task span when the
    request came through Serve), so the engine loop thread can emit the
    request's span later without any contextvar of its own."""
    req.submit_wall = time.time()
    try:
        from ..util import tracing
        if tracing.tracing_enabled():
            req.trace_ctx = tracing.current_context() or \
                (tracing.new_trace_id(), None)
        from ..serve.context import get_request_context
        req.request_id = get_request_context().request_id
    except Exception:
        pass  # tracing/request context are optional


@_never_raise
def on_admit(engine, req) -> None:
    req.admit_t = time.perf_counter()


@_never_raise
def on_first_token(engine, req) -> None:
    tags = {"engine": engine.telemetry_kind}
    if req.submit_t:
        _hist("rtpu_llm_ttft_seconds",
              "time to first generated token").observe(
            req.first_token_t - req.submit_t, tags=tags)
        if req.admit_t:
            _hist("rtpu_llm_queue_wait_seconds",
                  "submit to batch admission").observe(
                max(req.admit_t - req.submit_t, 0.0), tags=tags)


@_never_raise
def on_finish(engine, req, finish: Optional[str] = None) -> None:
    now = time.perf_counter()
    if finish is None:
        eos = engine._eos_id()
        if eos is not None and eos in req.out_ids:
            finish = "stop"
        elif len(req.out_ids) >= req.params.max_tokens:
            finish = "length"
        else:
            finish = "other"
    tags = {"engine": engine.telemetry_kind}
    _counter("rtpu_llm_requests_total", "retired requests",
             tag_keys=("engine", "finish")).inc(
        1.0, tags={**tags, "finish": finish})
    if req.submit_t:
        _hist("rtpu_llm_e2e_seconds", "submit to retirement").observe(
            now - req.submit_t, tags=tags)
    n = len(req.out_ids)
    if n > 1 and req.first_token_t:
        _hist("rtpu_llm_inter_token_seconds",
              "mean inter-token gap over the request").observe(
            max(now - req.first_token_t, 0.0) / (n - 1), tags=tags)
    _emit_request_span(req)


@_never_raise
def on_preempted(engine) -> None:
    _counter("rtpu_llm_preemptions_total",
             "requests finished early because the KV page pool ran "
             "dry").inc(1.0, tags={"engine": engine.telemetry_kind})


@_never_raise
def on_step(engine) -> None:
    """Per-step gauges + counter deltas from the engine's stats dict.
    Cheap on purpose: a handful of dict updates under one lock, all
    host-side state (never forces a device transfer)."""
    kind = engine.telemetry_kind
    tags = {"engine": kind}
    gtags = {"engine": kind, "proc": _proc()}
    cfg = engine.cfg
    _gauge("rtpu_llm_batch_occupancy",
           "active decode slots / max_batch_size").set(
        len(engine._active) / max(cfg.max_batch_size, 1), tags=gtags)
    _gauge("rtpu_llm_pending_requests",
           "submitted, not yet admitted").set(
        len(engine._pending), tags=gtags)
    _gauge("rtpu_llm_decoding_requests", "requests in the decode set").set(
        len(engine._active), tags=gtags)
    prefilling = getattr(engine, "_prefilling", None)
    if prefilling is not None:
        _gauge("rtpu_llm_prefilling_requests",
               "admitted, prompt not fully prefilled").set(
            len(prefilling), tags=gtags)
    free = getattr(engine, "_free_pages", None)
    if free is not None:
        pool = cfg.num_pages - 1  # page 0 is the write sink
        # cached (unreferenced, prefix-reusable) pages are reclaimable on
        # demand: they count as capacity, not utilization — a warm cache
        # must not read as a saturated pool
        cached = len(getattr(engine, "_cached_lru", ()))
        _gauge("rtpu_llm_kv_utilization",
               "KV pages in use / pool size").set(
            (pool - len(free) - cached) / max(pool, 1), tags=gtags)
        if getattr(engine, "_prefix_on", False):
            # single accounting source (paged_engine.prefix_accounting):
            # the gauges here, pool_stats() and metrics_summary() must
            # agree by construction, not by parallel bookkeeping
            acct = engine.prefix_accounting()
            _gauge("rtpu_llm_prefix_cached_pages",
                   "unreferenced KV pages retained for prefix reuse").set(
                acct["cached_pages"], tags=gtags)
            if acct["hits"] + acct["misses"]:
                _gauge("rtpu_llm_prefix_cache_hit_rate",
                       "prefix cache hits / (hits + misses)").set(
                    acct["hit_rate"], tags=gtags)
            if getattr(engine, "spill", None) is not None:
                # tier-resident gauges: what the host tier holds NOW
                # (same accounting snapshot as the counters above)
                _gauge("rtpu_llm_prefix_spill_resident_pages",
                       "prefix pages resident in the host spill "
                       "tier").set(
                    acct["spill_resident_pages"], tags=gtags)
                _gauge("rtpu_llm_prefix_spill_resident_bytes",
                       "bytes resident in the host spill tier").set(
                    acct["spill_resident_bytes"], tags=gtags)
    stats = getattr(engine, "stats", None)
    if stats:
        _ship_stat_deltas(engine, stats, tags)
    if getattr(engine, "chains", None) is not None:
        _ship_chain_stats(engine, gtags)


_STAT_COUNTERS = (
    ("tokens_out", "rtpu_llm_tokens_generated_total",
     "generated tokens", None),
    ("spec_proposed", "rtpu_llm_spec_proposed_total",
     "speculative draft tokens proposed", None),
    ("spec_accepted", "rtpu_llm_spec_accepted_total",
     "speculative draft tokens accepted", None),
    ("prefill_dispatches", "rtpu_llm_dispatches_total",
     "device dispatches by program family", "prefill"),
    ("decode_dispatches", "rtpu_llm_dispatches_total",
     "device dispatches by program family", "decode"),
    ("spec_dispatches", "rtpu_llm_dispatches_total",
     "device dispatches by program family", "verify"),
    ("prefix_hits", "rtpu_llm_prefix_cache_hits_total",
     "full prompt pages served from the prefix cache", None),
    ("prefix_misses", "rtpu_llm_prefix_cache_misses_total",
     "full prompt pages computed by prefill", None),
    ("prefix_evictions", "rtpu_llm_prefix_cache_evictions_total",
     "cached pages reclaimed under allocation pressure", None),
    ("prefix_tokens_saved", "rtpu_llm_prefix_cache_tokens_saved_total",
     "prompt tokens whose prefill was skipped via cached pages", None),
    ("prefix_imported_pages", "rtpu_llm_prefix_cache_imported_pages_total",
     "pages seeded from another replica's export", None),
    ("prefix_exported_pages", "rtpu_llm_prefix_cache_exported_pages_total",
     "cached pages gathered to host for another replica", None),
    # spill tier (cfg.kv_spill, llm/tiering.py) — the
    # rtpu_llm_prefix_spill_* family; engine.stats is the single source
    ("spill_pages", "rtpu_llm_prefix_spill_pages_total",
     "evicted prefix pages captured into the host spill tier", None),
    ("spill_bytes", "rtpu_llm_prefix_spill_bytes_total",
     "bytes demoted into the host spill tier", None),
    ("spill_demotions", "rtpu_llm_prefix_spill_demotions_total",
     "eviction-site demote decisions that kept a tier copy "
     "(captures plus clean re-evictions of tier-resident content)",
     None),
    ("spill_promotions", "rtpu_llm_prefix_spill_promotions_total",
     "spilled pages promoted back into HBM (admission-time, re-warm, "
     "or cross-replica via the prefix directory)", None),
    ("spill_expired", "rtpu_llm_prefix_spill_expired_total",
     "tier pages expired under the byte budget or at teardown", None),
    ("spill_drops", "rtpu_llm_prefix_spill_drops_total",
     "validate-on-promote failures: stale/corrupt spill content "
     "dropped, request prefilled cold", None),
    # mesh-parallel engine (cfg.mesh): the zero-involuntary-reshard
    # contract is that reshard_bytes stays 0 while input/output bytes
    # track exactly the declared host arrays (token ids in, tokens out)
    ("mesh_dispatches", "rtpu_llm_mesh_dispatches_total",
     "device dispatches executed under a sharded mesh", None),
    ("mesh_input_bytes", "rtpu_llm_mesh_input_bytes_total",
     "declared host->mesh input bytes (token ids, block tables)", None),
    ("mesh_output_bytes", "rtpu_llm_mesh_output_bytes_total",
     "declared mesh->host output bytes (sampled tokens, logprobs)",
     None),
    ("mesh_reshard_bytes", "rtpu_llm_mesh_reshard_bytes_total",
     "bytes of committed buffers found off their pinned sharding "
     "after a dispatch (must stay 0)", None),
)


def _ship_stat_deltas(engine, stats: dict, tags: dict) -> None:
    last = getattr(engine, "_telem_shipped", None)
    if last is None:
        last = engine._telem_shipped = {}
    for key, name, desc, family in _STAT_COUNTERS:
        cur = stats.get(key)
        if cur is None:
            continue
        delta = cur - last.get(key, 0)
        if delta <= 0:
            continue
        last[key] = cur
        if family is None:
            _counter(name, desc).inc(float(delta), tags=tags)
        else:
            _counter(name, desc, tag_keys=("engine", "family")).inc(
                float(delta), tags={**tags, "family": family})


def _chain_gauge(name, desc):
    # per-chain gauges: the `chain` label values come verbatim from the
    # ChainStatsTable's slot identities (minted once, at most
    # chain_stats_slots of them, plus __overflow__), so the series set
    # stays bounded no matter how diverse client prompts are
    return cached_metric(Gauge, name, desc,
                         tag_keys=("engine", "proc", "chain"))


#: seconds between chain-gauge publishes. The per-chain table updates at
#: O(1) on the hot path; only this snapshot walk is rate-limited.
_CHAIN_SHIP_INTERVAL_S = 2.0


def _ship_chain_stats(engine, gtags: dict) -> None:
    """Publish the engine's top-K hot chains (+ overflow sink) as
    per-chain gauges. Gauge semantics fit: per-chain values are
    last-write-wins snapshots of cumulative table counters, and a
    replica's series zero out with the other proc gauges on exit."""
    now = time.monotonic()
    last = getattr(engine, "_chain_ship_t", 0.0)
    if now - last < _CHAIN_SHIP_INTERVAL_S:
        return
    engine._chain_ship_t = now
    rows = engine.chains.top(engine.cfg.chain_stats_top_k, now)
    for row in rows:
        ctags = {**gtags, "chain": row["chain"]}
        _chain_gauge("rtpu_llm_prefix_chain_hits",
                     "cumulative prefix-cache page hits, per hot "
                     "chain").set(row["hits"], tags=ctags)
        _chain_gauge("rtpu_llm_prefix_chain_tokens_saved",
                     "prompt tokens whose prefill was skipped, per hot "
                     "chain").set(row["tokens_saved"], tags=ctags)
        _chain_gauge("rtpu_llm_prefix_chain_resident_pages",
                     "KV pages of the chain currently in HBM").set(
            row["resident_pages"], tags=ctags)
        age = row["last_hit_age_s"]
        if age is not None:
            _chain_gauge("rtpu_llm_prefix_chain_last_hit_age_s",
                         "seconds since the chain last served a "
                         "hit").set(age, tags=ctags)
    _gauge("rtpu_llm_prefix_chain_tracked",
           "chains holding dedicated heat-table slots").set(
        engine.chains.stats()["tracked"], tags=gtags)


# --------------------------------------------------------------------- #
# multi-LoRA (llm/multilora) — the rtpu_llm_lora_* family
# --------------------------------------------------------------------- #
#   lora_requests_total        counter  adapter-routed requests resolved
#   lora_hits_total            counter  resolves served by a resident slot
#   lora_loads_total           counter  cold slot loads (registry fetch +
#       device scatter)
#   lora_evictions_total       counter  LRU slots reclaimed for a load
#   lora_swaps_total           counter  hot-swaps: a newer version loaded
#       while an older one stayed resident (pinned by in-flight requests)
#   lora_publishes_total       counter  registry publishes, by namespace
#   lora_resident_adapters     gauge    slots currently holding an adapter

def lora_publishes() -> Counter:
    return _counter("rtpu_llm_lora_publishes_total",
                    "adapter versions published to the registry",
                    tag_keys=("namespace",))


_LORA_COUNTERS = (
    ("requests", "rtpu_llm_lora_requests_total",
     "requests resolved to an adapter slot"),
    ("hits", "rtpu_llm_lora_hits_total",
     "adapter resolves served by an already-resident slot"),
    ("loads", "rtpu_llm_lora_loads_total",
     "cold adapter loads into the slot table"),
    ("evictions", "rtpu_llm_lora_evictions_total",
     "resident slots LRU-reclaimed to load another adapter"),
    ("swaps", "rtpu_llm_lora_swaps_total",
     "hot-swaps (newer version loaded beside a pinned older one)"),
)


@_never_raise
def on_lora_stats(manager) -> None:
    """Ship the manager's counter deltas + residency gauge (called on
    every resolve — a handful of dict updates, same budget as
    on_step)."""
    last = getattr(manager, "_telem_shipped", None)
    if last is None:
        last = manager._telem_shipped = {}
    for key, name, desc in _LORA_COUNTERS:
        cur = manager.stats.get(key, 0)
        delta = cur - last.get(key, 0)
        if delta > 0:
            last[key] = cur
            _counter(name, desc).inc(float(delta), tags={"engine": "paged"})
    _gauge("rtpu_llm_lora_resident_adapters",
           "slot-table rows currently holding an adapter").set(
        float(len(manager._resident)),
        tags={"engine": "paged", "proc": _proc()})


def _emit_request_span(req) -> None:
    ctx: Optional[tuple] = getattr(req, "trace_ctx", None)
    if ctx is None:
        return
    try:
        from ..util import tracing
        trace_id, parent_id = ctx
        rec = {"trace_id": trace_id, "span_id": tracing.new_span_id(),
               "parent_id": parent_id, "name": "llm.request",
               "start_s": req.submit_wall,
               "dur_s": max(time.time() - req.submit_wall, 0.0)}
        if getattr(req, "request_id", ""):
            rec["request_id"] = req.request_id
        tracing.record_span(rec)
    except Exception:
        pass  # span loss must never break retire
