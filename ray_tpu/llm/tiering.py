"""Tiered KV-cache: the host spill tier behind the paged prefix cache.

PR 14 shipped the cache heat plane — per-chain hit/eviction/last-hit
history (llm/chainstats.py) — as pure observation. This module is the
policy+storage half those signals were built to drive: when a
refcount-0 cached page falls off the engine's LRU pool, instead of
freeing the KV outright the engine *demotes* a host copy into a
``SpillTier`` (heat-gated by ``SpillPolicy``), and a later request
whose prompt chains into spilled pages *promotes* them back into HBM
at admission time, before any cold prefill. The serving layer then
makes the tier cluster-visible: staged pages are packed into
``export_prefix``-format payloads, put into the host object store, and
registered in the cluster prefix directory as ``spill:<hash hex>``
entries beside the heat summaries — so ANY replica can re-import a
prefix that NO replica still holds in device memory.

Tier mechanics:

- **demote** (engine, eviction site): the page's KV is gathered to
  host numpy *before* the page id is handed back to the allocator —
  after that the device page gets overwritten. A page is captured at
  most once per content hash; re-evictions of content already in the
  tier only refresh recency (a "clean" eviction, vLLM-style).
- **staged → stored**: captured pages start *staged* (host arrays in
  this process). The replica's engine loop batches staged pages per
  chain into one export-format payload and ``ray_tpu.put``s it —
  *stored* entries keep only the ObjectRef + row index. Refs are held
  by the tier, so the store payload is refcounted and owner-swept on
  replica death: spill can never leak the store. Without a cluster
  runtime the tier simply stays staged — same budget, same promote
  path, zero dependencies (bench/long-tail and unit tests run so).
- **promote**: ``payload_for(hashes)`` rebuilds an export-format
  payload for a consecutive hash run from staged arrays and/or fetched
  store segments; the engine scatters it through the same donated
  ``_import_fn`` as ``import_prefix``, so a promoted page is
  bit-identical to a never-evicted one.
- **budget**: tier bytes are capped by ``kv_spill_max_bytes``; over
  budget, the policy ranks victims coldest-first from the live
  ChainStatsTable (hits, then last-hit recency, then demote order) and
  expires them. Expiry/teardown drop segment refs as their last member
  leaves.

Iron invariant (the module's failure model): every tier entry and
every ``spill:`` directory row is a HINT. Validate-on-promote — a
payload whose hashes or page geometry don't match the request's chain
is dropped (counted ``spill_drops``) and the request prefills cold. A
stale or lost spill entry can cost latency, never correctness.
"""
from __future__ import annotations

import dataclasses
from collections import OrderedDict
from typing import Any, Optional

import numpy as np


@dataclasses.dataclass
class SpillPolicy:
    """Heat-driven demote/expire/re-warm decisions, read from the PR 14
    ChainStatsTable. The default knobs admit everything and let the
    byte budget govern — at long-tail working sets the cheapest page to
    re-create is the one you never dropped — while ``min_hits`` /
    ``max_idle_s`` let deployments refuse tier residence to one-shot or
    long-idle chains outright."""

    #: chains with fewer lifetime cache hits than this are freed, not
    #: spilled (0 = spill on first eviction: the long tail's first
    #: revisit is exactly the hit the tier exists to catch)
    min_hits: int = 0
    #: > 0: chains idle longer than this many seconds demote to the
    #: floor (freed instead of spilled)
    max_idle_s: float = 0.0
    #: proactive re-warm: only chains with at least this many hits are
    #: worth device pages before a request asks for them
    rewarm_min_hits: int = 1
    #: re-warm only while at least this fraction of the pool is free —
    #: warming must never evict, only fill idle headroom
    rewarm_free_frac: float = 0.5

    def admit(self, chains, slot: Optional[int], now: float) -> bool:
        """Spill-vs-free at the eviction site. No table or no learned
        chain means no signal — admit, and let the budget expire it
        coldest-first."""
        if chains is None or not slot:
            return True
        if self.min_hits > 0 and int(chains.hits[slot]) < self.min_hits:
            return False
        if self.max_idle_s > 0 and chains.last_hit[slot] and \
                now - chains.last_hit[slot] > self.max_idle_s:
            return False
        return True

    def victim_key(self, entry: "_SpilledPage", chains, now: float):
        """Sort key for budget expiry: lowest expires first. Cold
        chains (few hits, stale last-hit) go before hot ones; within a
        chain, demote order (FIFO) breaks ties."""
        if chains is None or not entry.chain:
            return (0, 0.0, entry.seq)
        return (int(chains.hits[entry.chain]),
                float(chains.last_hit[entry.chain]), entry.seq)

    def rewarm_slot(self, chains, spilled_slots, free_frac: float):
        """The chain most worth proactively promoting — hottest spilled
        chain above ``rewarm_min_hits`` — or None when the pool lacks
        idle headroom or nothing qualifies. ``spilled_slots`` is the
        set of chain slots with pages resident in the tier."""
        if chains is None or free_frac < self.rewarm_free_frac:
            return None
        best, best_hits = None, self.rewarm_min_hits - 1
        for s in spilled_slots:
            if s and int(chains.hits[s]) > best_hits:
                best, best_hits = s, int(chains.hits[s])
        return best


class _SpilledPage:
    """One demoted page: chain attribution + either staged host arrays
    (ks/vs, one per layer) or a pointer into a stored segment."""

    __slots__ = ("chain", "seq", "ks", "vs", "seg", "row")

    def __init__(self, chain: int, seq: int, ks, vs):
        self.chain = chain
        self.seq = seq
        self.ks = ks            # staged: list[np.ndarray] per layer
        self.vs = vs
        self.seg: Optional[str] = None   # stored: segment id
        self.row: int = -1               # row inside the segment payload


class _Segment:
    """One store payload holding several pages of one chain. The ref is
    the ONLY pin on the payload: dropping it (expiry of the last
    member, teardown, replica death) frees the store object."""

    __slots__ = ("ref", "hashes", "live")

    def __init__(self, ref, hashes: list):
        self.ref = ref
        self.hashes = list(hashes)
        self.live = set(hashes)


class SpillTier:
    """Hash-keyed host tier for demoted prefix pages, byte-budgeted.

    NOT thread-safe by itself: demote/promote run on the engine's
    stepping thread under its pool lock, and the serving loop's
    materialize/publish runs on that same thread — the identical
    serialization contract as the engine structures it shadows. The
    cross-replica READ path never touches a peer's SpillTier object;
    it fetches the refcounted store payload directly."""

    def __init__(self, max_bytes: int, page_nbytes: int,
                 policy: Optional[SpillPolicy] = None):
        self.max_bytes = int(max_bytes)
        self.page_nbytes = max(int(page_nbytes), 1)
        self.policy = policy or SpillPolicy()
        # insertion order = demote order (the FIFO tie-break)
        self._pages: "OrderedDict[bytes, _SpilledPage]" = OrderedDict()
        self._segs: dict[str, _Segment] = {}
        self._seq = 0
        self._next_seg = 0
        self.resident_bytes = 0
        # directory publish deltas (drained by the serving loop)
        self._pub_new: list[bytes] = []
        self._pub_gone: list[bytes] = []
        # the live ChainStatsTable the expiry ranking reads (None = no
        # heat plane; FIFO order governs). Injected by the engine so
        # the tier never imports engine internals.
        self._chains_ref: Any = None

    # -- capacity ------------------------------------------------------

    def resident_pages(self) -> int:
        return len(self._pages)

    def has(self, h: bytes) -> bool:
        return h in self._pages

    def spilled_slots(self) -> set:
        return {e.chain for e in self._pages.values()}

    # -- demote side ---------------------------------------------------

    def touch(self, h: bytes) -> None:
        """Recency refresh for a re-eviction of content already in the
        tier (the page was promoted or re-computed, then evicted again
        — a clean eviction, nothing to copy)."""
        e = self._pages.get(h)
        if e is not None:
            self._seq += 1
            e.seq = self._seq

    def add(self, h: bytes, chain: int, ks, vs,
            now: float = 0.0) -> list:
        """Stage a captured page. Returns the entries expired to fit
        the budget as ``[(hash, chain), ...]`` so the caller can keep
        chain accounting exact. A page larger than the whole budget is
        refused (returned as its own expiry)."""
        if self.page_nbytes > self.max_bytes:
            return [(h, chain)]
        self._seq += 1
        self._pages[h] = _SpilledPage(chain, self._seq, ks, vs)
        self.resident_bytes += self.page_nbytes
        self._pub_new.append(h)
        expired = []
        if self.resident_bytes > self.max_bytes:
            expired = self._expire_over_budget(now, protect=h)
        return expired

    def _expire_over_budget(self, now: float, protect: bytes) -> list:
        chains = self._chains_ref
        order = sorted(
            ((self.policy.victim_key(e, chains, now), hh)
             for hh, e in self._pages.items() if hh != protect))
        out = []
        for _key, hh in order:
            if self.resident_bytes <= self.max_bytes:
                break
            out.append((hh, self._pages[hh].chain))
            self._drop(hh)
        return out

    def bind_chains(self, chains) -> None:
        self._chains_ref = chains

    def _drop(self, h: bytes) -> None:
        e = self._pages.pop(h, None)
        if e is None:
            return
        self.resident_bytes -= self.page_nbytes
        self._pub_gone.append(h)
        if e.seg is not None:
            seg = self._segs.get(e.seg)
            if seg is not None:
                seg.live.discard(h)
                if not seg.live:
                    del self._segs[e.seg]   # last member: drop the ref
        else:
            e.ks = e.vs = None

    def discard(self, hashes) -> list:
        """Drop entries outright (validate-on-promote failures, expiry
        sweeps). Returns ``[(hash, chain), ...]`` actually removed."""
        out = []
        for h in hashes:
            e = self._pages.get(h)
            if e is not None:
                out.append((h, e.chain))
                self._drop(h)
        return out

    def clear(self) -> list:
        """Teardown: drop everything (and thus every segment ref) so
        the store drains to exact baseline. Returns removed entries
        for accounting, like discard()."""
        return self.discard(list(self._pages))

    # -- promote side --------------------------------------------------

    def chain_of(self, h: bytes) -> int:
        e = self._pages.get(h)
        return e.chain if e is not None else 0

    def covered_run(self, hashes) -> int:
        """How many consecutive hashes from the front the tier holds."""
        n = 0
        for h in hashes:
            if h not in self._pages:
                break
            n += 1
        return n

    def payload_for(self, hashes, page_size: int, fetch=None) -> tuple:
        """-> (payload, dropped). Export-format payload for a
        consecutive run of tier-resident hashes — None when nothing
        usable (caller prefills cold). ``dropped`` lists the
        ``(hash, chain)`` entries purged by validate-on-promote
        (stale/corrupt tier content; caller counts them). ``fetch``
        resolves a stored segment's ref to its payload (ray_tpu.get
        under the serving layer; None = staged-only, the engine-local
        default — stored entries just end the run there)."""
        rows: list = []           # (hash, list[k_layer], list[v_layer])
        seg_cache: dict[str, Any] = {}
        bad: list[bytes] = []
        for h in hashes:
            e = self._pages.get(h)
            if e is None:
                break
            if e.seg is None:
                if e.ks is None or e.vs is None:
                    bad.append(h)
                    break
                rows.append((h, e.ks, e.vs))
                continue
            seg = self._segs.get(e.seg)
            payload = seg_cache.get(e.seg)
            if payload is None:
                if seg is None or fetch is None:
                    break           # stored but unfetchable here: stop
                try:
                    payload = fetch(seg.ref)
                except Exception:
                    payload = None
                if not _payload_ok(payload, page_size):
                    bad.extend(seg.live)
                    break
                seg_cache[e.seg] = payload
            try:
                i = payload["page_hashes"].index(h)
                rows.append((h,
                             [lay["k"][i] for lay in payload["pages"]],
                             [lay["v"][i] for lay in payload["pages"]]))
            except (ValueError, KeyError, IndexError, TypeError):
                bad.append(h)       # segment no longer carries the hash
                break
        if bad:
            # stale/corrupt tier content: purge so the next request
            # doesn't re-validate the same garbage
            return None, self.discard(bad)
        if not rows:
            return None, []
        n_layers = len(rows[0][1])
        shapes = [np.shape(k) for k in rows[0][1]]
        for _h, ks, vs in rows:
            if len(ks) != n_layers or \
                    any(np.shape(k) != s for k, s in zip(ks, shapes)):
                return None, self.discard([_h])  # geometry drift:
                # never scatter it into the live cache pools
        return {
            "page_size": page_size,
            "page_hashes": [r[0] for r in rows],
            "pages": [{"k": np.stack([r[1][li] for r in rows]),
                       "v": np.stack([r[2][li] for r in rows])}
                      for li in range(n_layers)],
        }, []

    # -- cluster materialization (serving loop) ------------------------

    def drain_publish_delta(self) -> tuple:
        """-> (new_hashes, gone_hashes) since the last drain, filtered
        to current residence (an add-then-expire nets out)."""
        if not self._pub_new and not self._pub_gone:
            return (), ()
        new, self._pub_new = self._pub_new, []
        gone, self._pub_gone = self._pub_gone, []
        new = [h for h in dict.fromkeys(new) if h in self._pages]
        gone = [h for h in dict.fromkeys(gone) if h not in self._pages]
        return new, gone

    def requeue_publish(self, hashes) -> None:
        """Put drained hashes back on the new-delta queue — the serving
        loop's retry path when materialization (no store yet, put
        failure) couldn't mint a ref this cadence."""
        self._pub_new.extend(h for h in hashes if h in self._pages)

    def materialize(self, hashes, page_size: int, put) -> dict:
        """Pack still-staged entries among ``hashes`` into one store
        payload per chain via ``put`` (ray_tpu.put under the serving
        layer) and flip them staged→stored, freeing the host copies.
        Returns {hash: ref_binary} for every requested hash resident
        in the tier (already-stored entries report their existing
        segment's ref). Failures leave entries staged — materializing
        is an optimization, never a correctness step."""
        out: dict = {}
        by_chain: dict[int, list] = {}
        for h in hashes:
            e = self._pages.get(h)
            if e is None:
                continue
            if e.seg is not None:
                seg = self._segs.get(e.seg)
                if seg is not None:
                    out[h] = seg.ref.binary()
                continue
            by_chain.setdefault(e.chain, []).append(h)
        for _chain, group in by_chain.items():
            entries = [self._pages[h] for h in group]
            n_layers = len(entries[0].ks)
            payload = {
                "page_size": page_size,
                "page_hashes": list(group),
                "pages": [{"k": np.stack([e.ks[li] for e in entries]),
                           "v": np.stack([e.vs[li] for e in entries])}
                          for li in range(n_layers)],
            }
            try:
                ref = put(payload)
            except Exception:
                continue            # no store today: stay staged
            seg_id = f"s{self._next_seg}"
            self._next_seg += 1
            self._segs[seg_id] = _Segment(ref, group)
            for i, h in enumerate(group):
                e = self._pages[h]
                e.seg, e.row = seg_id, i
                e.ks = e.vs = None
                out[h] = ref.binary()
        return out

    def stats(self) -> dict:
        return {
            "resident_pages": len(self._pages),
            "resident_bytes": self.resident_bytes,
            "max_bytes": self.max_bytes,
            "page_bytes": self.page_nbytes,
            "staged_pages": sum(1 for e in self._pages.values()
                                if e.seg is None),
            "stored_segments": len(self._segs),
        }


def _payload_ok(payload, page_size: int) -> bool:
    """Structural validation of a fetched spill payload — the
    validate-on-promote gate for store-fetched segments."""
    try:
        return (isinstance(payload, dict)
                and payload["page_size"] == page_size
                and isinstance(payload["page_hashes"], list)
                and len(payload["pages"]) > 0)
    except Exception:
        return False
