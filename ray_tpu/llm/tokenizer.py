"""Tokenizers for the LLM stack.

Offline-friendly: the default ByteTokenizer needs no vocab download (the
image has no egress); real deployments pass a HuggingFace tokenizer name or
object (transformers is baked in) via get_tokenizer.
"""
from __future__ import annotations

from typing import Any


class ByteTokenizer:
    """UTF-8 bytes + BOS/EOS: ids 0..255 are bytes, 256=BOS, 257=EOS."""

    vocab_size = 258
    bos_id = 256
    eos_id = 257

    def encode(self, text: str, add_bos: bool = True) -> list[int]:
        ids = list(text.encode("utf-8"))
        return ([self.bos_id] if add_bos else []) + ids

    def decode(self, ids) -> str:
        data = bytes(i for i in ids if 0 <= int(i) < 256)
        return data.decode("utf-8", errors="replace")


def get_tokenizer(spec: Any = None):
    """None -> ByteTokenizer; str -> transformers AutoTokenizer (requires a
    local cache — no egress in CI); object -> used as-is."""
    if spec is None:
        return ByteTokenizer()
    if isinstance(spec, str):
        from transformers import AutoTokenizer
        return AutoTokenizer.from_pretrained(spec)
    return spec
