"""ray_tpu.models — TPU-native model zoo.

The reference ships no model implementations of its own (models live in
torch/vLLM which it orchestrates); this package provides the JAX-native
models the framework's Train/Serve/RLlib stacks run. All models follow the
same contract:

  cfg        — frozen dataclass, hashable (usable as a jit static arg)
  init(rng, cfg)            -> params pytree
  apply(params, inputs, cfg) -> outputs (pure; jit/pjit-friendly)
  logical_axes(cfg)          -> pytree of logical-axis tuples matching params
                                (resolved by parallel.sharding rules)
"""
import importlib

_MODULES = ("llama", "resnet")
__all__ = list(_MODULES)


def __getattr__(name):
    if name in _MODULES:
        return importlib.import_module(f".{name}", __name__)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
