"""Llama-family transformer, TPU-first.

The framework's flagship model (BASELINE.json north star: Llama-3-8B ≥45% MFU
on v5e). Design choices that are TPU-idiomatic rather than ports:

* pure-pytree params + pure functions — everything jit/pjit-friendly;
* `lax.scan` over layers with stacked parameters — O(1) HLO size, fast
  compiles at 80+ layers;
* every weight carries logical sharding axes (parallel.sharding rules map
  them to dp/fsdp/tp/sp mesh axes), activations are constrained at layer
  boundaries so XLA inserts exactly the Megatron-style collectives;
* attention = ops.flash_attention (Pallas on TPU); with an "sp" mesh axis the
  trainer swaps in parallel.ring.ring_attention for long context;
* bf16 params/activations, f32 RMSNorm accumulation and logits.

Decode-time KV caching lives here too (used by the serving engine).
"""
from __future__ import annotations

import dataclasses
import functools
import math
from typing import Any, Optional

import jax
import jax.numpy as jnp

from ..ops.flash_attention import _on_tpu, flash_attention, mha_reference
from ..parallel.sharding import constrain


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 128256
    dim: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    mlp_dim: int = 14336
    max_seq_len: int = 8192
    rope_theta: float = 500000.0
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    # parallel/perf knobs
    remat: bool = True                # jax.checkpoint each layer
    # "full" recomputes everything in the backward; "save_attn" keeps the
    # flash-attention output+lse (ops/flash_attention.py checkpoint_name
    # tags) so attention's forward is NOT replayed — more memory, fewer
    # FLOPs: the right default for MFU on HBM-rich chips
    remat_policy: str = "save_attn"
    use_flash: bool = True            # Pallas flash attention (vs reference)
    attn_block_q: int = 512
    attn_block_k: int = 512
    # mixture-of-experts (0 = dense MLP). Experts shard over the ep mesh
    # axis ("expert" logical axis); dispatch/combine einsums induce the
    # all-to-all when tokens are dp/sp-sharded (SURVEY §2.4 EP row).
    moe_experts: int = 0
    moe_top_k: int = 2
    moe_capacity: float = 2.0         # slots per expert = cap*k*T/E
    moe_aux_weight: float = 0.01      # load-balance loss weight

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    def flops_per_token(self, seq_len: Optional[int] = None) -> float:
        """Approximate training FLOPs/token (fwd+bwd ≈ 6·params + attention)."""
        n_params = self.num_params()
        attn = 12 * self.n_layers * self.dim * (seq_len or self.max_seq_len)
        return 6 * n_params + attn

    def num_params(self) -> int:
        d, v = self.dim, self.vocab_size
        if self.moe_experts:
            mlp = (3 * d * self.mlp_dim * self.moe_experts
                   + d * self.moe_experts)                           # + router
        else:
            mlp = 3 * d * self.mlp_dim
        per_layer = (
            d * d + 2 * d * self.n_kv_heads * self.head_dim + d * d  # qkvo
            + mlp                                                    # (swi)glu
            + 2 * d)                                                 # norms
        return v * d + self.n_layers * per_layer + d + d * v


# Reference-scale presets + test-scale configs.
def llama3_8b(**kw) -> LlamaConfig:
    return LlamaConfig(**kw)


def llama3_70b(**kw) -> LlamaConfig:
    return LlamaConfig(dim=8192, n_layers=80, n_heads=64, n_kv_heads=8,
                       mlp_dim=28672, **kw)


def llama_tiny(**kw) -> LlamaConfig:
    """CI-scale config: same topology, toy sizes."""
    defaults = dict(vocab_size=256, dim=64, n_layers=2, n_heads=4,
                    n_kv_heads=2, mlp_dim=128, max_seq_len=128,
                    dtype=jnp.float32, remat=False)
    defaults.update(kw)
    return LlamaConfig(**defaults)


# ---------------------------------------------------------------------------
# Params
# ---------------------------------------------------------------------------

def init(rng: jax.Array, cfg: LlamaConfig) -> dict:
    """Initialize parameters. Layer weights are stacked on a leading
    n_layers axis (scanned in apply)."""
    k_emb, k_layers, k_out = jax.random.split(rng, 3)
    d, hd = cfg.dim, cfg.head_dim
    kvd = cfg.n_kv_heads * hd
    L = cfg.n_layers

    def norm_init(*shape):
        return jnp.ones(shape, cfg.dtype)

    def dense_init(key, shape, fan_in):
        std = 1.0 / math.sqrt(fan_in)
        return (jax.random.normal(key, shape, jnp.float32) * std).astype(
            cfg.dtype)

    # 7 keys as in the dense-only original; the router key is derived via
    # fold_in so dense init for a given seed is unchanged by the MoE branch
    ks = jax.random.split(k_layers, 7)
    if cfg.moe_experts:
        E = cfg.moe_experts
        mlp = {
            "mlp_norm": norm_init(L, d),
            "w_router": (jax.random.normal(
                jax.random.fold_in(k_layers, 7), (L, d, E),
                jnp.float32) / math.sqrt(d)),
            "w_gate": dense_init(ks[4], (L, E, d, cfg.mlp_dim), d),
            "w_up": dense_init(ks[5], (L, E, d, cfg.mlp_dim), d),
            "w_down": dense_init(ks[6], (L, E, cfg.mlp_dim, d), cfg.mlp_dim),
        }
    else:
        mlp = {
            "mlp_norm": norm_init(L, d),
            "w_gate": dense_init(ks[4], (L, d, cfg.mlp_dim), d),
            "w_up": dense_init(ks[5], (L, d, cfg.mlp_dim), d),
            "w_down": dense_init(ks[6], (L, cfg.mlp_dim, d), cfg.mlp_dim),
        }
    return {
        "embed": dense_init(k_emb, (cfg.vocab_size, d), d),
        "layers": {
            "attn_norm": norm_init(L, d),
            "wq": dense_init(ks[0], (L, d, cfg.n_heads * hd), d),
            "wk": dense_init(ks[1], (L, d, kvd), d),
            "wv": dense_init(ks[2], (L, d, kvd), d),
            "wo": dense_init(ks[3], (L, cfg.n_heads * hd, d), cfg.dim),
            **mlp,
        },
        "final_norm": norm_init(d),
        "lm_head": dense_init(k_out, (d, cfg.vocab_size), d),
    }


def logical_axes(cfg: LlamaConfig) -> dict:
    """Logical sharding axes per param (leading None = scanned layer dim).
    Resolved against the mesh by parallel.sharding.logical_sharding."""
    if cfg.moe_experts:
        mlp = {
            "mlp_norm": (None, "norm"),
            "w_router": (None, "embed", None),
            "w_gate": (None, "expert", "embed", "mlp"),
            "w_up": (None, "expert", "embed", "mlp"),
            "w_down": (None, "expert", "mlp", "embed"),
        }
    else:
        mlp = {
            "mlp_norm": (None, "norm"),
            "w_gate": (None, "embed", "mlp"),
            "w_up": (None, "embed", "mlp"),
            "w_down": (None, "mlp", "embed"),
        }
    return {
        "embed": ("vocab", "embed"),
        "layers": {
            "attn_norm": (None, "norm"),
            "wq": (None, "embed", "heads"),
            "wk": (None, "embed", "heads"),
            "wv": (None, "embed", "heads"),
            "wo": (None, "heads", "embed"),
            **mlp,
        },
        "final_norm": ("norm",),
        "lm_head": ("embed", "vocab"),
    }


# ---------------------------------------------------------------------------
# Building blocks
# ---------------------------------------------------------------------------

def rms_norm(x, w, eps: float):
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    return (xf * jax.lax.rsqrt(var + eps)).astype(x.dtype) * w


def rope_freqs(cfg: LlamaConfig, positions: jax.Array):
    """positions [B, S] -> (cos, sin) each [B, S, head_dim/2], f32."""
    inv = 1.0 / (cfg.rope_theta ** (
        jnp.arange(0, cfg.head_dim, 2, dtype=jnp.float32) / cfg.head_dim))
    ang = positions[..., None].astype(jnp.float32) * inv     # [B,S,hd/2]
    return jnp.cos(ang), jnp.sin(ang)


def apply_rope(x, cos, sin):
    """x [B, S, H, D]; rotate pairs (even, odd interleave by halves)."""
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    c, s = cos[:, :, None, :], sin[:, :, None, :]
    return jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s],
                           axis=-1).astype(x.dtype)


def _attention(q, k, v, cfg: LlamaConfig, causal: bool, attn_impl):
    if attn_impl is not None:
        return attn_impl(q, k, v)
    if cfg.use_flash:
        return flash_attention(q, k, v, causal, None,
                               cfg.attn_block_q, cfg.attn_block_k)
    return mha_reference(q, k, v, causal=causal)


def _qkv(h, p, cfg: LlamaConfig, cos, sin, lora=None, slots=None):
    """Projections + RoPE, shared by every forward mode. h [B, S, D].

    ``lora``/``slots``: optional per-layer adapter slot table
    (_lora_at_layer) and per-row slot ids — the batched multi-LoRA
    serving path adds scale·(h@A[slot])@B[slot] to each projection.
    None (every training/base path) leaves the math untouched."""
    b, s, _ = h.shape
    q = h @ p["wq"]
    k = h @ p["wk"]
    v = h @ p["wv"]
    if lora is not None:
        q = _lora_add(q, h, lora, "wq", slots)
        k = _lora_add(k, h, lora, "wk", slots)
        v = _lora_add(v, h, lora, "wv", slots)
    q = q.reshape(b, s, cfg.n_heads, cfg.head_dim)
    k = k.reshape(b, s, cfg.n_kv_heads, cfg.head_dim)
    v = v.reshape(b, s, cfg.n_kv_heads, cfg.head_dim)
    q = apply_rope(q, cos, sin)
    k = apply_rope(k, cos, sin)
    q = constrain(q, ("batch", "sequence", "heads", "head_dim"))
    k = constrain(k, ("batch", "sequence", "kv_heads", "head_dim"))
    return q, k, v


# ---------------------------------------------------------------------------
# Batched multi-LoRA (llm/multilora): slot-table deltas on the serving paths
# ---------------------------------------------------------------------------
# The slot table is a fixed-shape pytree (llm/multilora/slots.py):
#   "<t>.A" [S, L, in_t, R]  "<t>.B" [S, L, R, out_t]   t in wq/wk/wv/wo
#   "lm_head.A" [S, d, R]    "lm_head.B" [S, R, V]
#   "scale" [S] f32 (alpha/rank per slot; slot 0 = base, all-zero A/B)
# so every dispatch keeps XLA-static shapes no matter which tenants are
# in the batch; per-row `slots` ids select each row's adapter. Padding
# (rank < R, missing targets, slot 0) contributes an exact +0.0, so the
# base path through a lora-enabled program is bit-identical to the
# plain program.

_LORA_LAYER_TARGETS = ("wq", "wk", "wv", "wo")


def _lora_at_layer(lora, layer: int):
    """Slice the [S, L, ...] layer-stacked tables at one layer (static
    index — the serving paths unroll layers in Python)."""
    if lora is None:
        return None
    out = {"scale": lora["scale"]}
    for t in _LORA_LAYER_TARGETS:
        a = lora.get(f"{t}.A")
        if a is not None:
            out[f"{t}.A"] = a[:, layer]
            out[f"{t}.B"] = lora[f"{t}.B"][:, layer]
    return out


def _lora_add(y, x, lora, target: str, slots):
    """y + scale[slot]·(x @ A[slot]) @ B[slot] for one projection.

    x [..., in]; slots is a scalar (single-sequence scan rows: prefill
    chunk / verify) or [B] (batched decode). The low-rank math runs in
    f32 — mirroring lora.merge, which merges in f32 before casting —
    and the delta is cast back to y.dtype. Absent targets return y
    unchanged."""
    a = lora.get(f"{target}.A")
    if a is None:
        return y
    b = lora[f"{target}.B"]
    sc = lora["scale"][slots]
    xf = x.astype(jnp.float32)
    if jnp.ndim(slots) == 0:
        d = ((xf @ a[slots]) @ b[slots]) * sc
    else:
        d = jnp.einsum("bsr,bro->bso",
                       jnp.einsum("bsi,bir->bsr", xf, a[slots]),
                       b[slots]) * sc[:, None, None]
    return y + d.astype(y.dtype)


def _mlp_block(x, p, cfg: LlamaConfig):
    """Post-attention MLP with residual: dense SwiGLU, or top-k MoE when
    cfg.moe_experts > 0 (returns aux=0.0 / load-balance loss)."""
    h = rms_norm(x, p["mlp_norm"], cfg.norm_eps)
    if cfg.moe_experts:
        y, aux = _moe_ffn(h, p, cfg)
        x = x + y
        return constrain(x, ("batch", "sequence", "embed")), aux
    gate = jax.nn.silu(h @ p["w_gate"])
    x = x + (gate * (h @ p["w_up"])) @ p["w_down"]
    return constrain(x, ("batch", "sequence", "embed")), jnp.float32(0.0)


def _moe_ffn(h, p, cfg: LlamaConfig):
    """Top-k expert SwiGLU over capacity-bounded slots (GShard-style dense
    dispatch/combine einsums — static shapes, MXU-friendly; with experts
    sharded over ep and tokens over dp, XLA lowers the dispatch einsum to
    the expert all-to-all). h [B, S, D] -> (out [B, S, D], aux_loss)."""
    b, s, d = h.shape
    E, k = cfg.moe_experts, cfg.moe_top_k
    T = b * s
    C = max(1, int(cfg.moe_capacity * k * T / E))
    ht = h.reshape(T, d)

    logits = ht.astype(jnp.float32) @ p["w_router"]            # [T, E]
    probs = jax.nn.softmax(logits, axis=-1)
    gate_k, idx_k = jax.lax.top_k(probs, k)                    # [T, k]
    gate_k = gate_k / jnp.maximum(
        gate_k.sum(axis=-1, keepdims=True), 1e-9)

    # Switch-style load-balance aux: E * sum(frac_routed * mean_prob)
    me = probs.mean(axis=0)                                    # [E]
    ce = jax.nn.one_hot(idx_k[:, 0], E).mean(axis=0)           # [E]
    aux = E * jnp.sum(me * ce)

    combine = jnp.zeros((T, E, C), jnp.float32)
    prev_counts = jnp.zeros((E,), jnp.int32)
    for j in range(k):                                         # k is tiny
        oh = jax.nn.one_hot(idx_k[:, j], E, dtype=jnp.int32)   # [T, E]
        pos = jnp.cumsum(oh, axis=0) - 1 + prev_counts         # [T, E]
        prev_counts = prev_counts + oh.sum(axis=0)
        in_cap = (pos < C) & (oh > 0)                          # [T, E]
        slot = jax.nn.one_hot(jnp.clip(pos, 0, C - 1), C)      # [T, E, C]
        combine = combine + (gate_k[:, j][:, None, None]
                             * in_cap[..., None] * slot)
    dispatch = (combine > 0).astype(h.dtype)                   # [T, E, C]

    xe = jnp.einsum("tec,td->ecd", dispatch, ht)               # [E, C, D]
    xe = constrain(xe, ("expert", None, "embed"))
    ge = jax.nn.silu(jnp.einsum("ecd,edf->ecf", xe, p["w_gate"]))
    ue = jnp.einsum("ecd,edf->ecf", xe, p["w_up"])
    ye = jnp.einsum("ecf,efd->ecd", ge * ue, p["w_down"])
    ye = constrain(ye, ("expert", None, "embed"))
    out = jnp.einsum("tec,ecd->td", combine.astype(ye.dtype), ye)
    return out.reshape(b, s, d), aux


def _layer(x, layer_params, cfg: LlamaConfig, cos, sin, attn_impl,
           kv_cache=None, cache_idx=None):
    """One transformer block. x [B, S, D]. Returns (x, new_kv) where new_kv
    is None in training mode."""
    p = layer_params
    h = rms_norm(x, p["attn_norm"], cfg.norm_eps)
    b, s, _ = h.shape
    q, k, v = _qkv(h, p, cfg, cos, sin)

    new_kv = None
    if kv_cache is not None:
        ck, cv = kv_cache
        ck = jax.lax.dynamic_update_slice_in_dim(ck, k, cache_idx, axis=1)
        cv = jax.lax.dynamic_update_slice_in_dim(cv, v, cache_idx, axis=1)
        new_kv = (ck, cv)
        # decode: attend over the cache prefix. The causal mask k_pos <=
        # q_pos also hides the not-yet-written cache tail (its positions
        # exceed every query position).
        k_pos = jnp.arange(ck.shape[1])                        # [K]
        q_pos = cache_idx + jnp.arange(s)                      # [S]
        mask = k_pos[None, :] <= q_pos[:, None]                # [S, K]
        groups = cfg.n_heads // cfg.n_kv_heads
        kr = jnp.repeat(ck, groups, axis=2)
        vr = jnp.repeat(cv, groups, axis=2)
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, kr,
                            preferred_element_type=jnp.float32)
        scores = scores * (cfg.head_dim ** -0.5)
        scores = jnp.where(mask[None, None], scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1).astype(vr.dtype)
        attn = jnp.einsum("bhqk,bkhd->bqhd", probs, vr)
    else:
        attn = _attention(q, k, v, cfg, causal=True, attn_impl=attn_impl)

    attn = attn.reshape(b, s, cfg.n_heads * cfg.head_dim)
    x = x + attn @ p["wo"]
    x = constrain(x, ("batch", "sequence", "embed"))
    x, aux = _mlp_block(x, p, cfg)
    return x, aux, new_kv


# ---------------------------------------------------------------------------
# Forward passes
# ---------------------------------------------------------------------------

def apply(params: dict, tokens: jax.Array, cfg: LlamaConfig,
          attn_impl=None) -> jax.Array:
    """Training/prefill forward: tokens [B, S] int32 -> logits [B, S, V] f32.

    `attn_impl(q, k, v)` overrides attention (the trainer passes a
    ring-attention closure when an "sp" axis is active). MoE configs:
    use apply_with_aux to also get the load-balance loss.
    """
    return apply_with_aux(params, tokens, cfg, attn_impl)[0]


def apply_with_aux(params: dict, tokens: jax.Array, cfg: LlamaConfig,
                   attn_impl=None):
    """(logits, aux) — aux is the mean per-layer MoE load-balance loss
    (0.0 for dense configs); add cfg.moe_aux_weight * aux to the loss."""
    x = params["embed"][tokens].astype(cfg.dtype)
    x = constrain(x, ("batch", "sequence", "embed"))
    positions = jnp.broadcast_to(jnp.arange(tokens.shape[1]), tokens.shape)
    cos, sin = rope_freqs(cfg, positions)

    def body(carry, layer_params):
        x, aux = carry
        y, a, _ = _layer(x, layer_params, cfg, cos, sin, attn_impl)
        return (y, aux + a), None

    if cfg.remat:
        if cfg.remat_policy == "save_attn":
            policy = jax.checkpoint_policies.save_only_these_names(
                "flash_out", "flash_lse")
            body = jax.checkpoint(body, policy=policy)
        else:
            body = jax.checkpoint(body)
    (x, aux), _ = jax.lax.scan(body, (x, jnp.float32(0.0)),
                               params["layers"])
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    logits = jnp.einsum("bsd,dv->bsv", x, params["lm_head"],
                        preferred_element_type=jnp.float32)
    return logits, aux / cfg.n_layers


def init_kv_cache(cfg: LlamaConfig, batch: int, max_len: int) -> dict:
    shape = (cfg.n_layers, batch, max_len, cfg.n_kv_heads, cfg.head_dim)
    return {"k": jnp.zeros(shape, cfg.dtype),
            "v": jnp.zeros(shape, cfg.dtype),
            "idx": jnp.zeros((), jnp.int32)}


def apply_decode(params: dict, tokens: jax.Array, cache: dict,
                 cfg: LlamaConfig) -> tuple[jax.Array, dict]:
    """Incremental forward with KV cache: tokens [B, S_step] appended at
    cache['idx']. Returns (logits [B, S_step, V], updated cache)."""
    x = params["embed"][tokens].astype(cfg.dtype)
    positions = cache["idx"] + jnp.broadcast_to(
        jnp.arange(tokens.shape[1]), tokens.shape)
    cos, sin = rope_freqs(cfg, positions)

    def body(x, scanned):
        layer_params, kv = scanned
        y, _, new_kv = _layer(x, layer_params, cfg, cos, sin, None,
                              kv_cache=kv, cache_idx=cache["idx"])
        return y, new_kv

    x, (new_k, new_v) = jax.lax.scan(
        body, x, (params["layers"], (cache["k"], cache["v"])))
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    logits = jnp.einsum("bsd,dv->bsv", x, params["lm_head"],
                        preferred_element_type=jnp.float32)
    new_cache = {"k": new_k, "v": new_v,
                 "idx": cache["idx"] + tokens.shape[1]}
    return logits, new_cache


# ---------------------------------------------------------------------------
# Continuous-batching cache (slot-based; used by the llm engine)
# ---------------------------------------------------------------------------

def init_slot_cache(cfg: LlamaConfig, max_batch: int, max_len: int) -> dict:
    """Per-slot KV cache: each batch row is an independent request with its
    own length (unlike init_kv_cache's single shared position)."""
    shape = (cfg.n_layers, max_batch, max_len, cfg.n_kv_heads, cfg.head_dim)
    return {"k": jnp.zeros(shape, cfg.dtype),
            "v": jnp.zeros(shape, cfg.dtype),
            "lengths": jnp.zeros((max_batch,), jnp.int32)}


def apply_with_kv(params: dict, tokens: jax.Array, cfg: LlamaConfig):
    """Prefill forward returning per-layer rope'd K/V for cache seeding:
    tokens [B, S] -> (logits [B, S, V], k/v [L, B, S, KVH, D])."""
    x = params["embed"][tokens].astype(cfg.dtype)
    positions = jnp.broadcast_to(jnp.arange(tokens.shape[1]), tokens.shape)
    cos, sin = rope_freqs(cfg, positions)

    def body(x, layer_params):
        p = layer_params
        h = rms_norm(x, p["attn_norm"], cfg.norm_eps)
        b, s, _ = h.shape
        q, k, v = _qkv(h, p, cfg, cos, sin)
        attn = _attention(q, k, v, cfg, causal=True, attn_impl=None)
        x = x + attn.reshape(b, s, -1) @ p["wo"]
        x = constrain(x, ("batch", "sequence", "embed"))
        x, _ = _mlp_block(x, p, cfg)
        return x, (k, v)

    x, (ks, vs) = jax.lax.scan(body, x, params["layers"])
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    logits = jnp.einsum("bsd,dv->bsv", x, params["lm_head"],
                        preferred_element_type=jnp.float32)
    return logits, ks, vs


def decode_batched(params: dict, tokens: jax.Array, cache: dict,
                   cfg: LlamaConfig) -> tuple[jax.Array, dict]:
    """One decode step for a batch of independent slots.

    tokens [B, 1] — next token per slot; cache rows advance at their own
    `lengths`. Returns (logits [B, V], updated cache). Inactive slots should
    carry any token; caller masks their outputs.
    """
    b = tokens.shape[0]
    rows = jnp.arange(b)
    x = params["embed"][tokens].astype(cfg.dtype)         # [B, 1, D]
    positions = cache["lengths"][:, None]                 # [B, 1]
    cos, sin = rope_freqs(cfg, positions)
    k_pos = jnp.arange(cache["k"].shape[2])[None, :]      # [1, S]
    mask = k_pos <= positions                             # [B, S]

    def body(x, scanned):
        p, (ck, cv) = scanned
        h = rms_norm(x, p["attn_norm"], cfg.norm_eps)
        q, k, v = _qkv(h, p, cfg, cos, sin)
        ck = ck.at[rows, cache["lengths"]].set(k[:, 0].astype(ck.dtype))
        cv = cv.at[rows, cache["lengths"]].set(v[:, 0].astype(cv.dtype))
        groups = cfg.n_heads // cfg.n_kv_heads
        kr = jnp.repeat(ck, groups, axis=2)
        vr = jnp.repeat(cv, groups, axis=2)
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, kr,
                            preferred_element_type=jnp.float32)
        scores = scores * (cfg.head_dim ** -0.5)
        scores = jnp.where(mask[:, None, None, :], scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1).astype(vr.dtype)
        attn = jnp.einsum("bhqk,bkhd->bqhd", probs, vr)
        x = x + attn.reshape(b, 1, -1) @ p["wo"]
        x, _ = _mlp_block(x, p, cfg)
        return x, (ck, cv)

    x, (new_k, new_v) = jax.lax.scan(
        body, x, (params["layers"], (cache["k"], cache["v"])))
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    logits = jnp.einsum("bsd,dv->bsv", x, params["lm_head"],
                        preferred_element_type=jnp.float32)[:, 0]
    new_cache = {"k": new_k, "v": new_v, "lengths": cache["lengths"] + 1}
    return logits, new_cache


# ---------------------------------------------------------------------------
# Pipeline-parallel forward (GPipe over the pp mesh axis)
# ---------------------------------------------------------------------------

def apply_pipelined(params: dict, tokens: jax.Array, cfg: LlamaConfig,
                    mesh, num_microbatches: int,
                    attn_impl=None, num_chunks: int = 1) -> jax.Array:
    """Training forward with transformer blocks pipelined over the mesh's
    `pp` axis (parallel.pipeline schedules: GPipe, or breadth-first
    interleaved virtual stages with num_chunks > 1 — bubble drops from
    (S-1)/(M+S-1) to (S-1)/(num_chunks*M+S-1)). Embedding and lm_head are
    pp-replicated and stay outside the pipeline; pp_size * num_chunks must
    divide cfg.n_layers. Matches `apply` numerically."""
    from ..parallel.pipeline import (interleave_stages, pipeline_apply,
                                     split_stages)

    if cfg.moe_experts:
        # the GPipe stage fn drops each layer's load-balance aux term; MoE
        # training must not lose it silently — use apply_with_aux (dense pp
        # for MoE needs an aux-accumulating pipeline, not yet built)
        raise NotImplementedError(
            "apply_pipelined does not propagate the MoE aux loss; "
            "train MoE configs with apply_with_aux (ep/dp sharding)")

    n_stages = mesh.shape.get("pp", 1)
    x = params["embed"][tokens].astype(cfg.dtype)
    positions = jnp.arange(tokens.shape[1])[None, :]
    cos, sin = rope_freqs(cfg, positions)  # [1, S, hd/2]: broadcasts over mb

    def stage_fn(stage_layers, h):
        def body(h, layer_params):
            y, _, _ = _layer(h, layer_params, cfg, cos, sin, attn_impl)
            return y, None
        h, _ = jax.lax.scan(body, h, stage_layers)
        return h

    stages = split_stages(params["layers"], n_stages * num_chunks)
    if num_chunks > 1:
        stages = interleave_stages(stages, n_stages, num_chunks)
    x = pipeline_apply(stage_fn, stages, x, mesh, num_microbatches,
                       remat=cfg.remat, num_chunks=num_chunks)
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    return jnp.einsum("bsd,dv->bsv", x, params["lm_head"],
                      preferred_element_type=jnp.float32)


# ---------------------------------------------------------------------------
# Paged KV cache (block tables; used by the paged serving engine)
# ---------------------------------------------------------------------------

def init_paged_cache(cfg: LlamaConfig, num_pages: int,
                     page_size: int) -> list[dict]:
    """Per-layer page pools: [{'k','v': [P, page, KVH, D]}] * n_layers.

    Kept as SEPARATE per-layer arrays (not a stacked [L, ...] tensor): the
    decode step is unrolled over layers so each Pallas paged-attention call
    consumes its layer's pool directly — a scan-sliced stacked tensor would
    materialize a full-layer copy per step.

    Convention: physical page 0 is a write SINK — allocators must never
    hand it to a sequence. decode_paged (idle rows) and prefill_paged_chunk
    (pad pages) dump never-attended writes there.
    """
    shape = (num_pages, page_size, cfg.n_kv_heads, cfg.head_dim)
    return [{"k": jnp.zeros(shape, cfg.dtype),
             "v": jnp.zeros(shape, cfg.dtype)}
            for _ in range(cfg.n_layers)]


def _layer_params(params: dict, layer: int) -> dict:
    return jax.tree.map(lambda a: a[layer], params["layers"])


def decode_paged(params: dict, tokens: jax.Array, caches: list[dict],
                 block_tables: jax.Array, lengths: jax.Array,
                 cfg: LlamaConfig, *, page_size: int,
                 interpret: bool = False, lora=None, slots=None):
    """One decode step over paged caches.

    tokens [B, 1]; block_tables [B, max_pages]; lengths [B] = tokens already
    WRITTEN (current token goes at position `lengths`). Returns
    (logits [B, V], updated caches). Inactive rows: pass length 0 and mask
    the output — their token writes land in page block_tables[b, 0] slot 0
    and are overwritten on real use.

    ``lora``/``slots`` [B]: batched multi-LoRA — each row's projections
    (and logits, for lm_head adapters) get its slot's low-rank delta, so
    ONE dispatch serves a mixed-tenant batch (see _lora_add).
    """
    from ..ops.paged_attention import paged_decode_reference
    from ..ops.ragged_paged_attention import ragged_decode_attention

    b = tokens.shape[0]
    rows = jnp.arange(b)
    x = params["embed"][tokens].astype(cfg.dtype)          # [B, 1, D]
    cos, sin = rope_freqs(cfg, lengths[:, None])
    page_ids = block_tables[rows, lengths // page_size]    # [B]
    offsets = lengths % page_size                          # [B]
    # hoisted: the platform probe + partial are trace-time constants, so
    # selecting per layer just re-evaluated them n_layers times per step
    attend = (functools.partial(ragged_decode_attention, interpret=interpret)
              if (interpret or _on_tpu()) else paged_decode_reference)

    new_caches = []
    for layer in range(cfg.n_layers):
        p = _layer_params(params, layer)
        ll = _lora_at_layer(lora, layer)
        cache = caches[layer]
        h = rms_norm(x, p["attn_norm"], cfg.norm_eps)
        q, k, v = _qkv(h, p, cfg, cos, sin, ll, slots)     # q [B,1,H,D]
        k_pages = cache["k"].at[page_ids, offsets].set(
            k[:, 0].astype(cache["k"].dtype))
        v_pages = cache["v"].at[page_ids, offsets].set(
            v[:, 0].astype(cache["v"].dtype))
        attn = attend(q[:, 0], k_pages, v_pages, block_tables,
                      lengths + 1)                         # [B, H, D]
        proj = attn.reshape(b, 1, -1)
        y = proj @ p["wo"]
        if ll is not None:
            y = _lora_add(y, proj, ll, "wo", slots)
        x = x + y
        x, _ = _mlp_block(x, p, cfg)
        new_caches.append({"k": k_pages, "v": v_pages})

    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    logits = jnp.einsum("bsd,dv->bsv", x, params["lm_head"],
                        preferred_element_type=jnp.float32)
    if lora is not None and "lm_head.A" in lora:
        logits = _lora_add(logits, x, lora, "lm_head", slots)
    return logits[:, 0], new_caches


def prefill_paged_chunk(params: dict, chunk: jax.Array, caches: list[dict],
                        block_table_row: jax.Array, start_pos: jax.Array,
                        cfg: LlamaConfig, *, page_size: int,
                        true_chunk_len: jax.Array | None = None,
                        interpret: bool = False, lora=None, slot=None):
    """Prefill ONE page-aligned chunk of one sequence.

    chunk [1, C] (C a multiple of page_size, right-padded with zeros);
    block_table_row [max_pages]; start_pos = tokens already prefilled
    (page-aligned); true_chunk_len = real tokens in this chunk (defaults to
    C). Attends over the already-written paged prefix plus causally within
    the chunk, writes the chunk's K/V into its pages, and returns
    (logits [C, V], updated caches) — caller picks the logit at the
    prompt's true last position.

    Attention dispatch: the chunk's K/V is scattered into its pages
    FIRST, so attention always reads pages only (prefix + causal window
    in one predicate). On TPU (or under ``interpret``) that is the
    ragged Pallas kernel (ops/ragged_paged_attention.py) with HBM
    traffic tracking the row's live page count; elsewhere it is the
    kernel's own jnp oracle (ragged_paged_reference), whose gather cost
    scales with the block-table row WIDTH — which the engine buckets to
    the live page count (power-of-two page buckets) at long tables.

    Pages past the chunk's real tokens (pad pages of the final chunk, or
    logical pages beyond the block table) are written to page 0 — the
    reserved sink page no sequence owns — so a short final chunk can never
    clobber pages the allocator has handed to another sequence.

    Chunked prefill exists so admission never stalls decode: the engine
    interleaves one bounded chunk per step (vLLM's chunked-prefill role).
    """
    from ..ops.ragged_paged_attention import (
        ragged_paged_attention, ragged_paged_reference,
    )

    c = chunk.shape[1]
    n_chunk_pages = c // page_size
    max_pages = block_table_row.shape[0]
    positions = start_pos + jnp.arange(c)[None, :]        # [1, C]
    cos, sin = rope_freqs(cfg, positions)
    scale = cfg.head_dim ** -0.5
    use_kernel = interpret or _on_tpu()
    if true_chunk_len is None:
        true_chunk_len = jnp.int32(c)
    # gather (not dynamic_slice: it clamps at the row end and would silently
    # shift the write window); invalid logical pages route to sink page 0
    logical = start_pos // page_size + jnp.arange(n_chunk_pages)
    valid_pages = (true_chunk_len + page_size - 1) // page_size
    valid = (jnp.arange(n_chunk_pages) < valid_pages) & (logical < max_pages)
    chunk_page_ids = jnp.where(
        valid, block_table_row[jnp.clip(logical, 0, max_pages - 1)], 0)

    x = params["embed"][chunk].astype(cfg.dtype)          # [1, C, D]
    new_caches = []
    for layer in range(cfg.n_layers):
        p = _layer_params(params, layer)
        ll = _lora_at_layer(lora, layer)
        cache = caches[layer]
        h = rms_norm(x, p["attn_norm"], cfg.norm_eps)
        q, k, v = _qkv(h, p, cfg, cos, sin, ll, slot)     # [1,C,H/KVH,D]

        # write the chunk's K/V into its (page-aligned) pages
        k_w = k[0].reshape(n_chunk_pages, page_size,
                           cfg.n_kv_heads, cfg.head_dim)
        v_w = v[0].reshape(n_chunk_pages, page_size,
                           cfg.n_kv_heads, cfg.head_dim)
        k_pages = cache["k"].at[chunk_page_ids].set(
            k_w.astype(cache["k"].dtype))
        v_pages = cache["v"].at[chunk_page_ids].set(
            v_w.astype(cache["v"].dtype))

        # the scatter above already placed the window's K/V, so both
        # paths attend pages only (prefix + causal window in one
        # predicate); the jnp oracle IS the fallback — one copy of the
        # gather/mask/grouped-GQA math to keep in sync with the kernel.
        # Real queries (q < true_chunk_len) read only real pages; pad
        # queries read sink-routed garbage the caller discards.
        starts1 = jnp.reshape(start_pos, (1,)).astype(jnp.int32)
        qlens1 = jnp.reshape(true_chunk_len, (1,)).astype(jnp.int32)
        if use_kernel:
            attn = ragged_paged_attention(
                q, k_pages, v_pages, block_table_row[None], starts1,
                qlens1, scale=scale, interpret=interpret).astype(cfg.dtype)
        else:
            attn = ragged_paged_reference(
                q, k_pages, v_pages, block_table_row[None], starts1,
                qlens1, scale=scale).astype(cfg.dtype)
        proj = attn.reshape(1, c, -1)
        y = proj @ p["wo"]
        if ll is not None:
            y = _lora_add(y, proj, ll, "wo", slot)
        x = x + y
        x, _ = _mlp_block(x, p, cfg)
        new_caches.append({"k": k_pages, "v": v_pages})

    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    logits = jnp.einsum("bsd,dv->bsv", x, params["lm_head"],
                        preferred_element_type=jnp.float32)
    if lora is not None and "lm_head.A" in lora:
        logits = _lora_add(logits, x, lora, "lm_head", slot)
    return logits[0], new_caches


def prefill_paged_rows(params: dict, chunks: jax.Array, caches: list[dict],
                       bt_rows: jax.Array, start_pos: jax.Array,
                       true_lens: jax.Array, cfg: LlamaConfig, *,
                       page_size: int, interpret: bool = False,
                       lora=None, slots=None):
    """Prefill up to R chunk-rows in ONE compiled program.

    chunks [R, C] (each row one page-aligned chunk, right-padded);
    bt_rows [R, max_pages]; start_pos/true_lens [R]. Rows run sequentially
    under lax.scan carrying the caches, so consecutive rows may be
    consecutive chunks of the SAME sequence — row i+1 sees row i's page
    writes. Rows with true_lens == 0 are padding: all their page writes
    route to sink page 0. Returns (last_logits [R, V] — the logit at each
    row's last real token — and updated caches).

    Exists to cut engine-step dispatch count: a burst of prompts prefills
    in ceil(n_chunks / R) dispatches instead of one dispatch per chunk
    (the batched-prefill scheduling role of the reference's vLLM engine,
    llm/_internal/serve/deployments/llm/vllm/vllm_engine.py:180).
    """
    c = chunks.shape[1]
    # slots join the scanned operands only on the multi-LoRA path, so
    # lora=None traces exactly the pre-LoRA program
    if lora is not None and slots is None:
        slots = jnp.zeros((chunks.shape[0],), jnp.int32)

    def body(carry, row):
        chunk, bt, sp, tl = row[:4]
        sl = row[4] if lora is not None else None
        logits, carry = prefill_paged_chunk(
            params, chunk[None, :], carry, bt, sp, cfg,
            page_size=page_size, true_chunk_len=tl, interpret=interpret,
            lora=lora, slot=sl)
        last = logits[jnp.clip(tl - 1, 0, c - 1)]
        return carry, last

    xs = (chunks, bt_rows, start_pos, true_lens)
    if lora is not None:
        xs = xs + (slots,)
    caches, last = jax.lax.scan(body, caches, xs)
    return last, caches


def verify_paged_rows(params: dict, tokens: jax.Array, caches: list[dict],
                      bt_rows: jax.Array, starts: jax.Array,
                      cfg: LlamaConfig, *, page_size: int,
                      interpret: bool = False, lora=None, slots=None):
    """Speculative-verification forward (the scorer role of vLLM-style
    speculative decoding in the reference's serving engine): for each of
    R rows feed S1 = 1 + n_draft tokens at positions
    starts[r] .. starts[r]+S1-1 over that row's paged KV, writing their
    K/V in place, and return logits [R, S1, V] for every fed position —
    the engine accepts the longest draft prefix the model agrees with,
    so one dispatch can emit up to S1 tokens.

    Attention dispatch mirrors prefill_paged_chunk: the ragged paged
    kernel on TPU / under ``interpret`` (the K/V scatter already happens
    before attention here), the plain-jnp gather as fallback/oracle.

    Position p's K/V lands in page bt_rows[r, p // page_size] at slot
    p % page_size; positions past the block table route to sink page 0
    (their logits are garbage and the engine discards them). Rejected
    drafts leave stale K/V beyond the accepted length — never attended,
    because attention is causal and the engine re-feeds real tokens at
    those same positions next dispatch, overwriting in place.

    Rows run under one lax.scan carrying the caches (same shape
    discipline as prefill_paged_rows; R and S1 are static).
    """
    from ..ops.ragged_paged_attention import (
        ragged_paged_attention, ragged_paged_reference,
    )

    maxp = bt_rows.shape[1]
    s1 = tokens.shape[1]
    scale = cfg.head_dim ** -0.5
    use_kernel = interpret or _on_tpu()
    if lora is not None and slots is None:
        slots = jnp.zeros((tokens.shape[0],), jnp.int32)

    def body(carry, row):
        toks, bt, start = row[:3]
        sl = row[3] if lora is not None else None
        positions = start + jnp.arange(s1)                 # [S1]
        cos, sin = rope_freqs(cfg, positions[None])
        pidx = positions // page_size
        page_ids = jnp.where(pidx < maxp,
                             bt[jnp.clip(pidx, 0, maxp - 1)], 0)
        offsets = positions % page_size
        x = params["embed"][toks][None].astype(cfg.dtype)  # [1, S1, D]
        new_caches = []
        for layer in range(cfg.n_layers):
            p = _layer_params(params, layer)
            ll = _lora_at_layer(lora, layer)
            cache = carry[layer]
            h = rms_norm(x, p["attn_norm"], cfg.norm_eps)
            q, k, v = _qkv(h, p, cfg, cos, sin, ll, sl)    # [1,S1,H/KVH,D]
            k_pages = cache["k"].at[page_ids, offsets].set(
                k[0].astype(cache["k"].dtype))
            v_pages = cache["v"].at[page_ids, offsets].set(
                v[0].astype(cache["v"].dtype))
            if use_kernel:
                # the scatter above already placed the window's K/V, so
                # the ragged kernel attends pages only
                attn = ragged_paged_attention(
                    q, k_pages, v_pages, bt[None],
                    jnp.reshape(start, (1,)).astype(jnp.int32),
                    jnp.full((1,), s1, jnp.int32),
                    scale=scale, interpret=interpret).astype(cfg.dtype)
            else:
                # the gather happens AFTER the scatter, so the window's
                # own K/V is already in place — exactly the ragged
                # oracle's contract, so the fallback IS the oracle (one
                # copy of the gather/mask/grouped-GQA math to keep in
                # sync with the kernel)
                attn = ragged_paged_reference(
                    q, k_pages, v_pages, bt[None],
                    jnp.reshape(start, (1,)).astype(jnp.int32),
                    jnp.full((1,), s1, jnp.int32),
                    scale=scale).astype(cfg.dtype)
            proj = attn.reshape(1, s1, -1)
            y = proj @ p["wo"]
            if ll is not None:
                y = _lora_add(y, proj, ll, "wo", sl)
            x = x + y
            x, _ = _mlp_block(x, p, cfg)
            new_caches.append({"k": k_pages, "v": v_pages})
        x = rms_norm(x, params["final_norm"], cfg.norm_eps)
        logits = jnp.einsum("bsd,dv->bsv", x, params["lm_head"],
                            preferred_element_type=jnp.float32)
        if lora is not None and "lm_head.A" in lora:
            logits = _lora_add(logits, x, lora, "lm_head", sl)
        return new_caches, logits[0]

    xs = (tokens, bt_rows, starts)
    if lora is not None:
        xs = xs + (slots,)
    caches, logits = jax.lax.scan(body, caches, xs)
    return logits, caches                                  # [R, S1, V]


def cross_entropy_loss(logits: jax.Array, targets: jax.Array,
                       mask: Optional[jax.Array] = None) -> jax.Array:
    """Mean next-token NLL. logits [B,S,V] f32, targets [B,S] int32."""
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    if mask is not None:
        return (nll * mask).sum() / jnp.maximum(mask.sum(), 1)
    return nll.mean()
