"""ResNet (v1.5) in flax.linen — the vision model for BASELINE config 1
(ResNet-18 / CIFAR-10 single-host training).

Follows the models/ contract: `init/apply/logical_axes` wrappers around a
linen Module so the trainer treats every model family uniformly. Convs stay
NHWC (XLA's native TPU layout); batch norm uses running stats carried in a
separate `state` collection.
"""
from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class ResNetConfig:
    stage_sizes: tuple = (2, 2, 2, 2)      # resnet-18
    num_classes: int = 10
    num_filters: int = 64
    dtype: Any = jnp.float32
    small_images: bool = True              # CIFAR stem (3x3, no maxpool)


def resnet18(**kw) -> ResNetConfig:
    return ResNetConfig(**kw)


def resnet50(**kw) -> ResNetConfig:
    return ResNetConfig(stage_sizes=(3, 4, 6, 3), **kw)


class ResidualBlock(nn.Module):
    filters: int
    strides: int
    dtype: Any

    @nn.compact
    def __call__(self, x, train: bool):
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, dtype=self.dtype)
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        residual = x
        y = conv(self.filters, (3, 3), (self.strides, self.strides))(x)
        y = nn.relu(norm()(y))
        y = conv(self.filters, (3, 3))(y)
        y = norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = conv(self.filters, (1, 1),
                            (self.strides, self.strides))(residual)
            residual = norm()(residual)
        return nn.relu(y + residual)


class ResNet(nn.Module):
    cfg: ResNetConfig

    @nn.compact
    def __call__(self, x, train: bool = True):
        cfg = self.cfg
        conv = partial(nn.Conv, use_bias=False, dtype=cfg.dtype)
        if cfg.small_images:
            x = conv(cfg.num_filters, (3, 3))(x)
        else:
            x = conv(cfg.num_filters, (7, 7), (2, 2))(x)
        x = nn.relu(nn.BatchNorm(use_running_average=not train,
                                 momentum=0.9, dtype=cfg.dtype)(x))
        if not cfg.small_images:
            x = nn.max_pool(x, (3, 3), (2, 2), padding="SAME")
        for i, n_blocks in enumerate(cfg.stage_sizes):
            for j in range(n_blocks):
                strides = 2 if i > 0 and j == 0 else 1
                x = ResidualBlock(cfg.num_filters * 2 ** i, strides,
                                  cfg.dtype)(x, train)
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(cfg.num_classes, dtype=jnp.float32)(x)


def init(rng: jax.Array, cfg: ResNetConfig,
         input_shape: Sequence[int] = (1, 32, 32, 3)) -> dict:
    """Returns {'params': ..., 'batch_stats': ...}."""
    model = ResNet(cfg)
    return model.init(rng, jnp.zeros(input_shape, cfg.dtype), train=True)


def apply(variables: dict, images: jax.Array, cfg: ResNetConfig,
          train: bool = False):
    """Inference/eval forward -> logits [B, num_classes]."""
    return ResNet(cfg).apply(variables, images, train=False)


def apply_train(variables: dict, images: jax.Array, cfg: ResNetConfig):
    """Training forward -> (logits, updated batch_stats)."""
    logits, new_state = ResNet(cfg).apply(
        variables, images, train=True, mutable=["batch_stats"])
    return logits, new_state


def logical_axes(variables: dict) -> dict:
    """Conv/dense kernels replicate under pure DP; batch-parallel training
    needs no param sharding (they fit one chip)."""
    return jax.tree.map(lambda _: (None,), variables,
                        is_leaf=lambda x: hasattr(x, "shape"))
