"""ray_tpu.obs — the metrics plane: cluster time-series history, SLO
burn-rate engine, and the autoscaler's signal source.

- :mod:`ray_tpu.obs.tsdb` — fixed-memory ring-buffer time-series store
  (bounded by construction: preallocated per-series rings + a hard
  cardinality cap with an ``__overflow__`` sink).
- :mod:`ray_tpu.obs.scraper` — head thread folding the merged
  user-metric store into the TSDB every ``cfg.tsdb_scrape_s`` (no new
  wire frames), plus :func:`~ray_tpu.obs.scraper.autoscale_signals`.
- :mod:`ray_tpu.obs.slo` — declarative ``SLO(metric, objective,
  window)`` objectives evaluated as multi-window burn rates with an
  ok -> warn -> page alert state machine.

Query surfaces: ``state.metrics_history()`` / ``state.slo_report()``,
``cli top`` / ``cli slo``, dashboard ``/api/metrics_history`` +
``/api/slo``, and ``state.summary()["slo"]``.
"""
from __future__ import annotations

__all__ = ["TSDB", "SLO", "SLOEngine", "MetricsScraper",
           "autoscale_signals", "default_serve_slos"]


def __getattr__(name):
    # PEP 562 lazy exports: importing ray_tpu.obs must stay feather-
    # weight (GL005 / test_no_heavy_imports guard the closure)
    if name in ("TSDB",):
        from .tsdb import TSDB
        return TSDB
    if name in ("SLO", "SLOEngine", "default_serve_slos"):
        from . import slo as _slo
        return getattr(_slo, name)
    if name in ("MetricsScraper", "autoscale_signals"):
        from . import scraper as _scraper
        return getattr(_scraper, name)
    raise AttributeError(name)
