"""Head-side metrics scraper: folds the merged user-metric store into
the TSDB every ``cfg.tsdb_scrape_s``, runs the SLO engine, and answers
the autoscaler's signal queries.

No new transport: every process already ships its metric deltas to the
head over the existing control connection (util/metrics.py's 2 s
flusher), and ``Runtime.user_metrics_dump()`` is the merged view. The
scraper samples THAT — one dict walk per tick, no wire frames, no
PROTOCOL_VERSION bump. Remote drivers reach the history through the
existing rpc path (``metrics_history`` / ``slo_report`` /
``obs_signals`` in Runtime._RPC_METHODS).

Signal evaluation (:func:`autoscale_signals`) is head-side on purpose:
the controller asks one question per deployment per scrape period
("should I scale out?") instead of pulling four series over the RPC and
re-deriving burn rates in an actor process.
"""
from __future__ import annotations

import threading
import time
from typing import Optional

from .slo import WARN_BURN, SLOEngine
from .tsdb import TSDB

#: window for the reactive signals, in scrape ticks (with the 15 s
#: default scrape this is 5 minutes — the fast-short SLO window)
SIGNAL_WINDOW_TICKS = 20.0


class MetricsScraper:
    """One daemon thread on the head. Owns the TSDB + SLO engine."""

    def __init__(self, rt, tsdb: Optional[TSDB] = None,
                 engine: Optional[SLOEngine] = None):
        from ..core.config import cfg
        self.rt = rt
        self.period_s = max(0.01, float(cfg.tsdb_scrape_s))
        self.tsdb = tsdb if tsdb is not None else TSDB(
            cfg.tsdb_retention_points, cfg.tsdb_scrape_s,
            cfg.tsdb_max_series)
        self.engine = engine if engine is not None \
            else SLOEngine(self.tsdb)
        self.ticks = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # serializes ticks: callers may drive scrape_once() manually
        # (bench_serve's soak verdict, tests) while the daemon thread
        # runs — SLOEngine.evaluate's state machine must never see two
        # concurrent evaluations
        self._tick_lock = threading.Lock()

    def start(self) -> "MetricsScraper":
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="rtpu-obs-scraper")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()

    def _loop(self):
        while not self._stop.wait(self.period_s):
            try:
                self.scrape_once()
            except Exception:
                pass  # a bad tick must not kill the history thread

    def scrape_once(self, now: Optional[float] = None) -> None:
        """One tick: snapshot the merged store into the TSDB, sample a
        few core runtime gauges, evaluate the SLOs. Public so tests and
        the bench driver can drive it with a synthetic clock; the tick
        lock keeps a manual call from racing the daemon thread (a
        concurrent double-evaluate would double-fire alert
        transitions)."""
        now = time.time() if now is None else now
        with self._tick_lock:
            # user_metrics_dump flushes nothing itself; flush() folds
            # THIS process's pending deltas (head-resident serve
            # handles, engine stats) in first so head-local series
            # aren't a tick stale
            from ..util import metrics as um
            um.flush()
            self.tsdb.record_store(self.rt.user_metrics_dump(), now)
            self._scrape_core(now)
            self.engine.evaluate(now)
            self.ticks += 1

    def _scrape_core(self, now: float) -> None:
        """A few built-in runtime series the dashboards trend that no
        user metric carries (cheap reads; the store probes are lockless
        native calls)."""
        rt = self.rt
        with rt.lock:
            pending = len(rt.pending)
            workers_busy = sum(1 for w in rt.workers.values()
                               if w.state in ("busy", "actor"))
        self.tsdb.record("rtpu_core_pending_tasks", "gauge", (), now,
                         float(pending))
        self.tsdb.record("rtpu_core_workers_busy", "gauge", (), now,
                         float(workers_busy))
        self.tsdb.record("rtpu_core_store_bytes_in_use", "gauge", (),
                         now, float(rt.store.bytes_in_use()))

    def stats(self) -> dict:
        return {**self.tsdb.stats(), "ticks": self.ticks,
                "period_s": self.period_s}


def autoscale_signals(tsdb: TSDB, engine: Optional[SLOEngine],
                      app: str, deployment: str,
                      now: Optional[float] = None) -> dict:
    """Should ``app/deployment`` scale OUT? Composes the TSDB-backed
    signals the queue-depth autoscaler is blind to (ROADMAP items 3+4):

    - ``shed``: the admission gate shed recently (reactive — we are
      already late; rate over the signal window > 0);
    - ``burn``: the TTFT-p95 / e2e-p99 SLO is burning its error budget
      above the WARN rate on the fast-short window — the predictive
      signal that fires BEFORE the first 429 (queue wait is climbing
      into the latency histograms while admission still admits);
    - ``ttft_slope``: TTFT p95 is rising across the window AND already
      past half its SLO threshold (trend confirmation for clusters
      whose histograms move slower than their burn windows);
    - ``tenant_queue``: some tenant has requests parked at the
      admission gate (per-tenant queue-depth series — the
      adapter-aware scale-out input: one tenant's hot adapter backlog
      is invisible to deployment-wide ongoing counts).

    The latency histograms carry engine labels, not app/deployment, so
    ``burn`` and ``ttft_slope`` are CLUSTER-level observations; both
    are therefore gated on deployment-LOCAL pressure (a non-empty
    admission queue or non-zero ongoing requests) — deployment A's
    TTFT collapse must not step every healthy autoscaled deployment B
    out to max.

    Returns ``{"scale_out": bool, "reasons": [...], ...evidence}``.
    Never raises — an empty TSDB yields no signal, not an error."""
    now = time.time() if now is None else now
    window_s = SIGNAL_WINDOW_TICKS * tsdb.scrape_s
    tags = {"app": app, "deployment": deployment}
    reasons = []

    shed_rate = tsdb.rate("rtpu_serve_admission_shed_total", tags,
                          window_s, now=now)
    if shed_rate > 0:
        reasons.append("shed")

    tenant_queued = tsdb.instant("rtpu_serve_tenant_queued", tags)
    tq_max = max((s["value"] for s in tenant_queued), default=0.0)
    ongoing = max((s["value"] for s in tsdb.instant(
        "rtpu_serve_queue_depth", tags)), default=0.0)
    # deployment-local pressure: the gate for the cluster-level
    # latency signals below
    local_pressure = tq_max > 0 or ongoing > 0

    from ..core.config import cfg
    ttft_thresh = cfg.serve_slo_ttft_s
    burn = {}
    if engine is not None:
        for row in engine.report().get("slos", ()):
            if row["slo"] in ("ttft_p95", "e2e_p99"):
                burn[row["slo"]] = {"state": row["state"],
                                    "fast_short": row["burn_fast"][0]}
        if local_pressure and any(
                b["fast_short"] > WARN_BURN or b["state"] != "ok"
                for b in burn.values()):
            reasons.append("burn")

    half = window_s / 2.0
    p95_now = tsdb.histogram_quantiles(
        "rtpu_llm_ttft_seconds", None, half, (0.95,), now=now)[0]
    p95_prev = tsdb.histogram_quantiles(
        "rtpu_llm_ttft_seconds", None, half, (0.95,), now=now - half)[0]
    if local_pressure and p95_now is not None and \
            p95_now >= 0.5 * ttft_thresh and \
            (p95_prev is None or p95_now > p95_prev):
        reasons.append("ttft_slope")

    if tq_max > 0:
        reasons.append("tenant_queue")

    return {
        "scale_out": bool(reasons),
        "reasons": reasons,
        "shed_rate_per_s": shed_rate,
        "ttft_p95_s": p95_now,
        "ttft_p95_prev_s": p95_prev,
        "tenant_queued_max": tq_max,
        "burn": burn,
        "window_s": window_s,
    }
