"""Declarative SLOs evaluated as multi-window burn rates over the TSDB.

An :class:`SLO` names a metric, an objective, and a base window::

    SLO("ttft_p95", "rtpu_llm_ttft_seconds", "p95 <= 2.0")
    SLO("shed_ratio", "rtpu_serve_admission_shed_total",
        "ratio <= 0.05",
        denominator=("rtpu_serve_admission_admitted_total",
                     "rtpu_serve_admission_shed_total"))

Two objective shapes:

- ``pNN <= T``: a latency histogram; good events are observations at or
  under ``T`` seconds (interpolated between bucket boundaries), the
  error budget is ``1 - NN/100`` — "95% of requests see TTFT <= 2s".
- ``ratio <= B``: a counter ratio; bad events are the metric's windowed
  increase, total events the summed denominator increases, budget B.

**Burn rate** is the classic SRE quantity: ``bad_fraction / budget`` —
1.0 means exactly consuming the budget, 14 means the budget is gone in
1/14th of the compliance period. Each SLO is evaluated over two window
PAIRS scaled to ``cfg.tsdb_scrape_s`` (so tests with a 50 ms scrape run
in seconds while production with the 15 s default gets the canonical
5m/1h + 30m/6h):

- **page** when the fast pair — ``window`` (default 240 ticks = 1h at
  15 s) AND ``window/12`` (5m) — both burn above ``page_burn`` (14.4:
  budget exhausted inside ~3 days at that rate);
- **warn** when the slow pair — ``window/2`` (30m) and ``6*window``
  (6h) — both burn above ``warn_burn`` (6.0).

The dual-window AND is what keeps this noise-immune: the short window
makes alerts reset quickly once the burn stops, the long window keeps a
two-sample blip from paging anyone.

The per-SLO alert state machine (ok -> warn -> page, hysteresis-free
because the windows themselves smooth) emits on every transition: a
``slo_transition`` flight event, ``rtpu_obs_slo_transitions_total`` and
the ``rtpu_obs_slo_state`` / ``rtpu_obs_slo_burn_rate`` gauges — which
the scraper then folds back into the TSDB like any other series, so
``cli slo`` can show alert history.
"""
from __future__ import annotations

import re
import time
from typing import Optional, Sequence

from ..core import flight as _fl
from ..util.metrics import Counter, Gauge, cached_metric as _metric

_OBJECTIVE_RE = re.compile(
    r"^\s*(?:p(?P<q>\d+(?:\.\d+)?)|(?P<ratio>ratio))\s*<=?\s*"
    r"(?P<bound>[0-9.eE+-]+)\s*$")

_STATES = ("ok", "warn", "page")
_STATE_CODE = {s: i for i, s in enumerate(_STATES)}

# canonical burn thresholds (Google SRE workbook multiwindow values)
PAGE_BURN = 14.4
WARN_BURN = 6.0


def _slo_state_gauge() -> Gauge:
    return _metric(Gauge, "rtpu_obs_slo_state",
                   "alert state per SLO (0 ok, 1 warn, 2 page)",
                   tag_keys=("slo",))


def _slo_burn_gauge() -> Gauge:
    return _metric(Gauge, "rtpu_obs_slo_burn_rate",
                   "error-budget burn rate per SLO and window pair "
                   "(1.0 = consuming exactly the budget)",
                   tag_keys=("slo", "pair"))


def _slo_transitions() -> Counter:
    return _metric(Counter, "rtpu_obs_slo_transitions_total",
                   "alert state-machine transitions",
                   tag_keys=("slo", "from", "to"))


class SLO:
    """One declarative objective. ``window`` is the fast-long window in
    seconds; None derives 240 scrape ticks (1h at the 15 s default)."""

    def __init__(self, name: str, metric: str, objective: str,
                 window: Optional[float] = None, *,
                 denominator: Sequence[str] = (),
                 tags: Optional[dict] = None,
                 page_burn: float = PAGE_BURN,
                 warn_burn: float = WARN_BURN):
        m = _OBJECTIVE_RE.match(objective)
        if m is None:
            raise ValueError(
                f"objective {objective!r} must look like 'p95 <= 2.0' "
                f"or 'ratio <= 0.05'")
        self.name = name
        self.metric = metric
        self.objective = objective
        self.window = window
        self.tags = dict(tags or {})
        self.denominator = tuple(denominator)
        self.page_burn = float(page_burn)
        self.warn_burn = float(warn_burn)
        if m.group("ratio"):
            self.kind = "ratio"
            self.threshold = None
            self.budget = float(m.group("bound"))
        else:
            self.kind = "quantile"
            self.threshold = float(m.group("bound"))
            self.budget = 1.0 - float(m.group("q")) / 100.0
        if not (0.0 < self.budget <= 1.0):
            raise ValueError(f"objective {objective!r} leaves no error "
                             f"budget to burn")
        if self.kind == "ratio" and not self.denominator:
            raise ValueError("ratio objectives need denominator=(...) "
                             "counter names")

    # -- burn math --------------------------------------------------------

    def _bad_fraction(self, tsdb, window_s: float,
                      now: Optional[float]) -> Optional[float]:
        """Fraction of the window's events violating the objective, or
        None when the window saw no events at all (no traffic burns no
        budget)."""
        if self.kind == "quantile":
            buckets, total = tsdb.histogram_buckets(
                self.metric, self.tags, window_s, now=now)
            if total <= 0:
                return None
            return 1.0 - _good_count(buckets, self.threshold) / total
        bad = tsdb.increase(self.metric, self.tags, window_s, now=now)
        total = sum(tsdb.increase(d, self.tags, window_s, now=now)
                    for d in self.denominator)
        if total <= 0:
            return None
        return min(bad / total, 1.0)

    def burn(self, tsdb, window_s: float,
             now: Optional[float] = None) -> float:
        bad = self._bad_fraction(tsdb, window_s, now)
        return 0.0 if bad is None else bad / self.budget

    def windows(self, scrape_s: float) -> dict:
        """The four evaluation windows in seconds, derived from the base
        window (fast-long) scaled to the scrape tick."""
        fast_long = self.window if self.window is not None \
            else 240.0 * scrape_s
        return {"fast": (fast_long / 12.0, fast_long),
                "slow": (fast_long / 2.0, fast_long * 6.0)}


def _good_count(buckets: dict, threshold: float) -> float:
    """Observations at or under ``threshold``, linearly interpolated
    between the adjacent cumulative bucket boundaries (the same estimate
    histogram_quantile makes, inverted)."""
    pts = sorted(((float(le), c) for le, c in buckets.items()),
                 key=lambda p: p[0])
    if not pts:
        return 0.0
    prev_b, prev_c = 0.0, 0.0
    for b, c in pts:
        if threshold < b:
            if b == float("inf"):
                return prev_c
            width = b - prev_b
            frac = 1.0 if width <= 0 else (threshold - prev_b) / width
            return prev_c + max(0.0, min(1.0, frac)) * (c - prev_c)
        prev_b, prev_c = b, c
    return pts[-1][1]


def default_serve_slos() -> list[SLO]:
    """The shipped serving objectives (thresholds are cfg flags):
    TTFT p95, end-to-end p99, proxy error ratio, admission shed ratio."""
    from ..core.config import cfg
    return [
        SLO("ttft_p95", "rtpu_llm_ttft_seconds",
            f"p95 <= {cfg.serve_slo_ttft_s}"),
        SLO("e2e_p99", "rtpu_serve_request_latency_seconds",
            f"p99 <= {cfg.serve_slo_e2e_s}"),
        SLO("error_ratio", "rtpu_serve_request_errors_total",
            f"ratio <= {cfg.serve_slo_error_ratio}",
            denominator=("rtpu_serve_proxy_requests_total",)),
        SLO("shed_ratio", "rtpu_serve_admission_shed_total",
            f"ratio <= {cfg.serve_slo_shed_ratio}",
            denominator=("rtpu_serve_admission_admitted_total",
                         "rtpu_serve_admission_shed_total")),
    ]


class SLOEngine:
    """Evaluates a set of SLOs against a TSDB and runs the per-SLO alert
    state machine. Single-threaded by contract: only the scraper tick
    calls :meth:`evaluate`; readers take :meth:`report` snapshots."""

    def __init__(self, tsdb, slos: Optional[Sequence[SLO]] = None):
        self.tsdb = tsdb
        self.slos = list(slos) if slos is not None \
            else default_serve_slos()
        self._state: dict[str, dict] = {
            s.name: {"state": "ok", "since": time.time()}
            for s in self.slos}
        self._last_report: dict = {"slos": [], "states": {}}

    def add(self, slo: SLO) -> None:
        self.slos.append(slo)
        self._state[slo.name] = {"state": "ok", "since": time.time()}

    def evaluate(self, now: Optional[float] = None) -> dict:
        now = time.time() if now is None else now
        rows = []
        for i, slo in enumerate(self.slos):
            pairs = slo.windows(self.tsdb.scrape_s)
            burns = {
                pair: (slo.burn(self.tsdb, short, now),
                       slo.burn(self.tsdb, long_, now))
                for pair, (short, long_) in pairs.items()}
            paging = all(b > slo.page_burn for b in burns["fast"])
            warning = all(b > slo.warn_burn for b in burns["slow"])
            new = "page" if paging else ("warn" if warning else "ok")
            st = self._state[slo.name]
            old = st["state"]
            if new != old:
                st["state"] = new
                st["since"] = now
                self._on_transition(i, slo, old, new)
            self._gauge(slo, burns, new)
            rows.append({
                "slo": slo.name, "metric": slo.metric,
                "objective": slo.objective, "state": new,
                "since": st["since"],
                "burn_fast": [round(b, 4) for b in burns["fast"]],
                "burn_slow": [round(b, 4) for b in burns["slow"]],
                "budget": slo.budget,
                "windows_s": {k: list(v) for k, v in pairs.items()},
            })
        self._last_report = {
            "slos": rows,
            "states": {r["slo"]: r["state"] for r in rows},
            "evaluated_at": now,
        }
        return self._last_report

    def report(self) -> dict:
        return self._last_report

    def _on_transition(self, idx: int, slo: SLO, old: str, new: str):
        _fl.evt(_fl.SLO_TRANSITION, idx, _STATE_CODE[new],
                _STATE_CODE[old])
        try:
            _slo_transitions().inc(1.0, tags={
                "slo": slo.name, "from": old, "to": new})
        except Exception:
            pass  # telemetry must never fail an evaluation tick

    def _gauge(self, slo: SLO, burns: dict, state: str):
        try:
            _slo_state_gauge().set(float(_STATE_CODE[state]),
                                   tags={"slo": slo.name})
            for pair, (short, long_) in burns.items():
                # the pair's effective burn is the MIN of its two
                # windows (both must exceed the threshold to alert)
                _slo_burn_gauge().set(min(short, long_), tags={
                    "slo": slo.name, "pair": pair})
        except Exception:
            pass  # telemetry must never fail an evaluation tick
