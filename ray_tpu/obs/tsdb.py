"""Fixed-memory cluster time-series store (the metrics plane's floor).

Every ``rtpu_*`` metric so far has been an instantaneous last-value
snapshot: ``metrics_summary()`` can show the current queue depth, never
a trend, so nothing downstream (SLO burn rates, signal-driven
autoscaling, ``cli top``) could exist. This module is the retained
substrate: a per-series preallocated (ts, value) ring — no allocation
after first touch, no unbounded growth — fed by the head scraper
(obs/scraper.py) from the merged user-metric store every
``cfg.tsdb_scrape_s`` tick.

Memory is bounded by construction, not by policy:

- each series owns exactly ``retention_points`` (ts, value) float pairs,
  preallocated on first record and overwritten oldest-first;
- the series COUNT is capped (``cfg.tsdb_max_series``): once the table
  is full, samples for never-before-seen label sets fold into one
  ``__overflow__`` sink series per metric name — client-controlled
  labels (tenant ids, routes) can never grow head memory, the same
  contract as the front door's bounded tenant tracking. (The sinks
  themselves may sit past the cap: at most one extra ring per metric
  NAME, and names come from code, not from request data — the ceiling
  is ``(max_series + n_names) x retention x 16`` bytes, which
  ``stats()`` reports against the live name count.)

Counters are stored as the scraped cumulative values; :meth:`TSDB.rate`
and :meth:`TSDB.increase` are monotonic-reset-aware (a value drop reads
as a restart from zero, Prometheus ``increase()`` semantics), so a
replica death mid-window undercounts by at most the pre-reset running
total rather than going negative. Histogram bucket series ride the same
rings (one series per ``le``); :meth:`TSDB.histogram_quantiles` takes
bucket *increases* over any window and folds them through
``util.metrics.histogram_quantiles`` — windowed p50/p95/p99, not
since-boot.

Tag matching is subset-style: ``tags={"app": "default"}`` matches every
series carrying that pair, so callers aggregate across the labels they
don't name (again the Prometheus convention).
"""
from __future__ import annotations

import threading
from array import array
from typing import Optional, Sequence

from ..util.metrics import histogram_quantiles as _hist_quantiles

#: the per-name sink key once the series table is full
OVERFLOW_KEY = (("__overflow__", ""),)


class _SeriesRing:
    """One series: preallocated (ts, value) ring, oldest overwritten."""

    __slots__ = ("kind", "ts", "vals", "n", "head", "cap")

    def __init__(self, kind: str, cap: int):
        self.kind = kind
        self.cap = cap
        self.ts = array("d", bytes(8 * cap))
        self.vals = array("d", bytes(8 * cap))
        self.n = 0        # live points (<= cap)
        self.head = 0     # next write slot

    def push(self, ts: float, value: float) -> None:
        self.ts[self.head] = ts
        self.vals[self.head] = value
        self.head = (self.head + 1) % self.cap
        if self.n < self.cap:
            self.n += 1

    def points(self, since: Optional[float] = None) -> list:
        """Chronological [(ts, value)] — all retained points, or only
        those at or after ``since``. Delta/rate queries use the first
        IN-window point as their baseline (increments that landed
        between the last pre-window sample and the window edge are
        dropped, not double-counted — the conservative side of
        Prometheus's extrapolation)."""
        start = (self.head - self.n) % self.cap
        out = [(self.ts[(start + i) % self.cap],
                self.vals[(start + i) % self.cap])
               for i in range(self.n)]
        if since is None:
            return out
        return [p for p in out if p[0] >= since]

    def window(self, since: Optional[float],
               until: Optional[float]) -> list:
        """Points in [since, until] — ``until`` matters for historical
        queries (a slope's previous-window read must not see newer
        samples)."""
        pts = self.points(since)
        if until is None:
            return pts
        return [p for p in pts if p[0] <= until]

    def last(self) -> Optional[tuple]:
        if self.n == 0:
            return None
        i = (self.head - 1) % self.cap
        return (self.ts[i], self.vals[i])


def _key_matches(key: tuple, tags: Optional[dict]) -> bool:
    if not tags:
        return True
    pairs = dict(key)
    return all(pairs.get(k) == str(v) for k, v in tags.items())


def _increase(points: list) -> float:
    """Reset-aware counter increase across chronological points (the
    first point is the baseline; a drop = restart from zero)."""
    if len(points) < 2:
        return 0.0
    inc = 0.0
    prev = points[0][1]
    for _t, v in points[1:]:
        inc += (v - prev) if v >= prev else v
        prev = v
    return inc


class TSDB:
    """The head's bounded-memory time-series store. Thread-safe: the
    scraper records from its own thread while RPC-pool threads query."""

    def __init__(self, retention_points: int, scrape_s: float,
                 max_series: int):
        self.retention_points = max(8, int(retention_points))
        self.scrape_s = max(0.01, float(scrape_s))
        self.max_series = max(16, int(max_series))
        self._lock = threading.Lock()
        # (name, key) -> _SeriesRing
        self._series: dict[tuple, _SeriesRing] = {}  # guarded by: self._lock
        self._kinds: dict[str, str] = {}  # guarded by: self._lock
        self._overflow_samples = 0  # guarded by: self._lock
        self._recorded = 0  # guarded by: self._lock

    # -- ingest -----------------------------------------------------------

    def record(self, name: str, kind: str, key: tuple, ts: float,
               value: float) -> None:
        """Append one sample. ``key`` is the util/metrics tag tuple
        (sorted (k, v) pairs; histogram bucket rows carry their ``le``
        pair). Past the series cap, unseen (name, key) pairs fold into
        the per-name ``__overflow__`` sink."""
        with self._lock:
            ring = self._series.get((name, key))
            if ring is None:
                if len(self._series) >= self.max_series:
                    # table full: fold into the per-name sink. The sink
                    # ring itself may allocate past max_series — bounded
                    # by the number of metric NAMES, which come from
                    # code, not from client-controlled label values
                    # (the cap's actual threat model)
                    key = OVERFLOW_KEY
                    self._overflow_samples += 1
                    ring = self._series.get((name, key))
                if ring is None:
                    ring = self._series[(name, key)] = _SeriesRing(
                        kind, self.retention_points)
            self._kinds[name] = kind
            ring.push(ts, value)
            self._recorded += 1

    def record_store(self, store: dict, ts: float) -> None:
        """Fold one ``util.metrics.collect_store()`` snapshot — the
        scraper's per-tick call. Histogram ``le``/``__sum__`` rows become
        ordinary series (their key carries the distinguishing pair)."""
        for name, rec in store.items():
            kind = rec.get("kind", "gauge")
            for key, value in rec.get("series", {}).items():
                self.record(name, kind, key, ts, float(value))

    # -- queries ----------------------------------------------------------

    def names(self) -> list[str]:
        with self._lock:
            return sorted({n for n, _k in self._series})

    def kind_of(self, name: str) -> Optional[str]:
        with self._lock:
            return self._kinds.get(name)

    def query(self, name: str, tags: Optional[dict] = None,
              window_s: Optional[float] = None,
              now: Optional[float] = None) -> list[dict]:
        """Range query: every matching series with its retained points
        trimmed to [now - window_s, now]. An explicit ``now`` makes the
        query historical (synthetic clocks, slope previous-window
        reads); the upper bound is unenforced only when neither window
        nor now is given."""
        since = until = None
        if window_s is not None:
            import time
            until = time.time() if now is None else now
            since = until - window_s
        elif now is not None:
            until = now
        with self._lock:
            rows = [(k, r) for (n, k), r in self._series.items()
                    if n == name and _key_matches(k, tags)]
            return [{"key": list(k), "kind": r.kind,
                     "points": r.window(since, until)} for k, r in rows]

    def instant(self, name: str, tags: Optional[dict] = None) -> list[dict]:
        """Latest sample per matching series."""
        with self._lock:
            out = []
            for (n, k), r in self._series.items():
                if n != name or not _key_matches(k, tags):
                    continue
                last = r.last()
                if last is not None:
                    out.append({"key": list(k), "ts": last[0],
                                "value": last[1]})
            return out

    def increase(self, name: str, tags: Optional[dict] = None,
                 window_s: Optional[float] = None,
                 now: Optional[float] = None) -> float:
        """Counter increase over the window, summed across matching
        series, monotonic-reset-aware."""
        total = 0.0
        for s in self.query(name, tags, window_s, now=now):
            total += _increase(s["points"])
        return total

    def rate(self, name: str, tags: Optional[dict] = None,
             window_s: Optional[float] = None,
             now: Optional[float] = None) -> float:
        """Per-second counter rate over the window (increase / window).
        With no window, uses the full retention span actually covered."""
        if window_s is None:
            spans = [s["points"] for s in self.query(name, tags)]
            ts = [p[0] for pts in spans for p in pts]
            if len(ts) < 2:
                return 0.0
            window_s = max(max(ts) - min(ts), self.scrape_s)
            if now is None:
                # anchor the window at the DATA's end, not wall-clock
                # now: an idle counter's whole retained span must stay
                # inside the window (otherwise the earliest points fall
                # off and a since-boot burst reads as rate 0)
                now = max(ts)
        return self.increase(name, tags, window_s, now=now) \
            / max(window_s, 1e-9)

    def histogram_buckets(self, name: str, tags: Optional[dict] = None,
                          window_s: Optional[float] = None,
                          now: Optional[float] = None) -> tuple:
        """(cumulative bucket increases {le: count}, total observations)
        over the window — the shared substrate for windowed quantiles
        and the SLO engine's good-event fractions."""
        buckets: dict[str, float] = {}
        for s in self.query(name, tags, window_s, now=now):
            le = next((v for k, v in s["key"] if k == "le"), None)
            if le is None:
                continue
            buckets[le] = buckets.get(le, 0.0) + _increase(s["points"])
        return buckets, buckets.get("+Inf", 0.0)

    def histogram_quantiles(self, name: str, tags: Optional[dict] = None,
                            window_s: Optional[float] = None,
                            qs: Sequence[float] = (0.5, 0.95, 0.99),
                            now: Optional[float] = None) -> list:
        """Windowed quantiles from bucket-series increases — p50/p95/p99
        over ANY range, not since boot. Returns [None]*len(qs) when the
        window saw no observations."""
        buckets, total = self.histogram_buckets(name, tags, window_s,
                                                now=now)
        return _hist_quantiles(buckets, total, qs)

    def slope_per_s(self, name: str, tags: Optional[dict] = None,
                    window_s: Optional[float] = None,
                    now: Optional[float] = None) -> float:
        """Least-squares slope (value units per second) of a gauge over
        the window, summed-value across matching series per timestamp.
        The autoscaler's trend signal (is TTFT p95 / queue depth
        RISING?) without keeping model state anywhere."""
        merged: dict[float, float] = {}
        for s in self.query(name, tags, window_s, now=now):
            for t, v in s["points"]:
                merged[t] = merged.get(t, 0.0) + v
        pts = sorted(merged.items())
        if len(pts) < 2:
            return 0.0
        n = len(pts)
        mt = sum(t for t, _ in pts) / n
        mv = sum(v for _, v in pts) / n
        denom = sum((t - mt) ** 2 for t, _ in pts)
        if denom <= 0:
            return 0.0
        return sum((t - mt) * (v - mv) for t, v in pts) / denom

    # -- health -----------------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            return {
                "series": len(self._series),
                "max_series": self.max_series,
                "retention_points": self.retention_points,
                "scrape_s": self.scrape_s,
                "samples_recorded": self._recorded,
                "overflow_samples": self._overflow_samples,
                # the proof the store is bounded: rings are preallocated
                # (2 doubles/point), so this is a ceiling, not a guess —
                # max_series client-driven series plus at most one
                # __overflow__ sink per (code-controlled) metric name
                "max_bytes": ((self.max_series
                               + len({n for n, _k in self._series}))
                              * self.retention_points * 16),
            }
