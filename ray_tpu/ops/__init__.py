"""ray_tpu.ops — Pallas TPU kernels and their reference implementations.

The hot ops of the ML stack (SURVEY.md §7: 'Pallas kernels for the hot ops').
The reference has no kernels of its own (it orchestrates torch/vLLM); on TPU
these are ours. Every op has a pure-jnp reference path used on CPU and as the
numerical oracle in tests; the Pallas path engages on TPU.
"""
import importlib

_EXPORTS = {
    "flash_attention": "flash_attention",
    "mha_reference": "flash_attention",
}
_MODULES = ("flash_attention", "paged_attention", "ragged_paged_attention")

__all__ = list(_EXPORTS) + list(_MODULES)


def __getattr__(name):
    if name in _EXPORTS:
        return getattr(importlib.import_module(f".{_EXPORTS[name]}",
                                               __name__), name)
    if name in _MODULES:
        return importlib.import_module(f".{name}", __name__)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
