"""Flash attention for TPU: Pallas forward kernel + memory-efficient VJP.

The reference framework has no attention kernels (attention lives in vLLM /
torch, which it only orchestrates — SURVEY.md §2.4); on TPU the kernel is
ours. Design:

* Forward: a Pallas kernel tiled (block_q × block_k) over the MXU, with the
  standard streaming-softmax accumulator in VMEM scratch carried across the
  k-block grid dimension (TPU grids iterate sequentially, last dim fastest,
  so scratch persists across the k sweep of one q block). Emits the
  log-sum-exp residual for the backward pass and for ring-attention
  composition (parallel.ring).
* Backward: two Pallas kernels (a dq sweep and a dkv sweep) with f32 VMEM
  accumulators, GQA gathered via BlockSpec index maps (no repeat). A jnp
  blockwise-recompute fallback (chunked `lax.scan`, O(S) memory) covers
  non-TPU backends.
* CPU / debugging: `mha_reference` (the numerical oracle) is used when not
  on TPU; the Pallas path also runs under `interpret=True` in tests.

Layout convention: [batch, seq, heads, head_dim] (models/ convention), with
grouped-query attention supported via num_kv_heads <= num_heads.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = -1e30


# ---------------------------------------------------------------------------
# Reference (numerical oracle; CPU path)
# ---------------------------------------------------------------------------

def mha_reference(q, k, v, causal: bool = False,
                  scale: Optional[float] = None,
                  segment_ids=None) -> jax.Array:
    """Plain softmax attention. q [B,Sq,H,D], k/v [B,Sk,KVH,D]; KVH may
    divide H (GQA). Returns [B,Sq,H,D]."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    groups = q.shape[2] // k.shape[2]
    if groups > 1:
        k = jnp.repeat(k, groups, axis=2)
        v = jnp.repeat(v, groups, axis=2)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        mask = jnp.tril(jnp.ones((q.shape[1], k.shape[1]), bool))
        s = jnp.where(mask[None, None], s, NEG_INF)
    if segment_ids is not None:
        q_seg, kv_seg = segment_ids
        seg = q_seg[:, :, None] == kv_seg[:, None, :]
        s = jnp.where(seg[:, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v).astype(q.dtype)


# ---------------------------------------------------------------------------
# Pallas forward kernel
# ---------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref,          # blocks
                o_ref, lse_ref,               # outputs
                acc_ref, m_ref, l_ref,        # VMEM scratch (carried over k)
                *, causal: bool, scale: float, block_q: int, block_k: int,
                num_k_blocks: int, kv_valid: int = 0):
    from jax.experimental import pallas as pl

    iq = pl.program_id(2)
    ik = pl.program_id(3)

    @pl.when(ik == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    # kv_valid > 0: sequences were padded to the block grid; padded k
    # columns must not contribute (static mask — kv_valid is a trace-time
    # constant)
    pad_mask = kv_valid > 0  # static: pad columns exist in SOME block

    def _compute():
        q = q_ref[:, :]                                        # [BQ, D]
        k = k_ref[:, :]                                        # [BK, D]
        v = v_ref[:, :]                                        # [BK, D]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale        # [BQ, BK]
        keep = None
        if causal or pad_mask:
            q_pos = iq * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = ik * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            keep = (q_pos >= k_pos) if causal else None
            if pad_mask:
                inb = k_pos < kv_valid
                keep = inb if keep is None else (keep & inb)
            s = jnp.where(keep, s, NEG_INF)
        m_prev, l_prev = m_ref[:], l_ref[:]
        m_cur = jnp.max(s, axis=-1)[:, None]                   # [BQ, 1]
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)                                 # [BQ, BK]
        if keep is not None:
            p = jnp.where(keep, p, 0.0)
        alpha = jnp.exp(m_prev - m_new)                        # [BQ, 1]
        l_ref[:] = l_prev * alpha + jnp.sum(p, axis=-1)[:, None]
        m_ref[:] = m_new
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)                # [BQ, D]
        acc_ref[:] = acc_ref[:] * alpha + pv

    if causal:
        # Skip fully-masked tiles: block contributes iff any q_pos >= k_pos,
        # i.e. the block's last q row sees the block's first k column.
        @pl.when((iq + 1) * block_q - 1 >= ik * block_k)
        def _():
            _compute()
    else:
        _compute()

    @pl.when(ik == num_k_blocks - 1)
    def _finalize():
        l = jnp.maximum(l_ref[:], 1e-30)                       # noqa: E741
        o_ref[:, :] = (acc_ref[:] / l).astype(o_ref.dtype)
        lse_ref[:] = m_ref[:] + jnp.log(l)                     # [BQ, 1]


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


def _pad_to_blocks(q, k, v, block_q: int, block_k: int):
    """Zero-pad seq dims to the kernel's block grid (q rows to 8-aligned
    q blocks, k columns to 128-aligned k blocks — the TPU tile shapes the
    s = q @ k.T [BQ, BK] intermediate needs). Padded k columns are masked
    in the kernels via kv_valid; padded q rows are sliced off after."""
    b, sq, h, d = q.shape
    _, sk, kvh, _ = k.shape
    block_q = min(block_q, _round_up(sq, 8))
    block_k = min(block_k, _round_up(sk, 128))
    sq_pad = _round_up(sq, block_q)
    sk_pad = _round_up(sk, block_k)
    if sq_pad != sq:
        q = jnp.pad(q, ((0, 0), (0, sq_pad - sq), (0, 0), (0, 0)))
    if sk_pad != sk:
        pad = ((0, 0), (0, sk_pad - sk), (0, 0), (0, 0))
        k = jnp.pad(k, pad)
        v = jnp.pad(v, pad)
    return q, k, v, block_q, block_k, sq_pad, sk_pad


def _flash_fwd(q, k, v, causal: bool, scale: float,
               block_q: int, block_k: int, interpret: bool):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, sq, h, d = q.shape
    _, sk, kvh, _ = k.shape
    groups = h // kvh
    q, k, v, block_q, block_k, sq_pad, sk_pad = _pad_to_blocks(
        q, k, v, block_q, block_k)
    nq, nk = sq_pad // block_q, sk_pad // block_k

    kernel = functools.partial(
        _fwd_kernel, causal=causal, scale=scale, block_q=block_q,
        block_k=block_k, num_k_blocks=nk,
        kv_valid=sk if sk_pad != sk else 0)

    # Kernel layout is [B, H, S, D] with batch/head block dims squeezed
    # (None), so every ref is 2-D and the (8, 128)-tiling constraint falls
    # on (seq_block, head_dim) where it belongs.
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)

    grid = (b, h, nq, nk)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, None, block_q, d),
                         lambda bi, hi, iq, ik: (bi, hi, iq, 0)),
            pl.BlockSpec((None, None, block_k, d),
                         lambda bi, hi, iq, ik: (bi, hi // groups, ik, 0)),
            pl.BlockSpec((None, None, block_k, d),
                         lambda bi, hi, iq, ik: (bi, hi // groups, ik, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, None, block_q, d),
                         lambda bi, hi, iq, ik: (bi, hi, iq, 0)),
            # trailing unit dim keeps the (8, 128)-tiling rule satisfied
            # (last block dim == array dim); squeezed on return
            pl.BlockSpec((None, None, block_q, 1),
                         lambda bi, hi, iq, ik: (bi, hi, iq, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(qt.shape, q.dtype),
            jax.ShapeDtypeStruct((b, h, sq, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
        ],
        interpret=interpret,
    )(qt, kt, vt)
    out = jnp.swapaxes(out, 1, 2)
    lse = lse[..., 0]
    if sq_pad != sq:
        out = out[:, :sq]
        lse = lse[:, :, :sq]
    return out, lse


# ---------------------------------------------------------------------------
# Pallas backward kernels (dq sweep + dkv sweep)
# ---------------------------------------------------------------------------
#
# Standard flash-attention backward split into two MXU-friendly passes:
#   dq kernel : grid (B, H, nq, nk) — k-sweep innermost, dq accumulator in
#               VMEM scratch carried across the k blocks of one q block.
#   dkv kernel: grid (B, H, nk, nq) — q-sweep innermost, dk/dv accumulators
#               carried across the q blocks of one k block.
# GQA: k/v blocks are gathered per q-head via the BlockSpec index map
# (hi // groups) — no materialized repeat. dk/dv come out per q-head
# [B, Sk, H, D] and are group-summed to [B, Sk, KVH, D] by XLA (cheap,
# fused elementwise reduction).
# delta = rowsum(dO · O) is precomputed outside (bandwidth-bound, fuses).


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   dq_ref, dq_acc_ref,
                   *, causal: bool, scale: float, block_q: int, block_k: int,
                   num_k_blocks: int, kv_valid: int = 0):
    from jax.experimental import pallas as pl

    iq = pl.program_id(2)
    ik = pl.program_id(3)

    @pl.when(ik == 0)
    def _init():
        dq_acc_ref[:] = jnp.zeros_like(dq_acc_ref)

    pad_mask = kv_valid > 0  # static: pad columns exist in SOME block

    def _compute():
        q = q_ref[:, :]                                        # [BQ, D]
        k = k_ref[:, :]                                        # [BK, D]
        v = v_ref[:, :]                                        # [BK, D]
        do = do_ref[:, :]                                      # [BQ, D]
        lse = lse_ref[:, :]                                    # [BQ, 1]
        delta = delta_ref[:, :]                                # [BQ, 1]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale        # [BQ, BK]
        if causal or pad_mask:
            q_pos = iq * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = ik * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            keep = (q_pos >= k_pos) if causal else None
            if pad_mask:
                inb = k_pos < kv_valid
                keep = inb if keep is None else (keep & inb)
            s = jnp.where(keep, s, NEG_INF)
        p = jnp.exp(s - lse)                                   # [BQ, BK]
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)                # [BQ, BK]
        ds = p * (dp - delta) * scale
        dq_acc_ref[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)                # [BQ, D]

    if causal:
        @pl.when((iq + 1) * block_q - 1 >= ik * block_k)
        def _():
            _compute()
    else:
        _compute()

    @pl.when(ik == num_k_blocks - 1)
    def _finalize():
        dq_ref[:, :] = dq_acc_ref[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_acc_ref, dv_acc_ref,
                    *, causal: bool, scale: float, block_q: int, block_k: int,
                    num_q_blocks: int, kv_valid: int = 0):
    from jax.experimental import pallas as pl

    ik = pl.program_id(2)
    iq = pl.program_id(3)

    @pl.when(iq == 0)
    def _init():
        dk_acc_ref[:] = jnp.zeros_like(dk_acc_ref)
        dv_acc_ref[:] = jnp.zeros_like(dv_acc_ref)

    pad_mask = kv_valid > 0  # static: pad columns exist in SOME block

    def _compute():
        q = q_ref[:, :]                                        # [BQ, D]
        k = k_ref[:, :]                                        # [BK, D]
        v = v_ref[:, :]                                        # [BK, D]
        do = do_ref[:, :]                                      # [BQ, D]
        lse = lse_ref[:, :]                                    # [BQ, 1]
        delta = delta_ref[:, :]                                # [BQ, 1]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale        # [BQ, BK]
        if causal or pad_mask:
            q_pos = iq * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = ik * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            keep = (q_pos >= k_pos) if causal else None
            if pad_mask:
                inb = k_pos < kv_valid
                keep = inb if keep is None else (keep & inb)
            s = jnp.where(keep, s, NEG_INF)
        p = jnp.exp(s - lse)                                   # [BQ, BK]
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)                # [BQ, BK]
        ds = (p * (dp - delta) * scale).astype(q.dtype)        # [BQ, BK]
        # dk += ds^T @ q ; dv += p^T @ dO   (contract over the q dim)
        dk_acc_ref[:] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)                # [BK, D]
        dv_acc_ref[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)                # [BK, D]

    if causal:
        @pl.when((iq + 1) * block_q - 1 >= ik * block_k)
        def _():
            _compute()
    else:
        _compute()

    @pl.when(iq == num_q_blocks - 1)
    def _finalize():
        dk_ref[:, :] = dk_acc_ref[:].astype(dk_ref.dtype)
        dv_ref[:, :] = dv_acc_ref[:].astype(dv_ref.dtype)


def _flash_bwd(q, k, v, out, lse, g, *, causal: bool, scale: float,
               block_q: int, block_k: int, interpret: bool):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, sq, h, d = q.shape
    _, sk, kvh, _ = k.shape
    groups = h // kvh
    q, k, v, block_q, block_k, sq_pad, sk_pad = _pad_to_blocks(
        q, k, v, block_q, block_k)
    kv_valid = sk if sk_pad != sk else 0
    if sq_pad != sq:
        # padded q rows: zero grads; lse pad value is irrelevant (their
        # p rows multiply a zero dO) but must be finite
        pad_rows = ((0, 0), (0, sq_pad - sq), (0, 0), (0, 0))
        out = jnp.pad(out, pad_rows)
        g = jnp.pad(g, pad_rows)
        lse = jnp.pad(lse, ((0, 0), (0, 0), (0, sq_pad - sq)))
    nq, nk = sq_pad // block_q, sk_pad // block_k

    qt = jnp.swapaxes(q, 1, 2)                                 # [B,H,Sq,D]
    kt = jnp.swapaxes(k, 1, 2)                                 # [B,KVH,Sk,D]
    vt = jnp.swapaxes(v, 1, 2)
    gt = jnp.swapaxes(g, 1, 2)                                 # [B,H,Sq,D]
    delta = jnp.sum(gt.astype(jnp.float32)
                    * jnp.swapaxes(out, 1, 2).astype(jnp.float32),
                    axis=-1, keepdims=True)                    # [B,H,Sq,1]
    lse4 = lse[..., None]                                      # [B,H,Sq,1]

    q_spec = pl.BlockSpec((None, None, block_q, d),
                          lambda bi, hi, iq, ik: (bi, hi, iq, 0))
    kv_spec = pl.BlockSpec((None, None, block_k, d),
                           lambda bi, hi, iq, ik: (bi, hi // groups, ik, 0))
    row_spec = pl.BlockSpec((None, None, block_q, 1),
                            lambda bi, hi, iq, ik: (bi, hi, iq, 0))

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, causal=causal, scale=scale,
                          block_q=block_q, block_k=block_k, num_k_blocks=nk,
                          kv_valid=kv_valid),
        grid=(b, h, nq, nk),
        in_specs=[q_spec, kv_spec, kv_spec, q_spec, row_spec, row_spec],
        out_specs=[q_spec],
        out_shape=[jax.ShapeDtypeStruct(qt.shape, q.dtype)],
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(qt, kt, vt, gt, lse4, delta)[0]

    # dkv sweep: q innermost. Note the index maps take (bi, hi, ik, iq).
    q_spec2 = pl.BlockSpec((None, None, block_q, d),
                           lambda bi, hi, ik, iq: (bi, hi, iq, 0))
    kv_spec2 = pl.BlockSpec((None, None, block_k, d),
                            lambda bi, hi, ik, iq: (bi, hi // groups, ik, 0))
    row_spec2 = pl.BlockSpec((None, None, block_q, 1),
                             lambda bi, hi, ik, iq: (bi, hi, iq, 0))
    dkv_out_spec = pl.BlockSpec((None, None, block_k, d),
                                lambda bi, hi, ik, iq: (bi, hi, ik, 0))

    dk_h, dv_h = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, causal=causal, scale=scale,
                          block_q=block_q, block_k=block_k, num_q_blocks=nq,
                          kv_valid=kv_valid),
        grid=(b, h, nk, nq),
        in_specs=[q_spec2, kv_spec2, kv_spec2, q_spec2, row_spec2, row_spec2],
        out_specs=[dkv_out_spec, dkv_out_spec],
        out_shape=[jax.ShapeDtypeStruct((b, h, sk_pad, d), k.dtype),
                   jax.ShapeDtypeStruct((b, h, sk_pad, d), v.dtype)],
        scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32)],
        interpret=interpret,
    )(qt, kt, vt, gt, lse4, delta)

    dq = jnp.swapaxes(dq, 1, 2)                                # [B,Sq,H,D]
    dk_h = jnp.swapaxes(dk_h, 1, 2)                            # [B,Sk,H,D]
    dv_h = jnp.swapaxes(dv_h, 1, 2)
    if sq_pad != sq:
        dq = dq[:, :sq]
    if sk_pad != sk:
        dk_h = dk_h[:, :sk]
        dv_h = dv_h[:, :sk]
    if groups > 1:
        dk = dk_h.reshape(b, sk, kvh, groups, d).sum(axis=3)
        dv = dv_h.reshape(b, sk, kvh, groups, d).sum(axis=3)
    else:
        dk, dv = dk_h, dv_h
    return dq, dk.astype(k.dtype), dv.astype(v.dtype)


# ---------------------------------------------------------------------------
# Memory-efficient backward (blockwise recompute, jnp — CPU fallback)
# ---------------------------------------------------------------------------

def _bwd_blockwise(res, g, *, causal, scale, block_k):
    """Recompute attention k-block by k-block; O(Sq·block_k) live memory."""
    q, k, v, out, lse = res
    groups = q.shape[2] // k.shape[2]
    kr = jnp.repeat(k, groups, axis=2) if groups > 1 else k
    vr = jnp.repeat(v, groups, axis=2) if groups > 1 else v

    b, sq, h, d = q.shape
    sk = kr.shape[1]
    nk = max(1, sk // block_k)
    bk = sk // nk

    qf = q.astype(jnp.float32)
    gf = g.astype(jnp.float32)
    # delta_i = sum_d(dO_i * O_i) — the standard flash-bwd residual
    delta = jnp.sum(gf * out.astype(jnp.float32), axis=-1)     # [B,Sq,H]
    q_pos = jnp.arange(sq)

    kb = jnp.moveaxis(kr.astype(jnp.float32).reshape(b, nk, bk, h, d), 1, 0)
    vb = jnp.moveaxis(vr.astype(jnp.float32).reshape(b, nk, bk, h, d), 1, 0)

    def step(dq_acc, blk):
        k_blk, v_blk, ik = blk
        s = jnp.einsum("bqhd,bkhd->bhqk", qf, k_blk,
                       preferred_element_type=jnp.float32) * scale
        if causal:
            k_pos = ik * bk + jnp.arange(bk)
            mask = q_pos[:, None] >= k_pos[None, :]
            s = jnp.where(mask[None, None], s, NEG_INF)
        # p = exp(s - lse): exact softmax probabilities via saved lse
        p = jnp.exp(s - lse[..., None])                        # [B,H,Sq,BK]
        dp = jnp.einsum("bqhd,bkhd->bhqk", gf, v_blk,
                        preferred_element_type=jnp.float32)
        ds = p * (dp - jnp.moveaxis(delta, -1, 1)[..., None]) * scale
        dq_acc = dq_acc + jnp.einsum("bhqk,bkhd->bqhd", ds, k_blk,
                                     preferred_element_type=jnp.float32)
        dk_blk = jnp.einsum("bhqk,bqhd->bkhd", ds, qf,
                            preferred_element_type=jnp.float32)
        dv_blk = jnp.einsum("bhqk,bqhd->bkhd", p, gf,
                            preferred_element_type=jnp.float32)
        return dq_acc, (dk_blk, dv_blk)

    dq, (dk_b, dv_b) = jax.lax.scan(
        step, jnp.zeros(q.shape, jnp.float32),
        (kb, vb, jnp.arange(nk)))
    dk = jnp.moveaxis(dk_b, 0, 1).reshape(b, sk, h, d)
    dv = jnp.moveaxis(dv_b, 0, 1).reshape(b, sk, h, d)
    if groups > 1:
        dk = dk.reshape(b, sk, k.shape[2], groups, d).sum(axis=3)
        dv = dv.reshape(b, sk, k.shape[2], groups, d).sum(axis=3)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


# ---------------------------------------------------------------------------
# Public op
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def flash_attention(q, k, v, causal: bool = False,
                    scale: Optional[float] = None,
                    block_q: int = 512, block_k: int = 512,
                    interpret: bool = False):
    """Fused attention. q [B,Sq,H,D]; k/v [B,Sk,KVH,D] (GQA when KVH<H).

    Pallas kernel on TPU (or interpret=True); jnp reference elsewhere.
    """
    out, _ = _fwd(q, k, v, causal, scale, block_q, block_k, interpret)
    return out


_ON_TPU: Optional[bool] = None


def _on_tpu() -> bool:
    """Cached platform probe shared by every kernel-vs-reference dispatch
    (flash fwd/bwd, paged decode)."""
    global _ON_TPU
    if _ON_TPU is None:
        try:
            _ON_TPU = jax.devices()[0].platform == "tpu"
        except Exception:
            return False  # don't cache a failed probe
    return _ON_TPU


def _fwd(q, k, v, causal, scale, block_q, block_k, interpret):
    if scale is None:
        scale = q.shape[-1] ** -0.5
    if interpret or _on_tpu():
        return _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret)
    out = mha_reference(q, k, v, causal, scale)
    # lse for the backward: recomputed cheaply at reference sizes
    groups = q.shape[2] // k.shape[2]
    kr = jnp.repeat(k, groups, axis=2) if groups > 1 else k
    s = jnp.einsum("bqhd,bkhd->bhqk", q, kr,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        mask = jnp.tril(jnp.ones((q.shape[1], kr.shape[1]), bool))
        s = jnp.where(mask[None, None], s, NEG_INF)
    lse = jax.nn.logsumexp(s, axis=-1)                         # [B,H,Sq]
    return out, lse


def _flash_fwd_rule(q, k, v, causal, scale, block_q, block_k, interpret):
    out, lse = _fwd(q, k, v, causal, scale, block_q, block_k, interpret)
    # Tag the residuals so a `save_attn` remat policy (models/llama.py)
    # keeps them across the layer checkpoint: the backward then reads the
    # saved out/lse instead of replaying the whole attention forward —
    # the standard large-model policy (save softmax stats, recompute the
    # cheap projections).
    from jax.ad_checkpoint import checkpoint_name
    out = checkpoint_name(out, "flash_out")
    lse = checkpoint_name(lse, "flash_lse")
    return out, (q, k, v, out, lse)


def _flash_bwd_rule(causal, scale, block_q, block_k, interpret, res, g):
    q, k, v, out, lse = res
    if scale is None:
        scale = q.shape[-1] ** -0.5
    if interpret or _on_tpu():
        return _flash_bwd(q, k, v, out, lse, g, causal=causal, scale=scale,
                          block_q=block_q, block_k=block_k,
                          interpret=interpret)
    return _bwd_blockwise(res, g, causal=causal, scale=scale, block_k=block_k)


flash_attention.defvjp(_flash_fwd_rule, _flash_bwd_rule)
