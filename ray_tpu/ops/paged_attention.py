"""Paged decode attention for TPU (Pallas): block-table KV cache.

The serving engine's KV cache is a pool of fixed-size pages
(`k_pages/v_pages [num_pages, page_size, KVH, D]`); each sequence owns a
list of page ids (`block_table [B, max_pages]`, lengths `[B]`). One decode
step attends each query row over exactly the pages its sequence owns —
HBM traffic scales with the sequence's true length, not the pool capacity.

Kernel shape (the ragged-paged-attention idea from PAPERS.md, original
implementation): grid (batch, max_pages) with the block table scalar-
prefetched so the K/V page BlockSpec index maps select each sequence's
physical page; a streaming-softmax accumulator in VMEM scratch carries
across the page sweep; pages at or beyond the sequence's page count are
skipped (`pl.when`), and the tail page is masked by position.

Reference role (not design): vLLM's paged attention under
llm/_internal/serve/deployments/llm/vllm/vllm_engine.py:180 — the
reference orchestrates it, the kernel itself is ours.

This decode-specialized kernel is the ancestor of the GENERAL family in
ops/ragged_paged_attention.py (variable query windows: prefill chunks,
verify windows, decode as q_len=1), which the serving dispatch now
routes through; it stays as the q_len=1 equivalence baseline and the
home of the decode jnp oracle.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def _paged_decode_kernel(bt_ref, len_ref,                 # scalar prefetch
                         q_ref, k_ref, v_ref,             # blocks
                         o_ref,                           # output
                         acc_ref, m_ref, l_ref,           # VMEM scratch
                         *, scale: float, page_size: int, num_kv_heads: int,
                         groups: int, max_pages: int):
    from jax.experimental import pallas as pl

    b = pl.program_id(0)
    p = pl.program_id(1)

    @pl.when(p == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    length = len_ref[b]
    n_pages = (length + page_size - 1) // page_size

    @pl.when(p < n_pages)
    def _compute():
        q = q_ref[:, :]                                   # [H, D]
        k_pos = p * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (1, page_size), 1)                 # [1, page]
        valid = k_pos < length
        # per-kv-head static loop: each query group attends its kv head
        rows = []
        for h in range(num_kv_heads):
            q_sub = q[h * groups:(h + 1) * groups, :]     # [G, D]
            k_sub = k_ref[:, h, :]                        # [page, D]
            s = jax.lax.dot_general(
                q_sub, k_sub, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * scale   # [G, page]
            rows.append(s)
        s = jnp.concatenate(rows, axis=0)                 # [H, page]
        s = jnp.where(valid, s, NEG_INF)
        m_prev, l_prev = m_ref[:], l_ref[:]
        m_cur = jnp.max(s, axis=-1)[:, None]
        m_new = jnp.maximum(m_prev, m_cur)
        pexp = jnp.exp(s - m_new)
        pexp = jnp.where(valid, pexp, 0.0)
        alpha = jnp.exp(m_prev - m_new)
        l_ref[:] = l_prev * alpha + jnp.sum(pexp, axis=-1)[:, None]
        m_ref[:] = m_new
        pvs = []
        for h in range(num_kv_heads):
            p_sub = pexp[h * groups:(h + 1) * groups, :]  # [G, page]
            v_sub = v_ref[:, h, :]                        # [page, D]
            pvs.append(jax.lax.dot_general(
                p_sub.astype(v_sub.dtype), v_sub, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32))      # [G, D]
        pv = jnp.concatenate(pvs, axis=0)                 # [H, D]
        acc_ref[:] = acc_ref[:] * alpha + pv

    @pl.when(p == max_pages - 1)
    def _finalize():
        l = jnp.maximum(l_ref[:], 1e-30)                  # noqa: E741
        o_ref[:, :] = (acc_ref[:] / l).astype(o_ref.dtype)


def paged_decode_attention(q, k_pages, v_pages, block_table, lengths,
                           *, scale: float | None = None,
                           interpret: bool = False):
    """q [B, H, D]; k_pages/v_pages [P, page, KVH, D];
    block_table [B, max_pages] int32 (physical page per logical page);
    lengths [B] int32 (tokens already in cache INCLUDING current step's —
    i.e. attend over positions < length). Returns [B, H, D]."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, d = q.shape
    _, page_size, kvh, _ = k_pages.shape
    groups = h // kvh
    max_pages = block_table.shape[1]
    if scale is None:
        scale = d ** -0.5

    kernel = functools.partial(
        _paged_decode_kernel, scale=scale, page_size=page_size,
        num_kv_heads=kvh, groups=groups, max_pages=max_pages)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, max_pages),
        in_specs=[
            pl.BlockSpec((None, h, d), lambda bi, p, bt, ln: (bi, 0, 0)),
            # the physical page for (sequence bi, logical page p) comes from
            # the scalar-prefetched block table
            pl.BlockSpec((None, page_size, kvh, d),
                         lambda bi, p, bt, ln: (bt[bi, p], 0, 0, 0)),
            pl.BlockSpec((None, page_size, kvh, d),
                         lambda bi, p, bt, ln: (bt[bi, p], 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((None, h, d), lambda bi, p, bt, ln: (bi, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((h, d), jnp.float32),
            pltpu.VMEM((h, 1), jnp.float32),
            pltpu.VMEM((h, 1), jnp.float32),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, h, d), q.dtype),
        interpret=interpret,
    )(block_table, lengths, q, k_pages, v_pages)


def paged_decode_reference(q, k_pages, v_pages, block_table, lengths,
                           scale: float | None = None):
    """Numerical oracle (jnp gather). Same contract as the kernel.

    GQA runs as a grouped einsum against the ungathered-head K/V
    (q reshaped [B, KVH, G, D]) — the head axes line up by construction
    (query head h attends kv head h // G), so no O(groups) jnp.repeat
    materialization of the gathered cache is ever built."""
    b, h, d = q.shape
    p_total, page_size, kvh, _ = k_pages.shape
    groups = h // kvh
    max_pages = block_table.shape[1]
    if scale is None:
        scale = d ** -0.5
    # gather each sequence's pages -> [B, max_pages*page, KVH, D]
    k = k_pages[block_table].reshape(b, max_pages * page_size, kvh, d)
    v = v_pages[block_table].reshape(b, max_pages * page_size, kvh, d)
    qg = q.reshape(b, kvh, groups, d).astype(jnp.float32)
    s = jnp.einsum("bhgd,bkhd->bhgk", qg,
                   k.astype(jnp.float32)) * scale
    pos = jnp.arange(max_pages * page_size)[None, :]
    s = jnp.where((pos < lengths[:, None])[:, None, None], s, NEG_INF)
    w = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhgk,bkhd->bhgd", w,
                      v.astype(jnp.float32)).reshape(b, h, d).astype(q.dtype)
