"""Ragged paged attention for TPU (Pallas): ONE kernel family for every
paged-KV attention dispatch — prefill chunks, speculative-verify windows,
and decode (the q_len=1 degenerate case).

Reproduces the design of "Ragged Paged Attention: A High-Performance and
Flexible LLM Inference Kernel for TPU" (PAPERS.md): each row of a dispatch
is a variable-length query window `[start, start + q_len)` attending over
that row's paged prefix PLUS itself, with the window's own K/V already
scattered into the pages (the engine writes K/V before attention on every
path, so the kernel never needs a separate in-window concat). HBM traffic
is proportional to each row's TRUE length, not the pool capacity:

* grid ``(row, kv_pages)`` with the block table scalar-prefetched so the
  K/V page BlockSpec index maps select each row's physical pages;
* the index map CLAMPS the logical page to the row's last live page, so
  grid steps at/beyond the live page count re-request the block already
  resident and the pipeline elides the fetch — pages a row doesn't own
  are neither read nor computed (`pl.when` skips the body);
* a streaming-softmax accumulator in VMEM scratch carries across the
  page sweep (TPU grids iterate the last dimension fastest, so scratch
  persists across one row's sweep — same contract as
  `ops/paged_attention._paged_decode_kernel`);
* causal masking INSIDE the query window: key position ``k_pos`` is
  attended by query position ``q_pos = start + i`` iff ``k_pos <= q_pos``
  — which covers the prefix (always attended) and the window (causal)
  with one predicate;
* GQA by the static per-kv-head loop proven in the decode kernel: each
  group of ``groups`` query heads runs a [Q*G, page] MXU tile against its
  kv head's [page, D] block — no jnp.repeat materialization anywhere.

Row layout convention (everything else follows from it): the flattened
score/accumulator row index is ``h_kv * (Q * G) + q * G + g`` — per-kv-head
blocks, query-major within a block — because per-kv-head q slices
``q[:, h*G:(h+1)*G, :]`` reshape contiguously to [Q*G, D].

The pure-jnp oracle (`ragged_paged_reference`) uses the same
grouped-einsum GQA form and is the CPU fallback's numerical contract;
the kernel runs under ``interpret=True`` in tier-1 so parity is asserted
without a TPU.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

# one masking constant for the whole paged family: the q_len=1
# equivalence baseline (ops/paged_attention) must mask identically
from .paged_attention import NEG_INF


def _ragged_kernel(bt_ref, start_ref, qlen_ref,       # scalar prefetch
                   q_ref, k_ref, v_ref,               # blocks
                   o_ref,                             # output
                   acc_ref, m_ref, l_ref,             # VMEM scratch
                   *, scale: float, page_size: int, num_kv_heads: int,
                   groups: int, q_window: int, max_pages: int):
    from jax.experimental import pallas as pl

    r = pl.program_id(0)
    p = pl.program_id(1)

    @pl.when(p == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    start = start_ref[r]
    q_len = qlen_ref[r]
    kv_len = start + q_len                 # positions < kv_len are live
    n_pages = (kv_len + page_size - 1) // page_size

    @pl.when(p < n_pages)
    def _compute():
        qg = q_window * groups
        q = q_ref[...]                                    # [Q, H, D]
        rows = []
        for h in range(num_kv_heads):
            q_sub = q[:, h * groups:(h + 1) * groups, :].reshape(qg, -1)
            k_sub = k_ref[:, h, :]                        # [page, D]
            rows.append(jax.lax.dot_general(
                q_sub, k_sub, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * scale)
        s = jnp.concatenate(rows, axis=0)                 # [KVH*Q*G, page]
        n_rows = num_kv_heads * qg
        # row index -> query index (row layout: h*(Q*G) + q*G + g)
        q_idx = (jax.lax.broadcasted_iota(
            jnp.int32, (n_rows, page_size), 0) // groups) % q_window
        q_pos = start + q_idx
        k_pos = p * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (n_rows, page_size), 1)
        # one predicate covers prefix (k_pos < start <= q_pos) and the
        # causal window; k_pos < kv_len additionally hides stale K/V in
        # the tail page for PAD queries whose q_pos exceeds the row
        keep = (k_pos <= q_pos) & (k_pos < kv_len)
        s = jnp.where(keep, s, NEG_INF)
        m_prev, l_prev = m_ref[:], l_ref[:]
        m_cur = jnp.max(s, axis=-1)[:, None]
        m_new = jnp.maximum(m_prev, m_cur)
        pexp = jnp.exp(s - m_new)
        pexp = jnp.where(keep, pexp, 0.0)
        alpha = jnp.exp(m_prev - m_new)
        l_ref[:] = l_prev * alpha + jnp.sum(pexp, axis=-1)[:, None]
        m_ref[:] = m_new
        pvs = []
        for h in range(num_kv_heads):
            p_sub = pexp[h * qg:(h + 1) * qg, :]          # [Q*G, page]
            v_sub = v_ref[:, h, :]                        # [page, D]
            pvs.append(jax.lax.dot_general(
                p_sub.astype(v_sub.dtype), v_sub, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32))      # [Q*G, D]
        pv = jnp.concatenate(pvs, axis=0)                 # [KVH*Q*G, D]
        acc_ref[:] = acc_ref[:] * alpha + pv

    @pl.when(p == max_pages - 1)
    def _finalize():
        qg = q_window * groups
        l = jnp.maximum(l_ref[:], 1e-30)                  # noqa: E741
        o = acc_ref[:] / l                                # [KVH*Q*G, D]
        for h in range(num_kv_heads):
            blk = o[h * qg:(h + 1) * qg, :].reshape(
                q_window, groups, -1)
            o_ref[:, h * groups:(h + 1) * groups, :] = blk.astype(
                o_ref.dtype)


def ragged_paged_attention(q, k_pages, v_pages, block_tables, starts,
                           q_lens, *, scale: float | None = None,
                           interpret: bool = False):
    """q [R, Q, H, D]; k_pages/v_pages [P, page, KVH, D];
    block_tables [R, max_pages] int32 (physical page per logical page);
    starts [R] int32 (position of each row's first query token);
    q_lens [R] int32 (true query tokens this row, <= Q; 0 = padding row).

    Row r's queries sit at positions ``starts[r] + i`` and attend every
    key position ``<= starts[r] + i`` (paged prefix + causal window); the
    window's OWN K/V must already be scattered into the pages. Query
    positions ``i >= q_lens[r]`` produce garbage outputs the caller
    discards (their compute is bounded by the row's live pages). Returns
    [R, Q, H, D].
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    r, qw, h, d = q.shape
    _, page_size, kvh, _ = k_pages.shape
    groups = h // kvh
    max_pages = block_tables.shape[1]
    if scale is None:
        scale = d ** -0.5

    kernel = functools.partial(
        _ragged_kernel, scale=scale, page_size=page_size,
        num_kv_heads=kvh, groups=groups, q_window=qw, max_pages=max_pages)

    def _kv_index(ri, p, bt, start, qlen):
        # clamp to the row's last live page: grid steps beyond the live
        # count re-request the resident block (fetch elided), so HBM
        # traffic tracks true length even when the table tail is stale
        n = (start[ri] + qlen[ri] + page_size - 1) // page_size
        return (bt[ri, jnp.minimum(p, jnp.maximum(n - 1, 0))], 0, 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(r, max_pages),
        in_specs=[
            pl.BlockSpec((None, qw, h, d),
                         lambda ri, p, bt, st, ql: (ri, 0, 0, 0)),
            pl.BlockSpec((None, page_size, kvh, d), _kv_index),
            pl.BlockSpec((None, page_size, kvh, d), _kv_index),
        ],
        out_specs=pl.BlockSpec((None, qw, h, d),
                               lambda ri, p, bt, st, ql: (ri, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((kvh * qw * groups, d), jnp.float32),
            pltpu.VMEM((kvh * qw * groups, 1), jnp.float32),
            pltpu.VMEM((kvh * qw * groups, 1), jnp.float32),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((r, qw, h, d), q.dtype),
        interpret=interpret,
    )(block_tables, starts, q_lens, q, k_pages, v_pages)


def ragged_decode_attention(q, k_pages, v_pages, block_table, lengths,
                            *, scale: float | None = None,
                            interpret: bool = False):
    """Decode as the q_len=1 degenerate case. Same contract as
    ``ops.paged_attention.paged_decode_attention``: q [B, H, D],
    lengths [B] = tokens in cache INCLUDING the current step's (attend
    positions < length). Returns [B, H, D]."""
    lengths = lengths.astype(jnp.int32)
    out = ragged_paged_attention(
        q[:, None], k_pages, v_pages, block_table,
        starts=jnp.maximum(lengths - 1, 0),
        q_lens=jnp.minimum(lengths, 1),     # length 0 rows = padding
        scale=scale, interpret=interpret)
    return out[:, 0]


def ragged_paged_reference(q, k_pages, v_pages, block_tables, starts,
                           q_lens, scale: float | None = None):
    """Numerical oracle (jnp gather, grouped-GQA einsum — no repeat).
    Same contract as the kernel; masks exactly the kernel's live-key
    predicate, so outputs match at every query position i < q_lens[r]."""
    r, qw, h, d = q.shape
    _, page_size, kvh, _ = k_pages.shape
    groups = h // kvh
    max_pages = block_tables.shape[1]
    klen = max_pages * page_size
    if scale is None:
        scale = d ** -0.5
    k = k_pages[block_tables].reshape(r, klen, kvh, d)
    v = v_pages[block_tables].reshape(r, klen, kvh, d)
    qg = q.reshape(r, qw, kvh, groups, d).astype(jnp.float32)
    s = jnp.einsum("rqhgd,rkhd->rhgqk", qg,
                   k.astype(jnp.float32)) * scale
    k_pos = jnp.arange(klen)
    q_pos = starts[:, None] + jnp.arange(qw)[None, :]
    kv_len = starts + q_lens
    keep = (k_pos[None, None, :] <= q_pos[:, :, None]) & \
        (k_pos[None, None, :] < kv_len[:, None, None])    # [R, Q, K]
    s = jnp.where(keep[:, None, None], s, NEG_INF)
    w = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("rhgqk,rkhd->rqhgd", w, v.astype(jnp.float32))
    return out.reshape(r, qw, h, d).astype(q.dtype)
