"""ray_tpu.parallel — TPU-native parallelism substrate.

This package is the TPU-first replacement for the reference's accelerator
communication stack (ray.util.collective NCCL/Gloo groups —
python/ray/util/collective/collective.py:150 — and torch-NCCL process groups
set up by Train, train/torch/config.py:115). The design inversion (SURVEY.md
§7): inside a slice the XLA compiler owns communication, so parallelism is
expressed as shardings over a `jax.sharding.Mesh` and `jax.lax` collectives
inside compiled programs; the actor runtime only coordinates hosts/slices.

Modules:
  mesh        — device-mesh construction, axis conventions, TPU topology
  sharding    — logical-axis rules → NamedSharding, constraint helpers
  collective  — actor-level collective groups (control-plane; the reference
                API surface of ray.util.collective) implemented over the
                object store, plus in-program XLA collective helpers
  ring        — sequence/context parallelism: ring attention and Ulysses
                all-to-all re-sharding (absent from the reference, SURVEY §5.7)
  pipeline    — GPipe schedule over the pp axis inside one SPMD program
                (the compiled-graph/aDAG pipeline analog, SURVEY §2.4 PP)
"""
import importlib

# Lazy (PEP 562) so that `import ray_tpu` (which every worker process does)
# doesn't pay the jax import; only code that actually touches meshes does.
_EXPORTS = {
    "MeshSpec": "mesh", "build_mesh": "mesh", "get_mesh": "mesh",
    "use_mesh": "mesh", "tpu_topology": "mesh", "TpuTopology": "mesh",
    "LOGICAL_AXIS_RULES": "sharding", "logical_sharding": "sharding",
    "logical_spec": "sharding", "named_sharding": "sharding",
    "shard_pytree": "sharding", "constrain": "sharding",
    "ring_attention": "ring", "ulysses_attention": "ring",
    "ring_attention_sharded": "ring", "ulysses_attention_sharded": "ring",
    "pipeline_apply": "pipeline", "split_stages": "pipeline",
    "stage_sharding": "pipeline",
}
_MODULES = ("mesh", "sharding", "collective", "ring", "pipeline")

__all__ = list(_EXPORTS) + list(_MODULES)


def __getattr__(name):
    if name in _EXPORTS:
        mod = importlib.import_module(f".{_EXPORTS[name]}", __name__)
        return getattr(mod, name)
    if name in _MODULES:
        return importlib.import_module(f".{name}", __name__)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
