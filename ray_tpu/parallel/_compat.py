"""jax API version compatibility for the parallel layer.

`shard_map` moved twice upstream: jax < 0.6 ships it as
``jax.experimental.shard_map.shard_map`` with a ``check_rep`` flag; newer
releases export ``jax.shard_map`` with ``check_rep`` renamed to
``check_vma``. The container pins jax 0.4.x while the code targets the
current API, which broke every shard_map-based test with
``AttributeError: module 'jax' has no attribute 'shard_map'``. This shim
presents ONE surface (the current one: keyword mesh/in_specs/out_specs +
``check_vma``) over whichever implementation is importable.
"""
from __future__ import annotations

from typing import Any


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: Any = None):
    """Current-API shard_map over whichever jax provides.

    ``check_vma`` maps onto the old API's ``check_rep`` (same meaning:
    verify replication invariants of the out_specs); None means the
    implementation default.
    """
    import jax
    impl = getattr(jax, "shard_map", None)
    if impl is not None:  # jax >= 0.6: the current API, pass through
        kwargs = {} if check_vma is None else {"check_vma": check_vma}
        return impl(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                    **kwargs)
    from jax.experimental.shard_map import shard_map as legacy
    kwargs = {} if check_vma is None else {"check_rep": check_vma}
    return legacy(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  **kwargs)


def axis_size(axis_name) -> int:
    """Static size of a named mesh axis, from inside shard_map.

    ``jax.lax.axis_size`` only exists on newer jax; on older releases
    ``psum(1, axis)`` of the Python constant is constant-folded to the
    axis size as a plain int (the long-standing pmap idiom)."""
    import jax
    fn = getattr(jax.lax, "axis_size", None)
    if fn is not None:
        return fn(axis_name)
    return jax.lax.psum(1, axis_name)
