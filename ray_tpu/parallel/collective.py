"""Actor-level collective communication groups.

Reference parity: python/ray/util/collective/collective.py (API surface:
init_collective_group :150, allreduce :295, allgather :460, reducescatter
:509, send :568, recv :631) with its NCCL/Gloo backends replaced by two
TPU-native paths:

* **In-program collectives** — the hot path. Gradient/activation traffic
  rides XLA collectives (`jax.lax.psum/all_gather/ppermute/...`) compiled
  over the mesh (see parallel.ring for the sequence-parallel patterns). No
  runtime involvement at all; this module is NOT that path.
* **"shm" backend (this module)** — control-plane collectives *between
  actors/tasks* (parameter broadcast at init, metric reduction, rendezvous,
  cross-slice weight shuttling). Implemented over the shared-memory object
  store via a named rendezvous actor, the role Gloo plays in the reference's
  CPU backend (gloo_collective_group.py).

Semantics differ from the reference in one deliberate way: reference
collectives mutate torch tensors in place; jax arrays are immutable, so every
op here *returns* the result.

All ranks of a group must issue collectives in the same order (same
requirement as NCCL); ops are matched by per-group sequence number.
"""
from __future__ import annotations

import asyncio
import time
from typing import Any, Optional

import numpy as np

from ..core.ref import ObjectRef


class ReduceOp:
    SUM = "sum"
    PRODUCT = "product"
    MIN = "min"
    MAX = "max"


_REDUCERS = {
    ReduceOp.SUM: lambda parts: np.sum(parts, axis=0),
    ReduceOp.PRODUCT: lambda parts: np.prod(parts, axis=0),
    ReduceOp.MIN: lambda parts: np.min(parts, axis=0),
    ReduceOp.MAX: lambda parts: np.max(parts, axis=0),
}

_COORD_PREFIX = "rtpu:collective:"


class _Rendezvous:
    """Named async actor that matches collective ops across ranks.

    Async methods let all ranks' calls interleave on one asyncio loop
    (worker-side async actor execution), so a rank can park in `await` until
    the op completes — one round-trip per collective.
    """

    def __init__(self, world_size: int):
        self.world = world_size
        self.epoch = 0
        self._pending: dict[str, dict] = {}
        self._joining: dict = {"ranks": set(), "event": asyncio.Event(),
                               "result": None}
        # observability: bulk payload bytes that flowed THROUGH this actor.
        # The *_refs kinds keep this near zero — bulk data moves
        # store-to-store and only ObjectRefs pass here (the role split the
        # reference gets from NCCL transports vs the gloo CPU store).
        self.payload_bytes = 0

    def world_size(self) -> int:
        return self.world

    def stats(self) -> dict:
        return {"payload_bytes": self.payload_bytes}

    async def join(self, rank: int) -> int:
        """Barrier that admits a (re-)initializing group generation.

        Completes when all `world` ranks have joined; returns a fresh epoch
        that namespaces all op keys, so a restarted rank that re-inits
        together with the surviving ranks gets aligned sequence numbers and
        stale entries from the previous epoch are dropped.
        """
        j = self._joining
        j["ranks"].add(rank)
        if len(j["ranks"]) == self.world:
            self.epoch += 1
            self._pending.clear()
            j["result"] = self.epoch
            j["event"].set()
            self._joining = {"ranks": set(), "event": asyncio.Event(),
                             "result": None}
        await j["event"].wait()
        return j["result"]

    def _entry(self, key: str, world: int) -> dict:
        e = self._pending.get(key)
        if e is None:
            e = {"parts": {}, "event": asyncio.Event(), "result": None,
                 "fetched": 0, "world": world}
            self._pending[key] = e
        return e

    async def _finish(self, key: str, e: dict, my: Any):
        await e["event"].wait()
        result = e["result"] if my is None else my(e)
        e["fetched"] += 1
        if e["fetched"] >= e["world"]:
            del self._pending[key]
        return result

    async def gather_op(self, key: str, rank: int, payload, kind: str,
                        op: str = ReduceOp.SUM, src: int = 0):
        """allreduce / allgather / reducescatter / broadcast / barrier.

        Each rank's `payload` is EITHER inline data (small: an ndarray, or
        a per-destination chunk list of ndarrays for reducescatter) or its
        store-backed form (bulk: a [ObjectRef], or a chunk list of
        ObjectRefs) — ranks may mix freely since the threshold is a
        per-process config. This actor only MATCHES and routes; combining
        (reduce/concat) happens on the ranks after they deref, so bulk
        bytes never flow through here."""
        if isinstance(payload, np.ndarray):
            self.payload_bytes += payload.nbytes
        elif isinstance(payload, list):
            self.payload_bytes += sum(p.nbytes for p in payload
                                      if isinstance(p, np.ndarray))
        e = self._entry(key, self.world)
        e["parts"][rank] = payload
        if len(e["parts"]) == e["world"]:
            parts = [e["parts"][r] for r in range(e["world"])]
            if kind in ("allreduce", "allgather", "reducescatter"):
                e["result"] = parts
            elif kind == "broadcast":
                e["result"] = e["parts"][src]
            elif kind == "barrier":
                e["result"] = True
            else:
                raise ValueError(f"unknown collective kind {kind!r}")
            e["event"].set()
        if kind == "reducescatter":
            world = e["world"]

            def my(e):
                # rank r takes the r-th chunk of every contribution:
                # pre-chunked lists index directly; inline full tensors
                # slice here (np.array_split boundaries, matching the
                # chunking the bulk path used)
                out = []
                for part in e["result"]:
                    if isinstance(part, list):
                        out.append(part[rank])
                    else:
                        out.append(np.array_split(part, world)[rank])
                return out
            return await self._finish(key, e, my)
        return await self._finish(key, e, None)

    async def p2p_send(self, key: str, payload):
        if isinstance(payload, np.ndarray):
            self.payload_bytes += payload.nbytes
        e = self._entry(key, 2)
        e["result"] = payload
        e["event"].set()
        e["fetched"] += 1  # sender never fetches
        if e["fetched"] >= 2:
            del self._pending[key]

    async def p2p_recv(self, key: str):
        e = self._entry(key, 2)
        return await self._finish(key, e, None)


class _GroupState:
    def __init__(self, name: str, handle, rank: int, world: int, epoch: int):
        self.name = name
        self.handle = handle
        self.rank = rank
        self.world = world
        self.epoch = epoch
        self.seq = 0
        self.p2p_seq: dict[tuple[int, int], int] = {}

    def next_key(self, kind: str) -> str:
        self.seq += 1
        return f"e{self.epoch}:{kind}:{self.seq}"

    def next_p2p_key(self, src: int, dst: int) -> str:
        n = self.p2p_seq.get((src, dst), 0) + 1
        self.p2p_seq[(src, dst)] = n
        return f"e{self.epoch}:p2p:{src}:{dst}:{n}"


_groups: dict[str, _GroupState] = {}


def _ray():
    import ray_tpu
    return ray_tpu


def _coordinator_actor(name: str, world_size: int, rank: int,
                       timeout: float = 60.0):
    """Rank 0 creates (or resets) the named rendezvous actor; others poll."""
    ray = _ray()
    actor_name = _COORD_PREFIX + name
    if rank == 0:
        from .. import exceptions as exc
        try:
            h = ray.get_actor(actor_name)
            # Reusing a live group name: join it (no state reset — other
            # ranks may already have posted their init barrier). Changing
            # world size requires destroy_collective_group first.
            if ray.get(h.world_size.remote()) != world_size:
                raise RuntimeError(
                    f"collective group {name!r} already exists with a "
                    f"different world size; destroy_collective_group first")
            return h
        except ValueError:
            pass  # no such actor: create below
        except exc.ActorDiedError:
            pass  # stale registration of a just-destroyed coordinator:
            # fall through to create (its retry loop waits out the name)
        cls = ray.remote(_Rendezvous)
        deadline = time.monotonic() + 5.0
        while True:
            try:
                return cls.options(name=actor_name,
                                   max_concurrency=256).remote(world_size)
            except ValueError:
                # name still registered to a just-killed predecessor
                # (destroy → re-init race); cleared on its death event
                if time.monotonic() > deadline:
                    raise
                time.sleep(0.05)
    deadline = time.monotonic() + timeout
    while True:
        try:
            return ray.get_actor(actor_name)
        except ValueError:
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"collective group {name!r}: rank 0 never created the "
                    f"rendezvous actor") from None
            time.sleep(0.05)


def init_collective_group(world_size: int, rank: int,
                          backend: str = "shm",
                          group_name: str = "default",
                          timeout: float = 300.0) -> None:
    """Join this process to a collective group (reference:
    collective.py:150). Must be called by every rank, any order.

    Known limitation (round 1): if a rank crashes *between* posting its join
    and the rest of the group joining, its stale join is still counted for
    that generation; recovery is destroy_collective_group + full re-init by
    all live ranks. `timeout` bounds the hang and surfaces the error.
    """
    if backend not in ("shm", "xla"):
        raise ValueError(f"backend must be 'shm' or 'xla', got {backend!r}")
    if not 0 <= rank < world_size:
        raise ValueError(f"rank {rank} out of range for world {world_size}")
    from .. import exceptions as exc
    deadline = time.monotonic() + timeout
    while True:
        handle = _coordinator_actor(group_name, world_size, rank, timeout)
        try:
            # barrier: all ranks joined; bounded so a missing rank raises
            epoch = _ray().get(handle.join.remote(rank), timeout=timeout)
            break
        except exc.ActorDiedError:
            # destroy→re-init race: the name resolved to a dying (or, from a
            # worker, a never-registered duplicate-named) coordinator. Retry
            # until the old registration clears.
            if time.monotonic() > deadline:
                raise
            time.sleep(0.1)
    _groups[group_name] = _GroupState(group_name, handle, rank, world_size,
                                      epoch)


def destroy_collective_group(group_name: str = "default") -> None:
    """Tear down the group's rendezvous actor. Callable from any rank or from
    a non-member driver that set the group up via create_collective_group."""
    ray = _ray()
    _groups.pop(group_name, None)
    try:
        ray.kill(ray.get_actor(_COORD_PREFIX + group_name))
    except Exception:
        pass  # already dead / never created


def is_group_initialized(group_name: str = "default") -> bool:
    return group_name in _groups


def get_rank(group_name: str = "default") -> int:
    return _group(group_name).rank


def get_collective_group_size(group_name: str = "default") -> int:
    return _group(group_name).world


def _group(name: str) -> _GroupState:
    st = _groups.get(name)
    if st is None:
        raise RuntimeError(
            f"collective group {name!r} is not initialized in this process; "
            "call init_collective_group() first")
    return st


def _to_host(tensor) -> np.ndarray:
    return np.asarray(tensor)


def _collective(kind: str, tensor, group_name: str, op: str = ReduceOp.SUM,
                src: int = 0):
    ray = _ray()
    st = _group(group_name)
    key = st.next_key(kind)
    payload = None if tensor is None else _to_host(tensor)
    from ..core.config import cfg
    bulk = payload is not None and payload.nbytes > cfg.collective_inline_bytes
    if kind == "barrier":
        return ray.get(st.handle.gather_op.remote(
            key, st.rank, None, kind, op, src))
    # This rank's contribution: inline ndarray when small, store-backed
    # when bulk — bulk bytes live in the object store (crossing nodes via
    # the transfer service) and only ObjectRefs visit the rendezvous
    # actor. Refs ride NESTED in lists because top-level ObjectRef args
    # auto-resolve at the callee (reference semantics). Ranks may disagree
    # on the threshold (it's per-process config): the protocol composes
    # either form.
    if kind == "broadcast":
        contrib = None
        if st.rank == src:
            contrib = [ray.put(payload)] if bulk else payload
        res = ray.get(st.handle.gather_op.remote(
            key, st.rank, contrib, kind, op, src))
        return _deref(ray, res)
    if kind == "reducescatter":
        # per-destination chunks: rank r pulls ONLY the r-th chunk of
        # every peer, so total bytes on the wire equal one tensor
        chunks = np.array_split(payload, st.world, axis=0)
        contrib = [ray.put(c) for c in chunks] if bulk else chunks
        mine = ray.get(st.handle.gather_op.remote(
            key, st.rank, contrib, kind, op, src))
        return _REDUCERS[op]([_deref(ray, c) for c in mine])
    # allreduce / allgather
    contrib = [ray.put(payload)] if bulk else payload
    res = ray.get(st.handle.gather_op.remote(
        key, st.rank, contrib, kind, op, src))
    parts = [_deref(ray, p) for p in res]
    if kind == "allreduce":
        return _REDUCERS[op](parts)
    return parts  # allgather


def _deref(ray, res):
    """Resolve a store-backed contribution ([ObjectRef] or a bare ref);
    pass inline ndarrays through."""
    if isinstance(res, ObjectRef):
        return ray.get(res)
    if isinstance(res, list) and len(res) == 1 \
            and isinstance(res[0], ObjectRef):
        return ray.get(res[0])
    return res


def allreduce(tensor, group_name: str = "default",
              op: str = ReduceOp.SUM):
    """Reduce across all ranks; returns the reduced array
    (reference: collective.py:295)."""
    return _collective("allreduce", tensor, group_name, op)


def allgather(tensor, group_name: str = "default") -> list:
    """Returns list of every rank's tensor, ordered by rank
    (reference: collective.py:460)."""
    return _collective("allgather", tensor, group_name)


def reducescatter(tensor, group_name: str = "default",
                  op: str = ReduceOp.SUM):
    """Reduce then scatter along axis 0: rank r gets the r-th 1/world chunk
    (reference: collective.py:509)."""
    st = _group(group_name)
    t = _to_host(tensor)
    if t.shape[0] % st.world:
        raise ValueError(
            f"reducescatter dim0 {t.shape[0]} not divisible by world "
            f"{st.world}")
    return _collective("reducescatter", t, group_name, op)


def _check_rank(st: _GroupState, r: int, what: str):
    if not 0 <= r < st.world:
        raise ValueError(
            f"{what} {r} out of range for world size {st.world}")


def broadcast(tensor, src_rank: int = 0,
              group_name: str = "default"):
    """Every rank gets src_rank's tensor (reference: collective.py:403).
    Only src_rank's payload is shipped; other ranks contribute None."""
    st = _group(group_name)
    _check_rank(st, src_rank, "src_rank")
    payload = tensor if st.rank == src_rank else None
    return _collective("broadcast", payload, group_name, src=src_rank)


def barrier(group_name: str = "default") -> None:
    """Block until every rank arrives (reference: collective.py:683)."""
    _collective("barrier", None, group_name)


def send(tensor, dst_rank: int, group_name: str = "default") -> None:
    """Point-to-point send (reference: collective.py:568)."""
    ray = _ray()
    st = _group(group_name)
    _check_rank(st, dst_rank, "dst_rank")
    if dst_rank == st.rank:
        raise ValueError("cannot send to self")
    key = st.next_p2p_key(st.rank, dst_rank)
    from ..core.config import cfg
    payload = _to_host(tensor)
    if payload.nbytes > cfg.collective_inline_bytes:
        # bulk: receiver pulls store-to-store ([ref]: nested so the actor
        # arg does not auto-resolve)
        payload = [ray.put(payload)]
    ray.get(st.handle.p2p_send.remote(key, payload))


def recv(src_rank: int, group_name: str = "default"):
    """Point-to-point receive; returns the array (reference:
    collective.py:631 — reference writes into a passed tensor instead)."""
    ray = _ray()
    st = _group(group_name)
    _check_rank(st, src_rank, "src_rank")
    if src_rank == st.rank:
        raise ValueError("cannot recv from self")
    key = st.next_p2p_key(src_rank, st.rank)
    return _deref(ray, ray.get(st.handle.p2p_recv.remote(key)))


def create_collective_group(actors, world_size: int, ranks: list[int],
                            backend: str = "shm",
                            group_name: str = "default"):
    """Driver-side declarative setup (reference: collective.py:210): tells
    each actor to join the group via its `init_collective_group` method or a
    generic __ray_call__ if it has one."""
    ray = _ray()
    refs = []
    for actor, rank in zip(actors, ranks):
        refs.append(actor.init_collective_group.remote(
            world_size, rank, backend, group_name))
    return ray.get(refs)
