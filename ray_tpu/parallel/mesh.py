"""Device-mesh construction and TPU topology discovery.

Replaces two reference components with one TPU-native abstraction:

* the accelerator manager's TPU topology discovery
  (python/ray/_private/accelerators/tpu.py:110 TPUAcceleratorManager — chip
  counts, pod/slice env introspection), and
* the process-group bootstrap that Train performs per worker
  (python/ray/train/torch/config.py:115 `dist.init_process_group`).

On TPU there is no user-space comm library to initialise: a
`jax.sharding.Mesh` laid out over the slice's ICI torus *is* the communicator.
Axis conventions (used by models/, train/, serve/):

  dp    data parallel              (gradient psum over ICI/DCN)
  fsdp  fully-sharded data parallel (params/optimizer sharded, all-gathered)
  tp    tensor parallel            (Megatron-style layer sharding)
  sp    sequence/context parallel  (ring attention / Ulysses, parallel.ring)
  ep    expert parallel            (MoE expert sharding)
  pp    pipeline parallel          (multi-slice MPMD stages)
"""
from __future__ import annotations

import contextlib
import dataclasses
import math
import threading
from typing import Optional, Sequence

import numpy as np

# Canonical mesh-axis order. ICI-dominant axes (tp, sp) go last so that
# mesh_utils places them on the innermost (fastest, most tightly coupled)
# physical axes of the torus; dp/pp ride DCN across slices.
AXIS_ORDER = ("pp", "dp", "fsdp", "ep", "sp", "tp")

# Batch-like logical dimensions shard over every data-ish axis.
BATCH_AXES = ("dp", "fsdp")


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Declarative mesh shape: axis name -> size; at most one -1 (inferred).

    MeshSpec(dp=-1, tp=4) on 32 devices resolves dp=8. By default
    (keep_unit_axes=True) ALL six axes appear in the mesh, size-1 ones
    included — so sharding rules can target any axis unconditionally. With
    keep_unit_axes=False only axes of size > 1 are kept.
    """

    pp: int = 1
    dp: int = 1
    fsdp: int = 1
    ep: int = 1
    sp: int = 1
    tp: int = 1
    keep_unit_axes: bool = True

    def resolved(self, n_devices: int) -> dict[str, int]:
        sizes = {a: getattr(self, a) for a in AXIS_ORDER}
        inferred = [a for a, s in sizes.items() if s == -1]
        if len(inferred) > 1:
            raise ValueError(f"at most one axis may be -1, got {inferred}")
        known = math.prod(s for s in sizes.values() if s != -1)
        if inferred:
            if n_devices % known:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes {sizes}")
            sizes[inferred[0]] = n_devices // known
        elif known != n_devices:
            raise ValueError(
                f"mesh {sizes} needs {known} devices, have {n_devices}")
        return sizes


def build_mesh(spec: MeshSpec | dict | None = None,
               devices: Optional[Sequence] = None,
               axis_names: Optional[Sequence[str]] = None):
    """Build a `jax.sharding.Mesh` from a MeshSpec over `devices`.

    Uses `jax.experimental.mesh_utils.create_device_mesh` on real TPU so axis
    ordering respects ICI topology (nearest-neighbour axes innermost); plain
    reshape on CPU/virtual devices.
    """
    import jax
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    if isinstance(spec, dict):
        spec = MeshSpec(**spec)
    if spec is None:
        spec = MeshSpec(dp=-1)
    sizes = spec.resolved(len(devices))
    if axis_names is None:
        axis_names = [a for a in AXIS_ORDER
                      if spec.keep_unit_axes or sizes[a] > 1]
        if not axis_names:
            axis_names = ["dp"]
    shape = tuple(sizes[a] for a in axis_names)

    if devices[0].platform == "tpu":
        from jax.experimental import mesh_utils
        try:
            dev_array = mesh_utils.create_device_mesh(
                shape, devices=devices, allow_split_physical_axes=True)
        except Exception as e:
            import warnings
            warnings.warn(
                f"mesh_utils.create_device_mesh failed ({e!r}); falling back "
                f"to naive device order — collective bandwidth may suffer "
                f"because mesh axes no longer follow ICI topology",
                RuntimeWarning, stacklevel=2)
            dev_array = np.asarray(devices).reshape(shape)
    else:
        dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, tuple(axis_names))


# ---------------------------------------------------------------------------
# Current-mesh context (the analog of torch.distributed's implicit default
# process group; everything in models/train resolves shardings against this).
# ---------------------------------------------------------------------------

_local = threading.local()


def get_mesh():
    """Current mesh set by `use_mesh`, or None."""
    return getattr(_local, "mesh", None)


@contextlib.contextmanager
def use_mesh(mesh):
    """Set the current mesh for this thread (nestable)."""
    prev = getattr(_local, "mesh", None)
    _local.mesh = mesh
    try:
        yield mesh
    finally:
        _local.mesh = prev


# ---------------------------------------------------------------------------
# TPU topology discovery (TPUAcceleratorManager parity, tpu.py:110)
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class TpuTopology:
    """What the scheduler needs to know about the attached TPU.

    `slice_granularity` is the key scheduling fact the reference encodes as
    TPU-pod head resources: ICI failure domains are whole slices, so placement
    groups gang-reserve slices (SURVEY.md §7 'elastic slice recovery').
    """

    generation: str          # "v4", "v5e", "v5p", "v6e", "cpu"
    num_devices: int         # addressable chips from this process
    num_slices: int
    devices_per_slice: int
    chips_per_host: int
    peak_flops_bf16: float   # per chip, for MFU accounting

    @property
    def total_peak_flops(self) -> float:
        return self.peak_flops_bf16 * self.num_devices


# Per-chip peak bf16 FLOP/s (public spec-sheet numbers).
_PEAK_BF16 = {
    "v2": 45e12 / 2,   # per chip (2 cores @ 22.5e12)
    "v3": 123e12 / 2,
    "v4": 275e12,
    "v5e": 197e12,
    "v5p": 459e12,
    "v6e": 918e12,
    "cpu": 1e11,       # nominal, keeps MFU math defined in tests
}


def _generation_of(device) -> str:
    kind = getattr(device, "device_kind", "").lower()
    for gen in ("v6e", "v5p", "v5e", "v4", "v3", "v2"):
        if gen in kind.replace(" ", "").replace("lite", "e").replace(
                "tpu", "").replace("-", ""):
            return gen
    return "cpu" if device.platform != "tpu" else "v5e"


def tpu_topology(devices: Optional[Sequence] = None) -> TpuTopology:
    """Discover topology from `jax.devices()` attributes.

    Unlike the reference (GCE metadata + GKE env probing, tpu.py:213-320),
    JAX's PJRT device objects expose coords/slice_index directly — no cloud
    metadata round-trips.
    """
    import jax
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    d0 = devices[0]
    gen = _generation_of(d0)
    slice_ids = {getattr(d, "slice_index", 0) for d in devices}
    num_slices = max(1, len(slice_ids))
    hosts = {getattr(d, "process_index", 0) for d in devices}
    return TpuTopology(
        generation=gen,
        num_devices=len(devices),
        num_slices=num_slices,
        devices_per_slice=len(devices) // num_slices,
        chips_per_host=max(1, len(devices) // max(1, len(hosts))),
        peak_flops_bf16=_PEAK_BF16.get(gen, _PEAK_BF16["v5e"]),
    )
