"""Pipeline parallelism over the `pp` mesh axis (GPipe schedule, SPMD).

Reference role: the reference has NO pipeline schedule of its own — PP runs
inside vLLM over Ray workers coordinated by compiled graphs
(dag/compiled_dag_node.py:808; SURVEY.md §2.4). On TPU the idiomatic
construction is the inverse: the schedule lives INSIDE one compiled SPMD
program. Each pp shard holds one stage's parameters; every schedule tick,
all stages run the same stage function on their current microbatch and
activations hop to the next stage with `lax.ppermute`. Autodiff flows
through the whole schedule (ppermute transposes to the reverse rotation),
so the backward pipeline needs no extra code — this is the
compiled-graph-channels analog with XLA owning the transfers (PAPERS.md
JaxPP-style, original implementation).

Schedule: GPipe — M microbatches through S stages in M + S - 1 ticks;
activation-memory trade is handled by jax.checkpoint over the stage fn.
"""
from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def pipeline_apply(stage_fn: Callable, stage_params, x: jax.Array,
                   mesh: Mesh, num_microbatches: int,
                   remat: bool = True, x_spec: P = P()) -> jax.Array:
    """Run `x` through a chain of pp-sharded stages.

    stage_fn(params_one_stage, h) -> h : one stage's computation (e.g. a
        `lax.scan` over its transformer layers).
    stage_params : pytree whose leaves have leading dim S (=mesh pp size),
        sharded P("pp") — leaf i is stage i's parameters.
    x [B, ...] : input activations, replicated over pp (embedding and head
        stay outside the pipeline: they're pp-replicated). `x_spec` shards
        the activation dims over OTHER mesh axes (e.g. P("dp") to compose
        pp with data parallelism — each (pp, dp) shard pipelines its local
        batch slice).
    Returns y [B, ...] — the last stage's output, replicated over pp,
    sharded per x_spec elsewhere.

    The per-shard batch must divide into num_microbatches equal
    microbatches.
    """
    from jax import shard_map  # current API (check_vma, not check_rep)

    S = mesh.shape.get("pp", 1)
    if S == 1:
        return stage_fn(jax.tree.map(lambda a: a[0], stage_params), x)
    M = num_microbatches
    fn = jax.checkpoint(stage_fn) if remat else stage_fn

    def inner(params, xs):
        # params: this shard's stage, leading dim 1 — squeeze it
        sp = jax.tree.map(lambda a: a[0], params)
        idx = jax.lax.axis_index("pp")
        b = xs.shape[0]
        mb = b // M
        xs = xs.reshape(M, mb, *xs.shape[1:])
        state = jnp.zeros_like(xs[0])
        outputs = jnp.zeros_like(xs)
        fwd = [(i, (i + 1) % S) for i in range(S)]
        for t in range(M + S - 1):
            # stage 0 injects microbatch t; others consume the carried state
            inject = xs[t] if t < M else jnp.zeros_like(xs[0])
            h = jnp.where(idx == 0, inject, state)
            h = fn(sp, h)
            # the last stage's tick t output is microbatch t-(S-1)
            if t >= S - 1:
                outputs = outputs.at[t - (S - 1)].set(
                    jnp.where(idx == S - 1, h, outputs[t - (S - 1)]))
            state = jax.lax.ppermute(h, "pp", fwd)
        # replicate the last stage's outputs to every pp shard
        outputs = jnp.where(idx == S - 1, outputs, 0.0)
        outputs = jax.lax.psum(outputs, "pp")
        return outputs.reshape(b, *outputs.shape[2:])

    per_shard = x.shape[0]
    for ax in (x_spec[0] if len(x_spec) else None,) :
        if ax is not None:
            names = (ax,) if isinstance(ax, str) else tuple(ax)
            for n in names:
                per_shard //= mesh.shape.get(n, 1)
    if per_shard % M:
        raise ValueError(
            f"per-shard batch {per_shard} must divide microbatches {M}")

    return shard_map(
        inner, mesh=mesh,
        in_specs=(P("pp"), x_spec),
        out_specs=x_spec,
        check_vma=False,
    )(stage_params, x)


def split_stages(stacked_layer_params, n_stages: int):
    """[L, ...] layer-stacked params -> [S, L/S, ...] stage-major params
    (shard dim 0 over pp)."""
    def reshape(a):
        L = a.shape[0]
        if L % n_stages:
            raise ValueError(f"{L} layers not divisible by {n_stages} stages")
        return a.reshape(n_stages, L // n_stages, *a.shape[1:])

    return jax.tree.map(reshape, stacked_layer_params)


def stage_sharding(mesh: Mesh):
    """NamedSharding placing stage-major params on the pp axis."""
    return NamedSharding(mesh, P("pp"))
