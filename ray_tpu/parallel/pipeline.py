"""Pipeline parallelism over the `pp` mesh axis (GPipe schedule, SPMD).

Reference role: the reference has NO pipeline schedule of its own — PP runs
inside vLLM over Ray workers coordinated by compiled graphs
(dag/compiled_dag_node.py:808; SURVEY.md §2.4). On TPU the idiomatic
construction is the inverse: the schedule lives INSIDE one compiled SPMD
program. Each pp shard holds one stage's parameters; every schedule tick,
all stages run the same stage function on their current microbatch and
activations hop to the next stage with `lax.ppermute`. Autodiff flows
through the whole schedule (ppermute transposes to the reverse rotation),
so the backward pipeline needs no extra code — this is the
compiled-graph-channels analog with XLA owning the transfers (PAPERS.md
JaxPP-style, original implementation).

Schedules:
  - GPipe (num_chunks=1): M microbatches through S stages in M + S - 1
    ticks; bubble fraction (S-1)/(M+S-1).
  - Breadth-first interleaved virtual stages (num_chunks=V>1, the
    schedule Megatron calls interleaved 1F1B, bubble-wise): each device
    holds V stage CHUNKS (device d owns logical stages {c*S+d}), a
    microbatch makes V loops around the ring, and stage k=c*S+d runs
    microbatch m at tick (m//S)*S*V + c*S + (m%S) + d — conflict-free,
    every activation still hops d->d+1 each tick, and the bubble shrinks
    to (S-1)/(V*M+S-1) ticks. Requires M % S == 0.

Activation-memory trade is handled by jax.checkpoint over the stage fn.
"""
from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def pipeline_apply(stage_fn: Callable, stage_params, x: jax.Array,
                   mesh: Mesh, num_microbatches: int,
                   remat: bool = True, x_spec: P = P(),
                   num_chunks: int = 1) -> jax.Array:
    """Run `x` through a chain of pp-sharded stages.

    stage_fn(params_one_stage, h) -> h : one stage's computation (e.g. a
        `lax.scan` over its transformer layers).
    stage_params : pytree whose leaves have leading dim S*num_chunks,
        sharded P("pp") and ordered DEVICE-MAJOR (use interleave_stages to
        go from logical stage order to this layout) — device d holds
        chunks for logical stages {c*S+d | c < num_chunks}.
    x [B, ...] : input activations, replicated over pp (embedding and head
        stay outside the pipeline: they're pp-replicated). `x_spec` shards
        the activation dims over OTHER mesh axes (e.g. P("dp") to compose
        pp with data parallelism — each (pp, dp) shard pipelines its local
        batch slice).
    Returns y [B, ...] — the last stage's output, replicated over pp,
    sharded per x_spec elsewhere.

    The per-shard batch must divide into num_microbatches equal
    microbatches; interleaving additionally needs num_microbatches % S == 0.
    """
    from ._compat import shard_map  # current API on old/new jax alike

    S = mesh.shape.get("pp", 1)
    V = num_chunks
    if S == 1:
        # single device: chunks run back to back (device-major order with
        # d=0 IS logical order)
        if V == 1:
            return stage_fn(jax.tree.map(lambda a: a[0], stage_params), x)
        h = x
        for c in range(V):
            h = stage_fn(jax.tree.map(lambda a: a[c], stage_params), h)
        return h
    M = num_microbatches
    if V > 1 and M % S:
        raise ValueError(
            f"interleaved schedule needs microbatches ({M}) divisible by "
            f"pipeline stages ({S})")
    fn = jax.checkpoint(stage_fn) if remat else stage_fn

    def inner(params, xs):
        # params: this shard's V chunks, leading dims [1, V] — squeeze
        sp = jax.tree.map(lambda a: a[0], params)
        idx = jax.lax.axis_index("pp")
        b = xs.shape[0]
        mb = b // M
        xs = xs.reshape(M, mb, *xs.shape[1:])
        state = jnp.zeros_like(xs[0])
        outputs = jnp.zeros_like(xs)
        fwd = [(i, (i + 1) % S) for i in range(S)]
        SV = S * V

        def entry_tick(m):        # logical stage 0 consumes m at this tick
            return (m // S) * SV + (m % S)

        exits = {entry_tick(m) + SV - 1: m for m in range(M)}
        enters = {entry_tick(m): m for m in range(M)}
        for t in range(M * V + S - 1):
            # device 0 injects microbatch m when the schedule says stage 0
            # starts it this tick (static: t is a Python int)
            m_in = enters.get(t)
            inject = xs[m_in] if m_in is not None else jnp.zeros_like(xs[0])
            h = jnp.where(idx == 0, inject, state) if m_in is not None \
                else state
            # which chunk is this device running this tick? c such that
            # (t - d) mod SV lies in [c*S, c*S + S)
            if V == 1:
                h = fn(jax.tree.map(lambda a: a[0], sp), h)
            else:
                c = jnp.mod(t - idx, SV) // S
                h = jax.lax.switch(
                    c, [lambda hh, cc=cc: fn(
                        jax.tree.map(lambda a: a[cc], sp), hh)
                        for cc in range(V)], h)
            m_out = exits.get(t)
            if m_out is not None:   # last device finished logical stage SV-1
                outputs = outputs.at[m_out].set(
                    jnp.where(idx == S - 1, h, outputs[m_out]))
            state = jax.lax.ppermute(h, "pp", fwd)
        # replicate the last stage's outputs to every pp shard
        outputs = jnp.where(idx == S - 1, outputs, 0.0)
        outputs = jax.lax.psum(outputs, "pp")
        return outputs.reshape(b, *outputs.shape[2:])

    per_shard = x.shape[0]
    for ax in (x_spec[0] if len(x_spec) else None,) :
        if ax is not None:
            names = (ax,) if isinstance(ax, str) else tuple(ax)
            for n in names:
                per_shard //= mesh.shape.get(n, 1)
    if per_shard % M:
        raise ValueError(
            f"per-shard batch {per_shard} must divide microbatches {M}")

    # leaves arrive [S*V, ...] device-major; shard_map slices the leading
    # dim over pp leaving [V, ...] per shard — regroup as [1, V, ...] so
    # inner's squeeze-one convention holds for every V
    grouped = jax.tree.map(
        lambda a: a.reshape(S, V, *a.shape[1:]), stage_params)

    return shard_map(
        inner, mesh=mesh,
        in_specs=(P("pp"), x_spec),
        out_specs=x_spec,
        check_vma=False,
    )(grouped, x)


def interleave_stages(stacked_stage_params, n_stages: int, n_chunks: int):
    """Logical stage order [S*V, ...] (stage k runs k-th) -> the
    device-major layout pipeline_apply(num_chunks=V) expects: device d
    holds logical stages {c*S+d}, stored as g = d*V + c."""
    S, V = n_stages, n_chunks

    def rearr(a):
        if a.shape[0] != S * V:
            raise ValueError(
                f"leading dim {a.shape[0]} != stages*chunks {S * V}")
        a = a.reshape(V, S, *a.shape[1:])   # [c, d, ...] (k = c*S + d)
        a = jnp.swapaxes(a, 0, 1)           # [d, c, ...]
        return a.reshape(S * V, *a.shape[2:])

    return jax.tree.map(rearr, stacked_stage_params)


def split_stages(stacked_layer_params, n_stages: int):
    """[L, ...] layer-stacked params -> [S, L/S, ...] stage-major params
    (shard dim 0 over pp)."""
    def reshape(a):
        L = a.shape[0]
        if L % n_stages:
            raise ValueError(f"{L} layers not divisible by {n_stages} stages")
        return a.reshape(n_stages, L // n_stages, *a.shape[1:])

    return jax.tree.map(reshape, stacked_layer_params)


def stage_sharding(mesh: Mesh):
    """NamedSharding placing stage-major params on the pp axis."""
    return NamedSharding(mesh, P("pp"))
