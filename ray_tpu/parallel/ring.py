"""Sequence/context parallelism: ring attention and Ulysses all-to-all.

The reference has NO sequence parallelism (verified in SURVEY.md §5.7 — zero
hits for ring_attention/ulysses/context_parallel; long context lives in
external engines). On TPU it is ours to own, and the idiomatic design is
in-program: the sequence axis is a mesh axis ("sp"), K/V blocks rotate around
the ICI ring via `jax.lax.ppermute` while each step's partial attention is
computed blockwise with a streaming-softmax accumulator, so communication
overlaps compute and the full sequence never materializes on one chip.

Two schemes, matching the literature (see PAPERS.md):
* `ring_attention` — Liu et al. blockwise ring attention: K/V circulate,
  O(seq/n) memory per chip, exact result.
* `ulysses_attention` — DeepSpeed-Ulysses: all-to-all re-shards
  [B, S/n, H, D] -> [B, S, H/n, D], runs ordinary (flash) attention over the
  full sequence per head group, then re-shards back. Cheaper collectives for
  moderate sequence lengths; requires heads % n == 0.

Both are meant to be called inside `jax.shard_map` over the "sp" mesh axis;
`ring_attention_sharded` / `ulysses_attention_sharded` wrap that for callers
holding globally-sharded arrays.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from . import _compat

NEG_INF = -1e30


def _block_attn(q, k, v, scale: float, mask: Optional[jax.Array]):
    """One q-block × kv-block attention step -> (unnormalized_out, max, sum).

    Returns the pieces a streaming-softmax accumulator needs. Shapes:
    q [B, Sq, H, D], k/v [B, Sk, H, D]; out [B, Sq, H, D], m/l [B, Sq, H].
    """
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if mask is not None:
        scores = jnp.where(mask, scores, NEG_INF)
    m = jnp.max(scores, axis=-1)                       # [B, H, Sq]
    p = jnp.exp(scores - m[..., None])
    if mask is not None:
        # fully-masked rows: exp(NEG_INF - NEG_INF) = 1 — zero them instead
        p = jnp.where(mask, p, 0.0)
    l = jnp.sum(p, axis=-1)                            # noqa: E741
    out = jnp.einsum("bhqk,bkhd->bqhd", p, v,
                     preferred_element_type=jnp.float32)
    return out, jnp.moveaxis(m, 1, -1), jnp.moveaxis(l, 1, -1)


def _merge(acc_out, acc_m, acc_l, out, m, l):  # noqa: E741
    """Merge a new block into the streaming accumulator (flash-attention
    rescaling identity)."""
    new_m = jnp.maximum(acc_m, m)
    a = jnp.exp(acc_m - new_m)
    b = jnp.exp(m - new_m)
    new_out = acc_out * a[..., None] + out * b[..., None]
    new_l = acc_l * a + l * b
    return new_out, new_m, new_l


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                   axis_name: str = "sp",
                   causal: bool = False,
                   scale: Optional[float] = None) -> jax.Array:
    """Exact attention over a sequence sharded on `axis_name`.

    Call inside shard_map. q/k/v: [B, S_local, H, D] (the local sequence
    shard). K/V blocks rotate ring-wise via ppermute; `causal` masks with
    *global* positions derived from each block's ring offset.
    """
    n = _compat.axis_size(axis_name)
    my = jax.lax.axis_index(axis_name)
    s_local = q.shape[1]
    if scale is None:
        scale = q.shape[-1] ** -0.5

    q_pos = my * s_local + jnp.arange(s_local)          # global q positions

    def step(carry, i):
        k_blk, v_blk, acc_out, acc_m, acc_l = carry
        src = (my - i) % n                               # who produced k_blk
        if causal:
            k_pos = src * s_local + jnp.arange(s_local)
            mask = (q_pos[:, None] >= k_pos[None, :])[None, None]  # [1,1,q,k]
        else:
            mask = None
        out, m, l = _block_attn(q, k_blk, v_blk, scale, mask)  # noqa: E741
        acc_out, acc_m, acc_l = _merge(acc_out, acc_m, acc_l, out, m, l)
        perm = [(j, (j + 1) % n) for j in range(n)]
        k_nxt = jax.lax.ppermute(k_blk, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_blk, axis_name, perm)
        return (k_nxt, v_nxt, acc_out, acc_m, acc_l), None

    acc_out = jnp.zeros(q.shape, jnp.float32)
    acc_m = jnp.full(q.shape[:-1], NEG_INF, jnp.float32)
    acc_l = jnp.zeros(q.shape[:-1], jnp.float32)
    (_, _, acc_out, _, acc_l), _ = jax.lax.scan(
        step, (k, v, acc_out, acc_m, acc_l), jnp.arange(n))
    return (acc_out / jnp.maximum(acc_l, 1e-30)[..., None]).astype(q.dtype)


def ulysses_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                      axis_name: str = "sp",
                      causal: bool = False,
                      scale: Optional[float] = None,
                      attn_fn=None) -> jax.Array:
    """Ulysses all-to-all attention; call inside shard_map.

    Re-shards seq→heads with one all_to_all, runs full-sequence attention on
    H/n heads (any `attn_fn(q, k, v, causal, scale)`, default streaming-exact
    jnp), re-shards back.
    """
    n = _compat.axis_size(axis_name)
    if q.shape[2] % n:
        raise ValueError(f"heads {q.shape[2]} % sp size {n} != 0")

    def s2h(x):  # [B, S/n, H, D] -> [B, S, H/n, D]
        return jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                                  tiled=True)

    def h2s(x):  # [B, S, H/n, D] -> [B, S/n, H, D]
        return jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                                  tiled=True)

    qg, kg, vg = s2h(q), s2h(k), s2h(v)
    if attn_fn is None:
        sc = scale if scale is not None else q.shape[-1] ** -0.5
        s = qg.shape[1]
        mask = (jnp.tril(jnp.ones((s, s), bool))[None, None]
                if causal else None)
        out, _, l = _block_attn(qg, kg, vg, sc, mask)  # noqa: E741
        og = (out / jnp.maximum(l, 1e-30)[..., None]).astype(q.dtype)
    else:
        og = attn_fn(qg, kg, vg, causal=causal, scale=scale)
    return h2s(og)


def _sharded(fn, mesh, q_specs):
    from ._compat import shard_map
    return shard_map(fn, mesh=mesh, in_specs=q_specs, out_specs=q_specs[0],
                     check_vma=False)


def _seq_spec(mesh, axis_name, batch_axes, head_axis) -> P:
    if axis_name not in mesh.axis_names:
        raise ValueError(
            f"mesh {tuple(mesh.axis_names)} has no {axis_name!r} axis; "
            f"build it with MeshSpec(sp=...) to use sequence parallelism")
    return P(tuple(a for a in batch_axes if a in mesh.axis_names) or None,
             axis_name,
             head_axis if head_axis in mesh.axis_names else None)


def ring_attention_sharded(q, k, v, mesh, axis_name: str = "sp",
                           causal: bool = False,
                           batch_axes=("dp", "fsdp"), head_axis="tp"):
    """Ring attention over globally-sharded [B, S, H, D] arrays: batch over
    dp/fsdp, sequence over sp, heads over tp."""
    spec = _seq_spec(mesh, axis_name, batch_axes, head_axis)
    fn = functools.partial(ring_attention, axis_name=axis_name, causal=causal)
    return _sharded(fn, mesh, (spec, spec, spec))(q, k, v)


def ulysses_attention_sharded(q, k, v, mesh, axis_name: str = "sp",
                              causal: bool = False,
                              batch_axes=("dp", "fsdp"), head_axis="tp"):
    spec = _seq_spec(mesh, axis_name, batch_axes, head_axis)
    fn = functools.partial(ulysses_attention, axis_name=axis_name,
                           causal=causal)
    return _sharded(fn, mesh, (spec, spec, spec))(q, k, v)
