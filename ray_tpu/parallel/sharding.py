"""Logical-axis sharding rules → concrete NamedShardings.

The reference never does sharding math itself — it passes tensor/pipeline
degrees to external engines (vLLM: llm/_internal/serve/configs/
server_models.py:391-415) and wraps torch FSDP for sharded-DP
(train/torch/train_loop_utils.py `prepare_model`). Here sharding is
first-class: model code names its dimensions with *logical* axes and this
module maps them onto mesh axes, in the style of T5X/Flax partitioning rules.
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from .mesh import get_mesh, BATCH_AXES

# Default logical→mesh axis rules for transformer/CNN families.
# Each entry: (logical_axis, mesh axis or tuple of mesh axes or None).
# First rule whose mesh axes all exist in the mesh (and are unused so far in
# the same spec) wins.
LOGICAL_AXIS_RULES: tuple[tuple[str, object], ...] = (
    ("batch", ("dp", "fsdp")),
    ("sequence", "sp"),
    ("embed", "fsdp"),          # FSDP shards params along embed/feature dims
    ("mlp", "tp"),
    ("heads", "tp"),
    ("kv_heads", "tp"),
    ("q_seq", "sp"),
    ("kv_seq", None),
    ("head_dim", None),
    # Vocab rows shard over (tp, fsdp): Megatron-style vocab-parallel
    # embedding. The gather from a row-sharded table partitions cleanly
    # (clamp+mask+psum over tp·fsdp) and its output inherits the *index*
    # sharding (batch over dp·fsdp) — no feature-dim→batch-dim reshard. The
    # old rule (embed dim over fsdp) made every embedding lookup flip a
    # feature-sharded gather output to batch-sharded, which XLA can only do
    # by involuntary full rematerialization (replicate + repartition), fwd
    # and bwd. On lm_head ("embed", "vocab") the embed dim claims fsdp
    # first, so logits stay tp-sharded exactly as before.
    ("vocab", ("tp", "fsdp")),
    ("expert", "ep"),
    ("stage", "pp"),
    ("channel", None),
    ("norm", None),
)


def logical_spec(logical_axes: Sequence[Optional[str]],
                 mesh=None,
                 rules=LOGICAL_AXIS_RULES) -> P:
    """Map a tuple of logical axis names (None = replicated) to a PartitionSpec.

    Mesh axes present in the mesh with size 1 are kept (harmless); mesh axes
    absent from the mesh are dropped. A mesh axis is used at most once per
    spec (XLA requirement) — later logical axes lose the contested axis.
    """
    mesh = mesh or get_mesh()
    mesh_axes = set(mesh.axis_names) if mesh is not None else set()
    rule_map = dict(rules)
    used: set[str] = set()
    out = []
    for ax in logical_axes:
        if ax is None:
            out.append(None)
            continue
        if ax not in rule_map:
            raise ValueError(f"no sharding rule for logical axis {ax!r}")
        target = rule_map[ax]
        if target is None:
            out.append(None)
            continue
        cand = (target,) if isinstance(target, str) else tuple(target)
        cand = tuple(a for a in cand if a in mesh_axes and a not in used)
        used.update(cand)
        if not cand:
            out.append(None)
        elif len(cand) == 1:
            out.append(cand[0])
        else:
            out.append(cand)
    while out and out[-1] is None:
        out.pop()
    return P(*out)


def named_sharding(logical_axes: Sequence[Optional[str]], mesh=None,
                   rules=LOGICAL_AXIS_RULES) -> NamedSharding:
    mesh = mesh or get_mesh()
    if mesh is None:
        raise RuntimeError("no mesh: call inside parallel.use_mesh(...)")
    return NamedSharding(mesh, logical_spec(logical_axes, mesh, rules))


def logical_sharding(tree_of_axes, mesh=None, rules=LOGICAL_AXIS_RULES):
    """Map a pytree of logical-axis tuples to a pytree of NamedShardings."""
    return jax.tree.map(
        lambda axes: named_sharding(axes, mesh, rules),
        tree_of_axes,
        is_leaf=lambda x: isinstance(x, tuple),
    )


def shard_pytree(tree, tree_of_axes, mesh=None, rules=LOGICAL_AXIS_RULES):
    """device_put a pytree according to its logical axes."""
    shardings = logical_sharding(tree_of_axes, mesh, rules)
    return jax.device_put(tree, shardings)


def constrain(x, logical_axes: Sequence[Optional[str]], mesh=None,
              rules=LOGICAL_AXIS_RULES):
    """`lax.with_sharding_constraint` by logical axes; no-op without a mesh.

    Model code calls this at layer boundaries so XLA propagates the intended
    layout; safe to leave in for single-device / CPU tests.
    """
    mesh = mesh or get_mesh()
    if mesh is None or len(mesh.devices.flat) == 1:
        return x
    return jax.lax.with_sharding_constraint(
        x, NamedSharding(mesh, logical_spec(logical_axes, mesh, rules)))


def batch_spec(mesh=None) -> P:
    """PartitionSpec for a [batch, ...] array: batch over dp+fsdp."""
    mesh = mesh or get_mesh()
    axes = tuple(a for a in BATCH_AXES
                 if mesh is not None and a in mesh.axis_names)
    return P(axes if axes else None)
