"""ray_tpu.rl — RL training library (RLlib equivalent, JAX-native).

Reference parity: rllib/ (algorithms/algorithm.py:207, env/
single_agent_env_runner.py:68, core/learner/learner.py:108,
core/rl_module/rl_module.py:258). PPO is the first algorithm (north-star
config 3: PPO EnvRunner actors + jitted JAX learner over the mesh).
"""
from .algorithm import PPO, AlgorithmConfig
from .appo import APPO, AppoAlgorithmConfig, AppoConfig, AppoLearner
from .connectors import (ClipObs, Connector, ConnectorPipeline,
                         FlattenObs, MeanStdFilter)
from .dqn import (DQN, DQNAlgorithmConfig, DQNConfig, DQNLearner,
                  ReplayBuffer)
from .impala import (IMPALA, ImpalaAlgorithmConfig, ImpalaConfig,
                     ImpalaLearner, vtrace)
from .multi_agent import (MultiAgentEnv, MultiAgentEnvRunner,
                          MultiAgentPPO, MultiAgentPPOConfig)
from .sac import SAC, SACAlgorithmConfig, SACConfig, SACLearner
from .env_runner import EnvRunner, make_gym_env
from .learner import PPOConfig, PPOLearner, compute_gae
from .module import MLPConfig
from .offline import (BC, BCConfig, CQL, CQLConfig, MARWIL,
                      MARWILConfig, collect_transitions)

# Podracer (Sebulba/Anakin) exports resolve lazily (PEP 562): the
# subsystem pulls gymnasium/optax (and jax via the learners) on USE, so
# reaching the rest of ray_tpu.rl never pays for them and GL005's static
# heavy-import closure of `import ray_tpu` stays green.
_PODRACER_EXPORTS = (
    "PodracerTrainer", "SebulbaConfig", "SebulbaTrainer",
    "AnakinConfig", "AnakinTrainer", "RolloutQueue", "RolloutQueueSpec",
    "JaxCartPole",
)


def __getattr__(name):
    if name == "podracer" or name in _PODRACER_EXPORTS:
        import importlib
        mod = importlib.import_module(".podracer", __name__)
        return mod if name == "podracer" else getattr(mod, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


__all__ = [
    "APPO", "AppoAlgorithmConfig", "AppoConfig", "AppoLearner",
    "Connector", "ConnectorPipeline", "FlattenObs", "ClipObs",
    "MeanStdFilter",
    "DQN", "DQNAlgorithmConfig", "DQNConfig", "DQNLearner", "ReplayBuffer",
    "IMPALA", "ImpalaAlgorithmConfig", "ImpalaConfig", "ImpalaLearner",
    "vtrace", "SAC", "SACAlgorithmConfig", "SACConfig", "SACLearner",
    "MultiAgentEnv", "MultiAgentEnvRunner", "MultiAgentPPO",
    "MultiAgentPPOConfig",
    "PPO", "AlgorithmConfig", "EnvRunner", "make_gym_env",
    "PPOConfig", "PPOLearner", "compute_gae", "MLPConfig",
    "BC", "BCConfig", "CQL", "CQLConfig", "MARWIL", "MARWILConfig",
    "collect_transitions", "podracer", *_PODRACER_EXPORTS,
]
