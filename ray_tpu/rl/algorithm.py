"""Algorithm: the RL training driver loop (PPO first).

Reference parity: rllib/algorithms/algorithm.py:207 (training_step :2004 —
sample from the EnvRunnerGroup, update the LearnerGroup, sync weights) and
algorithm_config.py. The loop here is deliberately the same shape:

    Algorithm.train() -> {sample via EnvRunner actors}
                      -> PPOLearner.update (jitted, mesh-shardable)
                      -> broadcast new weights (object store put, one per
                         iteration — runners fetch by ref)

Tune-compatible: `Algorithm.as_trainable()` yields a function trainable that
reports `episode_return_mean` every iteration, so schedulers (ASHA/PBT) act
on RL runs exactly as the reference's Tuner(Algorithm) path does.
"""
from __future__ import annotations

import time
from typing import Callable, Optional

import numpy as np

from .env_runner import EnvRunner, make_gym_env
from .learner import PPOConfig, PPOLearner
from .module import MLPConfig


class AlgorithmConfig:
    """Builder-style config (reference: algorithm_config.py fluent API)."""

    def __init__(self):
        self.env_fn: Optional[Callable] = None
        self.num_env_runners = 2
        self.num_envs_per_runner = 4
        self.rollout_len = 64
        self.ppo = PPOConfig()
        self.hidden = (64, 64)
        self.seed = 0
        self.mesh = None
        self.runner_resources = {"CPU": 1}

    def environment(self, env: str | Callable, **kwargs) -> "AlgorithmConfig":
        self.env_fn = make_gym_env(env, **kwargs) if isinstance(env, str) \
            else env
        return self

    def env_runners(self, num_env_runners: int = 2,
                    num_envs_per_env_runner: int = 4,
                    rollout_fragment_length: int = 64) -> "AlgorithmConfig":
        self.num_env_runners = num_env_runners
        self.num_envs_per_runner = num_envs_per_env_runner
        self.rollout_len = rollout_fragment_length
        return self

    def training(self, **ppo_kwargs) -> "AlgorithmConfig":
        import dataclasses
        self.ppo = dataclasses.replace(self.ppo, **ppo_kwargs)
        return self

    def build(self) -> "PPO":
        return PPO(self)


class PPO:
    """Proximal Policy Optimization over EnvRunner actors + a JAX learner."""

    def __init__(self, config: AlgorithmConfig):
        import ray_tpu as ray

        from ..core.usage import record_library_usage
        record_library_usage("rl")

        if config.env_fn is None:
            raise ValueError("config.environment(...) is required")
        self.config = config
        probe = config.env_fn()
        obs_dim = int(np.prod(probe.observation_space.shape))
        num_actions = int(probe.action_space.n)
        probe.close()

        self.module_cfg = MLPConfig(obs_dim=obs_dim, num_actions=num_actions,
                                    hidden=tuple(config.hidden))
        self.learner = PPOLearner(self.module_cfg, config.ppo,
                                  seed=config.seed, mesh=config.mesh)

        RunnerCls = ray.remote(EnvRunner)
        self._runners = [
            RunnerCls.options(**{
                "num_cpus": config.runner_resources.get("CPU", 1)}).remote(
                config.env_fn, config.num_envs_per_runner,
                config.rollout_len, seed=config.seed + 1000 * (i + 1))
            for i in range(config.num_env_runners)
        ]
        self._ray = ray
        self.iteration = 0
        self._total_env_steps = 0
        self._recent_returns: list[float] = []

    # -- the training_step loop (reference algorithm.py:2004) --------------

    def train(self) -> dict:
        ray = self._ray
        t0 = time.perf_counter()
        weights_ref = ray.put(self.learner.get_params())
        samples = ray.get([r.sample.remote(weights_ref)
                           for r in self._runners])
        t_sample = time.perf_counter() - t0

        t1 = time.perf_counter()
        stats = self.learner.update(samples)
        t_update = time.perf_counter() - t1

        self.iteration += 1
        steps = (self.config.rollout_len * self.config.num_envs_per_runner
                 * self.config.num_env_runners)
        self._total_env_steps += steps
        for s in samples:
            self._recent_returns.extend(s["episode_returns"])
        self._recent_returns = self._recent_returns[-100:]
        mean_ret = (float(np.mean(self._recent_returns))
                    if self._recent_returns else float("nan"))
        dt = time.perf_counter() - t0
        return {
            "training_iteration": self.iteration,
            "episode_return_mean": mean_ret,
            "num_env_steps_sampled": steps,
            "num_env_steps_sampled_lifetime": self._total_env_steps,
            "env_steps_per_sec": steps / dt,
            "time_sample_s": t_sample,
            "time_update_s": t_update,
            **{f"learner/{k}": v for k, v in stats.items()},
        }

    def evaluate(self, num_episodes: int = 5) -> dict:
        ray = self._ray
        weights_ref = ray.put(self.learner.get_params())
        return ray.get(self._runners[0].evaluate.remote(
            weights_ref, num_episodes))

    def get_weights(self):
        return self.learner.get_params()

    def set_weights(self, weights):
        self.learner.set_params(weights)

    def save_checkpoint(self) -> dict:
        import jax
        return {"params": jax.device_get(self.learner.params),
                "opt_state": jax.device_get(self.learner.opt_state),
                "iteration": self.iteration,
                "total_env_steps": self._total_env_steps}

    def restore_checkpoint(self, state: dict) -> None:
        import jax.numpy as jnp
        import jax
        self.learner.params = jax.tree.map(jnp.asarray, state["params"])
        self.learner.opt_state = jax.tree.map(
            jnp.asarray, state["opt_state"])
        self.iteration = state["iteration"]
        self._total_env_steps = state["total_env_steps"]

    def stop(self):
        for r in self._runners:
            try:
                self._ray.kill(r)
            except Exception:
                pass

    # -- Tune integration ---------------------------------------------------

    @classmethod
    def as_trainable(cls, config: AlgorithmConfig,
                     stop_iters: int = 100) -> Callable:
        """A Tune function-trainable running this algorithm (reference:
        Algorithm IS a Trainable; here the adapter is explicit)."""

        def trainable(tune_config: dict):
            from ..tune import report
            import copy
            import dataclasses
            cfg = copy.copy(config)  # don't leak overrides across trials
            if tune_config:
                unknown = [k for k in tune_config
                           if not hasattr(cfg.ppo, k)]
                if unknown:
                    raise ValueError(
                        f"unknown PPO hyperparameters in search space: "
                        f"{unknown}")
                cfg.ppo = dataclasses.replace(cfg.ppo, **tune_config)
            algo = cls(cfg)
            try:
                for _ in range(stop_iters):
                    report(algo.train())
            finally:
                algo.stop()

        return trainable
