"""Algorithm: the RL training driver loop (PPO first).

Reference parity: rllib/algorithms/algorithm.py:207 (training_step :2004 —
sample from the EnvRunnerGroup, update the LearnerGroup, sync weights) and
algorithm_config.py. The loop here is deliberately the same shape:

    Algorithm.train() -> {sample via EnvRunner actors}
                      -> PPOLearner.update (jitted, mesh-shardable)
                      -> broadcast new weights (object store put, one per
                         iteration — runners fetch by ref)

Tune-compatible: `Algorithm.as_trainable()` yields a function trainable that
reports `episode_return_mean` every iteration, so schedulers (ASHA/PBT) act
on RL runs exactly as the reference's Tuner(Algorithm) path does.
"""
from __future__ import annotations

import time

import numpy as np

from .base import AlgorithmBase, AlgorithmConfigBase
from .env_runner import EnvRunner
from .learner import PPOConfig, PPOLearner
from .module import MLPConfig


class AlgorithmConfig(AlgorithmConfigBase):
    """Builder-style PPO config (reference: algorithm_config.py fluent
    API; base: AlgorithmConfigBase)."""

    HPARAM_FIELD = "ppo"
    HPARAM_FACTORY = PPOConfig

    def __init__(self):
        super().__init__()
        self.rollout_len = 64
        self.mesh = None

    @property
    def ALGO_CLS(self):
        return PPO


class PPO(AlgorithmBase):
    """Proximal Policy Optimization over EnvRunner actors + a JAX learner."""

    HPARAM_FIELD = "ppo"

    def __init__(self, config: AlgorithmConfig):
        self._setup(config, EnvRunner)
        self.learner = PPOLearner(self.module_cfg, config.ppo,
                                  seed=config.seed, mesh=config.mesh)

    # -- the training_step loop (reference algorithm.py:2004) --------------

    def train(self) -> dict:
        ray = self._ray
        t0 = time.perf_counter()
        weights_ref = ray.put(self.learner.get_params())
        samples = ray.get([r.sample.remote(weights_ref)
                           for r in self._runners])
        t_sample = time.perf_counter() - t0

        t1 = time.perf_counter()
        stats = self.learner.update(samples)
        t_update = time.perf_counter() - t1
        self._sync_connector_state()

        self.iteration += 1
        steps = (self.config.rollout_len * self.config.num_envs_per_runner
                 * self.config.num_env_runners)
        self._total_env_steps += steps
        mean_ret = self._note_returns(
            [r for s in samples for r in s["episode_returns"]])
        dt = time.perf_counter() - t0
        return {
            "training_iteration": self.iteration,
            "episode_return_mean": mean_ret,
            "num_env_steps_sampled": steps,
            "num_env_steps_sampled_lifetime": self._total_env_steps,
            "env_steps_per_sec": steps / dt,
            "time_sample_s": t_sample,
            "time_update_s": t_update,
            **{f"learner/{k}": v for k, v in stats.items()},
        }

