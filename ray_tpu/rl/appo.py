"""APPO: asynchronous PPO — IMPALA's pipeline with a PPO clipped
surrogate over V-trace advantages.

Reference parity: rllib/algorithms/appo/appo.py:345 (APPO — "IMPALA with
a surrogate policy loss with clipping", plus an optional KL penalty
toward the behaviour policy) riding the same async EnvRunner/V-trace
machinery as rllib/algorithms/impala/.

TPU-first: like IMPALA here, the whole V-trace + clipped-surrogate update
is one jitted program; only the loss differs, so APPO subclasses the
IMPALA learner/driver and swaps the loss function.
"""
from __future__ import annotations

import dataclasses

from . import module as module_lib
from .base import AlgorithmConfigBase
from .impala import IMPALA, ImpalaConfig, ImpalaLearner, vtrace


@dataclasses.dataclass(frozen=True)
class AppoConfig(ImpalaConfig):
    """(reference: appo.py APPOConfig.training — clip_param :168,
    use_kl_loss/kl_coeff :164-166)"""
    clip_param: float = 0.2
    use_kl_loss: bool = False
    kl_coeff: float = 0.2
    lr: float = 3e-4


class AppoLearner(ImpalaLearner):
    """IMPALA learner with the PPO clipped surrogate (reference:
    appo_learner.py — the loss is the only override)."""

    def _build_update(self):
        import jax
        import jax.numpy as jnp
        import optax
        cfg = self.cfg

        def loss_fn(params, batch):
            logits, values = module_lib.logits_and_value(
                params, batch["obs"])                       # [T, B, A]/[T, B]
            logp_all = jax.nn.log_softmax(logits, axis=-1)
            target_logp = jnp.take_along_axis(
                logp_all, batch["actions"][..., None], axis=-1)[..., 0]
            vs, pg_adv = vtrace(
                batch["logp"], target_logp, batch["rewards"], values,
                batch["dones"], batch["bootstrap_value"],
                cfg.gamma, cfg.rho_bar, cfg.c_bar)
            # PPO surrogate against the BEHAVIOUR policy's logp (the
            # fragment may be a policy version behind, as in IMPALA)
            ratio = jnp.exp(target_logp - batch["logp"])
            clipped = jnp.clip(ratio, 1.0 - cfg.clip_param,
                               1.0 + cfg.clip_param)
            pg_loss = -jnp.mean(
                jnp.minimum(ratio * pg_adv, clipped * pg_adv))
            vf_loss = 0.5 * jnp.mean((vs - values) ** 2)
            entropy = -jnp.mean(
                jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1))
            total = (pg_loss + cfg.vf_coeff * vf_loss
                     - cfg.entropy_coeff * entropy)
            if cfg.use_kl_loss:
                # KL(behaviour || target) estimated from the taken actions
                kl = jnp.mean(batch["logp"] - target_logp)
                total = total + cfg.kl_coeff * kl
            return total, (pg_loss, vf_loss, entropy)

        def update(params, opt_state, batch):
            (loss, aux), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, batch)
            updates, opt_state = self.optimizer.update(
                grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, loss, aux

        return update


class APPO(IMPALA):
    """The async driver loop is IMPALA's; only the learner differs
    (reference: APPO.training_step delegates to Impala.training_step)."""

    HPARAM_FIELD = "appo"

    def __init__(self, config: "AppoAlgorithmConfig"):
        from .env_runner import EnvRunner
        self._setup(config, EnvRunner)
        self.learner = AppoLearner(self.module_cfg, config.appo,
                                   seed=config.seed)
        self._inflight = {}
        weights_ref = self._ray.put(self.learner.params)
        for r in self._runners:
            self._inflight[r.sample.remote(weights_ref)] = r


class AppoAlgorithmConfig(AlgorithmConfigBase):
    """Fluent config for APPO (reference: appo.py APPOConfig)."""

    HPARAM_FIELD = "appo"
    HPARAM_FACTORY = AppoConfig

    @property
    def ALGO_CLS(self):
        return APPO
