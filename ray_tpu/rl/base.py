"""Shared Algorithm scaffolding for the RL family.

Reference parity: the common half of rllib/algorithms/algorithm.py —
every Algorithm builds an env probe + a runner-actor group, exposes
evaluate/save/restore/stop, and plugs into Tune as a trainable. PPO, DQN
and IMPALA subclass this and keep only their training_step logic.
"""
from __future__ import annotations

from typing import Callable

import numpy as np

from .module import MLPConfig


class AlgorithmBase:
    """Subclass contract: set ``self.learner`` (with ``.params`` or
    ``get_params()``), call ``_setup(config, runner_cls)`` in __init__,
    implement ``train()``; set class attr ``HPARAM_FIELD`` to the config
    attribute holding the per-algorithm dataclass (for as_trainable)."""

    HPARAM_FIELD: str = ""

    def _make_module_cfg(self, probe):
        """Module config from a probe env; override for non-discrete
        action spaces (SAC builds a continuous config here)."""
        return MLPConfig(
            obs_dim=int(np.prod(probe.observation_space.shape)),
            num_actions=int(probe.action_space.n),
            hidden=tuple(self.config.hidden))

    def _setup(self, config, runner_cls) -> None:
        import ray_tpu as ray

        from ..core.usage import record_library_usage
        record_library_usage("rl")
        if config.env_fn is None:
            raise ValueError("config.environment(...) is required")
        self.config = config
        probe = config.env_fn()
        self.module_cfg = self._make_module_cfg(probe)
        probe.close()
        RunnerCls = ray.remote(runner_cls)
        extra = {}
        if getattr(config, "env_to_module", None) is not None:
            import inspect
            if "connectors" not in inspect.signature(
                    runner_cls.__init__).parameters:
                raise ValueError(
                    f"{type(self).__name__} does not support connector "
                    f"pipelines ({runner_cls.__name__} takes no "
                    f"'connectors' argument)")
            extra["connectors"] = config.env_to_module
        self._runners = [
            RunnerCls.options(num_cpus=config.runner_resources.get(
                "CPU", 1)).remote(
                config.env_fn, config.num_envs_per_runner,
                config.rollout_len, seed=config.seed + 1000 * (i + 1),
                **extra)
            for i in range(config.num_env_runners)]
        self._ray = ray
        self.iteration = 0
        self._total_env_steps = 0
        self._recent_returns: list[float] = []

    # -- weights ---------------------------------------------------------- #

    def get_weights(self):
        lrn = self.learner
        return lrn.get_params() if hasattr(lrn, "get_params") \
            else lrn.params

    def set_weights(self, weights) -> None:
        lrn = self.learner
        if hasattr(lrn, "set_params"):
            lrn.set_params(weights)
        else:
            lrn.params = weights

    # -- lifecycle --------------------------------------------------------- #

    def evaluate(self, num_episodes: int = 5) -> dict:
        ray = self._ray
        weights_ref = ray.put(self.get_weights())
        return ray.get(self._runners[0].evaluate.remote(
            weights_ref, num_episodes))

    def _sync_connector_state(self) -> None:
        """Fold per-runner connector DELTAS (obs seen since the last
        broadcast) into the driver pipeline's global state and broadcast
        it back (reference: connector state syncing between EnvRunners
        each iteration; delta-based so the shared prior is never
        double-counted)."""
        pipeline = getattr(self.config, "env_to_module", None)
        if pipeline is None:
            return
        ray = self._ray
        deltas = ray.get([r.get_connector_state.remote()
                          for r in self._runners])
        merged = pipeline.absorb_deltas(deltas)
        ray.get([r.set_connector_state.remote(merged)
                 for r in self._runners])

    def _extra_state(self) -> dict:
        """Algorithm-specific checkpoint fields (e.g. DQN target net)."""
        return {}

    def _load_extra_state(self, state: dict) -> None:
        pass

    def save_checkpoint(self) -> dict:
        import jax
        out = {"params": jax.device_get(self.learner.params),
               "opt_state": jax.device_get(self.learner.opt_state),
               "iteration": self.iteration,
               "total_env_steps": self._total_env_steps,
               **{k: jax.device_get(v)
                  for k, v in self._extra_state().items()}}
        pipeline = getattr(self.config, "env_to_module", None)
        if pipeline is not None:
            # normalization stats are part of the policy: restoring
            # params without them would feed the net differently-scaled
            # inputs than it was trained on
            out["connector_state"] = pipeline.get_global()
        return out

    def restore_checkpoint(self, state: dict) -> None:
        import jax
        import jax.numpy as jnp
        self.learner.params = jax.tree.map(jnp.asarray, state["params"])
        self.learner.opt_state = jax.tree.map(
            jnp.asarray, state["opt_state"])
        self.iteration = state["iteration"]
        self._total_env_steps = state["total_env_steps"]
        pipeline = getattr(self.config, "env_to_module", None)
        if pipeline is not None and state.get("connector_state"):
            pipeline.set_state(state["connector_state"])
            self._ray.get([r.set_connector_state.remote(
                state["connector_state"]) for r in self._runners])
        self._load_extra_state(state)

    def stop(self) -> None:
        for r in self._runners:
            try:
                self._ray.kill(r)
            except Exception:
                pass  # runner already dead

    # -- bookkeeping shared by training_steps ------------------------------ #

    def _note_returns(self, episode_returns) -> float:
        self._recent_returns.extend(episode_returns)
        self._recent_returns = self._recent_returns[-100:]
        return (float(np.mean(self._recent_returns))
                if self._recent_returns else float("nan"))

    # -- Tune integration --------------------------------------------------- #

    @classmethod
    def as_trainable(cls, config, stop_iters: int = 100) -> Callable:
        """A Tune function-trainable for this algorithm (reference:
        Algorithm IS a Trainable; here the adapter is explicit). Search
        space keys override fields of the ``HPARAM_FIELD`` dataclass."""
        field = cls.HPARAM_FIELD

        def trainable(tune_config: dict):
            import copy
            import dataclasses

            from ..tune import report
            cfg = copy.copy(config)  # don't leak overrides across trials
            if tune_config:
                hp = getattr(cfg, field)
                unknown = [k for k in tune_config if not hasattr(hp, k)]
                if unknown:
                    raise ValueError(
                        f"unknown {field} hyperparameters in search "
                        f"space: {unknown}")
                setattr(cfg, field,
                        dataclasses.replace(hp, **tune_config))
            algo = cls(cfg)
            try:
                for _ in range(stop_iters):
                    report(algo.train())
            finally:
                algo.stop()

        return trainable


class AlgorithmConfigBase:
    """Fluent config shared by the algorithm family (reference:
    algorithm_config.py). Subclasses set ``HPARAM_FIELD`` (matching their
    Algorithm), ``HPARAM_FACTORY`` (the per-algo dataclass), ``ALGO_CLS``,
    and any extra defaults in __init__ AFTER calling super().__init__()."""

    HPARAM_FIELD: str = ""
    HPARAM_FACTORY = None
    ALGO_CLS = None

    def __init__(self):
        from typing import Callable, Optional  # noqa: F401
        self.env_fn = None
        self.num_env_runners = 2
        self.num_envs_per_runner = 4
        self.rollout_len = 32
        self.hidden = (64, 64)
        self.seed = 0
        self.runner_resources = {"CPU": 1}
        self.env_to_module = None
        setattr(self, self.HPARAM_FIELD, self.HPARAM_FACTORY())

    def environment(self, env, **kwargs):
        from .env_runner import make_gym_env
        self.env_fn = make_gym_env(env, **kwargs) if isinstance(env, str) \
            else env
        return self

    def env_runners(self, num_env_runners: int | None = None,
                    num_envs_per_env_runner: int | None = None,
                    rollout_fragment_length: int | None = None):
        # None keeps the config's default — a PPO config initialized with
        # rollout_len=64 must not silently drop to a base-class constant
        if num_env_runners is not None:
            self.num_env_runners = num_env_runners
        if num_envs_per_env_runner is not None:
            self.num_envs_per_runner = num_envs_per_env_runner
        if rollout_fragment_length is not None:
            self.rollout_len = rollout_fragment_length
        return self

    def training(self, **kwargs):
        import dataclasses
        hp = getattr(self, self.HPARAM_FIELD)
        setattr(self, self.HPARAM_FIELD, dataclasses.replace(hp, **kwargs))
        return self

    def connectors(self, env_to_module=None):
        """Attach an env-to-module connector pipeline (reference:
        AlgorithmConfig.env_runners(env_to_module_connector=...))."""
        from .connectors import Connector, ConnectorPipeline
        if env_to_module is not None and not isinstance(
                env_to_module, ConnectorPipeline):
            if isinstance(env_to_module, Connector):
                env_to_module = ConnectorPipeline([env_to_module])
            else:
                env_to_module = ConnectorPipeline(list(env_to_module))
        self.env_to_module = env_to_module
        return self

    def build(self):
        return self.ALGO_CLS(self)
