"""Connector pipelines: composable obs/action transforms for RL.

Reference parity: rllib/connectors/connector_v2.py:31 (ConnectorV2 — a
callable transform piece; pipelines are themselves connectors) and the
env-to-module pipeline every new-stack algorithm composes
(connectors/env_to_module/). Stateful pieces (MeanStdFilter) expose
mergeable state that the driver synchronizes across runners each
iteration, the role of RLlib's connector-state syncing between
EnvRunners and Learners.

TPU-first shape: connectors run runner-side on numpy batches (the policy
forward stays a pure jitted function over ALREADY-transformed obs), so
the compiled step never sees data-dependent preprocessing.
"""
from __future__ import annotations

from typing import Optional

import numpy as np


class Connector:
    """One transform piece: ``__call__(obs_batch, update=True) ->
    obs_batch`` (reference: connector_v2.py:31 — a connector is a
    callable; a pipeline of connectors is also a connector).
    ``update=False`` freezes stateful pieces (evaluation, boundary obs)
    so reads never contaminate training statistics.

    State protocol (delta-based, the reference's runner<->driver sync):
    ``get_state()`` returns only the observations accumulated SINCE the
    last ``set_state()`` (the delta); ``set_state(global)`` installs the
    merged global state and resets the delta. The driver folds deltas
    into its own global via ``ConnectorPipeline.absorb_deltas`` —
    merging running totals instead would double-count the shared prior
    every iteration."""

    def __call__(self, obs: np.ndarray, update: bool = True) -> np.ndarray:
        raise NotImplementedError

    # stateful pieces override these (reference: ConnectorV2 state API)
    def get_state(self) -> Optional[dict]:
        """The DELTA accumulated since the last set_state()."""
        return None

    def get_global(self) -> Optional[dict]:
        """Installed global state combined with the local delta."""
        return None

    def set_state(self, state: Optional[dict]) -> None:
        pass

    @staticmethod
    def merge_states(states: list) -> Optional[dict]:
        return None


class ConnectorPipeline(Connector):
    """Ordered chain; itself a Connector, so pipelines nest
    ((A->B)->C, reference connector_v2.py docstring)."""

    def __init__(self, pieces: Optional[list] = None):
        self.pieces: list[Connector] = list(pieces or [])

    def append(self, piece: Connector) -> "ConnectorPipeline":
        self.pieces.append(piece)
        return self

    def prepend(self, piece: Connector) -> "ConnectorPipeline":
        # pipeline construction, not a hot queue: runs once at setup on
        # a handful of pieces
        self.pieces.insert(0, piece)  # graftlint: disable=GL004
        return self

    def __call__(self, obs: np.ndarray, update: bool = True) -> np.ndarray:
        for p in self.pieces:
            obs = p(obs, update=update)
        return obs

    def get_state(self):
        return [p.get_state() for p in self.pieces]

    def get_global(self):
        return [p.get_global() for p in self.pieces]

    def set_state(self, state):
        if state is None:
            return
        for p, s in zip(self.pieces, state):
            p.set_state(s)

    def absorb_deltas(self, runner_deltas: list) -> list:
        """Driver-side: fold per-runner DELTAS into this (driver-held)
        pipeline's global state; returns the new global to broadcast."""
        out = []
        for i, p in enumerate(self.pieces):
            cur = p.get_global()
            deltas = [d[i] for d in runner_deltas if d is not None]
            merged = type(p).merge_states(
                ([cur] if cur is not None else []) + deltas)
            p.set_state(merged)
            out.append(merged)
        return out


class FlattenObs(Connector):
    """[..., *dims] -> [..., prod(dims)] (reference:
    env_to_module/flatten_observations.py)."""

    def __call__(self, obs, update: bool = True):
        obs = np.asarray(obs)
        return obs.reshape(obs.shape[0], -1)


class ClipObs(Connector):
    def __init__(self, low: float = -10.0, high: float = 10.0):
        self.low, self.high = low, high

    def __call__(self, obs, update: bool = True):
        return np.clip(obs, self.low, self.high)


def _welford_merge(a: Optional[dict], b: Optional[dict]) -> Optional[dict]:
    """Exact parallel-variance combine of two (count, mean, m2) states."""
    if a is None or a.get("mean") is None:
        return None if b is None else {k: (v.copy() if hasattr(v, "copy")
                                           else v) for k, v in b.items()}
    if b is None or b.get("mean") is None:
        return {k: (v.copy() if hasattr(v, "copy") else v)
                for k, v in a.items()}
    n, m = a["count"], b["count"]
    tot = n + m
    delta = b["mean"] - a["mean"]
    return {"count": tot,
            "mean": a["mean"] + delta * m / tot,
            "m2": a["m2"] + b["m2"] + delta ** 2 * n * m / tot}


class MeanStdFilter(Connector):
    """Running obs normalization (reference:
    env_to_module/mean_std_filter.py, Welford accumulation).

    Two accumulators: ``_base`` (the merged GLOBAL installed by the last
    set_state) and a LOCAL delta of everything seen since.
    Normalization always uses base+local; ``get_state()`` ships only the
    delta, so the driver's absorb-merge never double-counts the shared
    prior. ``update=False`` normalizes without accumulating
    (evaluation / boundary reads)."""

    def __init__(self, clip: float = 10.0, eps: float = 1e-8):
        self.clip = clip
        self.eps = eps
        self._base: Optional[dict] = None
        self._reset_local()

    def _reset_local(self):
        self.count = 0.0
        self.mean: Optional[np.ndarray] = None
        self.m2: Optional[np.ndarray] = None

    def _update(self, batch: np.ndarray):
        b = np.asarray(batch, np.float64)
        n = b.shape[0]
        bmean = b.mean(axis=0)
        bm2 = ((b - bmean) ** 2).sum(axis=0)
        if self.mean is None:
            self.count, self.mean, self.m2 = float(n), bmean, bm2
            return
        delta = bmean - self.mean
        tot = self.count + n
        self.mean = self.mean + delta * n / tot
        self.m2 = self.m2 + bm2 + delta ** 2 * self.count * n / tot
        self.count = tot

    def __call__(self, obs, update: bool = True):
        obs = np.asarray(obs, np.float32)
        if update:
            self._update(obs)
        eff = self.get_global()
        if eff is None or eff.get("mean") is None:
            return np.clip(obs, -self.clip, self.clip)
        std = np.sqrt(eff["m2"] / max(eff["count"], 1.0)) + self.eps
        out = (obs - eff["mean"]) / std
        return np.clip(out, -self.clip, self.clip).astype(np.float32)

    def get_state(self):
        """The local DELTA since the last set_state()."""
        return {"count": self.count,
                "mean": None if self.mean is None else self.mean.copy(),
                "m2": None if self.m2 is None else self.m2.copy()}

    def get_global(self):
        return _welford_merge(self._base, self.get_state())

    def set_state(self, state):
        self._base = state
        self._reset_local()

    @staticmethod
    def merge_states(states: list) -> Optional[dict]:
        out = None
        for s in states:
            out = _welford_merge(out, s)
        return out
