"""DQN: off-policy value learning with replay (double-DQN by default).

Reference parity: rllib/algorithms/dqn/ (dqn.py training_step: sample →
replay buffer add → sample minibatches → TD update → target sync) with
the new-API-stack roles: DQNRunner = single_agent_env_runner.py:68 doing
epsilon-greedy exploration, DQNLearner = dqn_learner / torch_dqn_learner
loss. TPU-first: the TD update over a K-minibatch scan is ONE jitted
program (replay indices are inputs), so the learner does one
device round-trip per train() regardless of num_updates; the replay
buffer is host-side numpy (it's bandwidth-bound bookkeeping, not FLOPs).
"""
from __future__ import annotations

import dataclasses
import time
from typing import Callable, Optional

import numpy as np

from . import module as module_lib
from .base import AlgorithmBase, AlgorithmConfigBase
from .module import MLPConfig


@dataclasses.dataclass(frozen=True)
class DQNConfig:
    """(reference: dqn.py DQNConfig.training(...))"""
    lr: float = 1e-3
    gamma: float = 0.99
    buffer_size: int = 50_000
    batch_size: int = 64
    num_updates_per_iter: int = 64
    target_update_freq: int = 500      # in gradient updates
    double_q: bool = True
    # epsilon-greedy schedule over env steps
    eps_start: float = 1.0
    eps_end: float = 0.05
    eps_decay_steps: int = 10_000
    learning_starts: int = 1_000       # env steps before updates begin
    huber_delta: float = 1.0
    # Rainbow components (reference: dqn.py's Rainbow configuration —
    # n_step, dueling, prioritized replay; each independently toggleable)
    n_step: int = 1                    # multi-step TD targets
    dueling: bool = False              # Q = V + A - mean(A) (two heads)
    prioritized_replay: bool = False   # PER (Schaul et al. 2016)
    per_alpha: float = 0.6             # priority exponent
    per_beta: float = 0.4              # IS-correction start (anneals to 1)
    per_beta_anneal_steps: int = 50_000   # in gradient updates
    per_eps: float = 1e-6              # priority floor


def nstep_transitions(obs, actions, rewards, next_obs, dones,
                      T: int, E: int, n: int, gamma: float,
                      ends=None):
    """Collapse a [T*E] rollout fragment into n-step transitions.

    Per env column, each step t gets return sum_k gamma^k r_{t+k} over
    its window, the window's LAST next_obs/done, and the EFFECTIVE
    discount gamma^len(window) — so a shortened window is still an exact
    (shorter) multi-step target, not a biased one (reference: Rainbow's
    n-step component; rllib stores n_step per batch the same way).

    Windows cut at ``ends`` (term OR trunc — any episode boundary: a
    time-limit truncation still separates episodes, so rewards must
    never sum across it) while ``dones`` (term only, when the true final
    obs is known) stays the bootstrap mask. Without ``ends``, ``dones``
    cuts — correct only when the collector treats truncation as
    terminal.
    """
    N = T * E
    R = np.zeros(N, np.float32)
    nxt = np.empty_like(next_obs)
    dn = np.zeros(N, np.float32)
    gm = np.empty(N, np.float32)
    r2 = rewards.reshape(T, E)
    e2 = (dones if ends is None else ends).reshape(T, E)
    for e in range(E):
        for t in range(T):
            acc, g = 0.0, 1.0
            k = 0
            while True:
                acc += g * float(r2[t + k, e])
                g *= gamma
                if e2[t + k, e] or k == n - 1 or t + k == T - 1:
                    break
                k += 1
            i = t * E + e
            j = (t + k) * E + e
            R[i] = acc
            nxt[i] = next_obs[j]
            dn[i] = dones[j]
            gm[i] = g
    return {"obs": obs, "actions": actions, "rewards": R,
            "next_obs": nxt, "dones": dn, "gammas": gm}


class ReplayBuffer:
    """Ring buffer over transitions, uniform or prioritized (reference:
    utils/replay_buffers/ episode_replay_buffer.py +
    prioritized_episode_replay_buffer.py, reduced to the flat case)."""

    def __init__(self, capacity: int, obs_dim: int, gamma: float = 0.99):
        self.capacity = capacity
        self.obs = np.empty((capacity, obs_dim), np.float32)
        self.next_obs = np.empty((capacity, obs_dim), np.float32)
        self.actions = np.empty((capacity,), np.int32)
        self.rewards = np.empty((capacity,), np.float32)
        self.dones = np.empty((capacity,), np.float32)
        # per-transition effective discount (gamma^n_step_len)
        self.gammas = np.full((capacity,), gamma, np.float32)
        # PER priorities; new entries get the max seen so every
        # transition is trained on at least once (Schaul et al. §3.3)
        self.prios = np.ones((capacity,), np.float64)
        self.max_prio = 1.0
        self.size = 0
        self.pos = 0

    def add_batch(self, obs, actions, rewards, next_obs, dones,
                  gammas=None):
        n = len(actions)
        idx = (self.pos + np.arange(n)) % self.capacity
        self.obs[idx] = obs
        self.next_obs[idx] = next_obs
        self.actions[idx] = actions
        self.rewards[idx] = rewards
        self.dones[idx] = dones
        if gammas is not None:
            self.gammas[idx] = gammas
        self.prios[idx] = self.max_prio
        self.pos = int((self.pos + n) % self.capacity)
        self.size = int(min(self.size + n, self.capacity))

    def sample_indices(self, rng: np.random.Generator, batch: int,
                      k: int) -> np.ndarray:
        return rng.integers(0, self.size, size=(k, batch))

    def sample_prioritized(self, rng: np.random.Generator, batch: int,
                           k: int, alpha: float, beta: float):
        """(indices [k,batch], IS weights [k,batch] normalized by their
        max) — probability p_i^alpha / sum, weights (N P_i)^-beta."""
        p = self.prios[:self.size] ** alpha
        P = p / p.sum()
        idx = rng.choice(self.size, size=(k, batch), p=P)
        w = (self.size * P[idx]) ** (-beta)
        w = w / w.max()
        return idx, w.astype(np.float32)

    def update_priorities(self, idx: np.ndarray, td_abs: np.ndarray,
                          eps: float) -> None:
        pr = np.abs(td_abs).astype(np.float64).ravel() + eps
        self.prios[idx.ravel()] = pr
        m = float(pr.max()) if len(pr) else 1.0
        self.max_prio = max(self.max_prio, m)


class DQNRunner:
    """Epsilon-greedy transition collector over a vector env."""

    def __init__(self, env_fn: Callable, num_envs: int, rollout_len: int,
                 seed: int = 0):
        import gymnasium as gym
        self._venv = gym.vector.SyncVectorEnv(
            [(lambda f=env_fn: f()) for _ in range(num_envs)],
            autoreset_mode=gym.vector.AutoresetMode.SAME_STEP)
        self._num_envs = num_envs
        self._rollout_len = rollout_len
        self._obs, _ = self._venv.reset(seed=seed)
        self._rng = np.random.default_rng(seed + 1)
        self._q_fn = None
        self._ep_return = np.zeros(num_envs, np.float64)
        self._completed: list[float] = []

    def sample(self, params, eps: float) -> dict:
        import jax
        if self._q_fn is None:
            self._q_fn = jax.jit(module_lib.deterministic_action)
        T, E = self._rollout_len, self._num_envs
        obs_dim = self._obs.shape[1]
        obs_b = np.empty((T * E, obs_dim), np.float32)
        nxt_b = np.empty((T * E, obs_dim), np.float32)
        act_b = np.empty((T * E,), np.int32)
        rew_b = np.empty((T * E,), np.float32)
        done_b = np.empty((T * E,), np.float32)
        end_b = np.empty((T * E,), np.float32)  # term|trunc: episode cut
        n_actions = self._venv.single_action_space.n
        for t in range(T):
            greedy = np.asarray(self._q_fn(
                params, self._obs.astype(np.float32)))
            explore = self._rng.random(E) < eps
            random_a = self._rng.integers(0, n_actions, size=E)
            action = np.where(explore, random_a, greedy).astype(np.int32)
            nxt, rew, term, trunc, info = self._venv.step(action)
            # bootstrap through time-limit truncation, not termination —
            # but with the TRUE final observation: under SAME_STEP
            # autoreset `nxt` already holds the next episode's reset obs
            # for ended envs (gymnasium puts the real one in info)
            nxt_td = nxt
            ended = np.logical_or(term, trunc)
            final = info.get("final_obs") if isinstance(info, dict) else None
            if final is not None and ended.any():
                nxt_td = nxt.copy()
                for i in np.nonzero(ended)[0]:
                    if final[i] is not None:
                        nxt_td[i] = final[i]
                done_for_td = term.astype(np.float32)
            else:
                # no final obs available: treat truncation as terminal
                # rather than bootstrapping from a reset state
                done_for_td = ended.astype(np.float32)
            sl = slice(t * E, (t + 1) * E)
            obs_b[sl] = self._obs
            nxt_b[sl] = nxt_td
            act_b[sl] = action
            rew_b[sl] = rew
            done_b[sl] = done_for_td
            end_b[sl] = ended.astype(np.float32)
            self._ep_return += rew
            for i in np.nonzero(np.logical_or(term, trunc))[0]:
                self._completed.append(float(self._ep_return[i]))
                self._ep_return[i] = 0.0
            self._obs = nxt
        episodes, self._completed = self._completed, []
        return {"obs": obs_b, "actions": act_b, "rewards": rew_b,
                "next_obs": nxt_b, "dones": done_b, "ends": end_b,
                "episode_returns": episodes,
                "rollout_len": T, "num_envs": E}

    def evaluate(self, params, num_episodes: int = 5) -> dict:
        import jax
        det = jax.jit(module_lib.deterministic_action)
        env = self._venv.envs[0]
        returns = []
        for ep in range(num_episodes):
            obs, _ = env.reset(seed=20_000 + ep)
            total, done = 0.0, False
            while not done:
                a = int(np.asarray(det(params, obs.astype(np.float32))))
                obs, rew, term, trunc, _ = env.step(a)
                total += float(rew)
                done = bool(term or trunc)
            returns.append(total)
        self._obs, _ = self._venv.reset()
        self._ep_return[:] = 0.0  # in-progress episodes were discarded
        return {"episode_returns": returns,
                "mean_return": float(np.mean(returns))}


class DQNLearner:
    """Jitted K-minibatch TD update (one compiled program per train())."""

    def __init__(self, module_cfg: MLPConfig, cfg: DQNConfig, seed: int = 0,
                 mesh=None):
        import jax
        import optax
        self.cfg = cfg
        self.module_cfg = module_cfg
        self.params = module_lib.init(jax.random.PRNGKey(seed), module_cfg)
        self.target_params = jax.tree.map(lambda x: x, self.params)
        self.optimizer = optax.adam(cfg.lr)
        self.opt_state = self.optimizer.init(self.params)
        self.updates_done = 0
        self._update = jax.jit(self._build_update())

    def _build_update(self):
        import jax
        import jax.numpy as jnp
        cfg = self.cfg

        def q_values(params, obs):
            logits, value = module_lib.logits_and_value(params, obs)
            if cfg.dueling:
                # Q = V + A - mean(A): the module's value head is the
                # state-value stream, the pi head the advantage stream
                # (reference: Rainbow's dueling architecture)
                return value[..., None] + logits - \
                    logits.mean(axis=-1, keepdims=True)
            return logits  # the pi head doubles as the Q head

        def loss_fn(params, target_params, batch):
            q = q_values(params, batch["obs"])
            q_a = jnp.take_along_axis(
                q, batch["actions"][:, None].astype(jnp.int32), 1)[:, 0]
            q_next_t = q_values(target_params, batch["next_obs"])
            if cfg.double_q:
                # action from the ONLINE net, value from the target net
                a_star = jnp.argmax(
                    q_values(params, batch["next_obs"]), axis=-1)
                q_next = jnp.take_along_axis(
                    q_next_t, a_star[:, None], 1)[:, 0]
            else:
                q_next = q_next_t.max(axis=-1)
            # per-sample effective discount: gamma^window for n-step
            target = batch["rewards"] + batch["gammas"] * (
                1.0 - batch["dones"]) * jax.lax.stop_gradient(q_next)
            td = q_a - target
            # huber, importance-weighted (weights are 1 without PER)
            adelta = jnp.abs(td)
            loss = jnp.where(
                adelta <= cfg.huber_delta,
                0.5 * td ** 2,
                cfg.huber_delta * (adelta - 0.5 * cfg.huber_delta))
            return (batch["weights"] * loss).mean(), (
                adelta, adelta.mean(), q_a.mean())

        def k_updates(params, target_params, opt_state, data, idx):
            def one(carry, i):
                params, opt_state = carry
                batch = {k: v[i] for k, v in data.items()}
                (loss, (td_abs, td, qm)), grads = jax.value_and_grad(
                    loss_fn, has_aux=True)(params, target_params, batch)
                updates, opt_state = self.optimizer.update(
                    grads, opt_state, params)
                import optax
                params = optax.apply_updates(params, updates)
                return (params, opt_state), (loss, td_abs, td, qm)

            (params, opt_state), (losses, td_abs, tds, qms) = jax.lax.scan(
                one, (params, opt_state), jnp.arange(idx.shape[0]))
            return (params, opt_state, losses.mean(), tds.mean(),
                    qms.mean(), td_abs)

        def update(params, target_params, opt_state, obs, actions, rewards,
                   next_obs, dones, gammas, weights, idx):
            data = {
                "obs": obs[idx], "actions": actions[idx],
                "rewards": rewards[idx], "next_obs": next_obs[idx],
                "dones": dones[idx], "gammas": gammas[idx],
                "weights": weights,
            }
            return k_updates(params, target_params, opt_state, data, idx)

        return update

    def _per_beta(self) -> float:
        cfg = self.cfg
        frac = min(1.0, self.updates_done /
                   max(1, cfg.per_beta_anneal_steps))
        return cfg.per_beta + frac * (1.0 - cfg.per_beta)

    def update_from_buffer(self, buf: ReplayBuffer,
                           rng: np.random.Generator) -> dict:
        import jax.numpy as jnp
        cfg = self.cfg
        k = cfg.num_updates_per_iter
        if cfg.prioritized_replay:
            idx, weights = buf.sample_prioritized(
                rng, cfg.batch_size, k, cfg.per_alpha, self._per_beta())
        else:
            idx = buf.sample_indices(rng, cfg.batch_size, k)
            weights = np.ones((k, cfg.batch_size), np.float32)
        # full-capacity arrays: fixed shapes -> ONE compile for the whole
        # run (indices never reach past buf.size)
        (self.params, self.opt_state, loss, td, qm,
         td_abs) = self._update(
            self.params, self.target_params, self.opt_state,
            jnp.asarray(buf.obs), jnp.asarray(buf.actions),
            jnp.asarray(buf.rewards), jnp.asarray(buf.next_obs),
            jnp.asarray(buf.dones), jnp.asarray(buf.gammas),
            jnp.asarray(weights), jnp.asarray(idx))
        if cfg.prioritized_replay:
            buf.update_priorities(idx, np.asarray(td_abs), cfg.per_eps)
        self.updates_done += k
        if self.updates_done % cfg.target_update_freq < k:
            import jax
            self.target_params = jax.tree.map(lambda x: x, self.params)
        return {"loss": float(loss), "td_error": float(td),
                "q_mean": float(qm)}


class DQN(AlgorithmBase):
    """The Algorithm driver (reference: dqn.py DQN.training_step)."""

    HPARAM_FIELD = "dqn"

    def __init__(self, config: "DQNAlgorithmConfig"):
        self._setup(config, DQNRunner)
        self.learner = DQNLearner(self.module_cfg, config.dqn,
                                  seed=config.seed)
        self.buffer = ReplayBuffer(config.dqn.buffer_size,
                                   self.module_cfg.obs_dim,
                                   gamma=config.dqn.gamma)
        self._np_rng = np.random.default_rng(config.seed)

    def _epsilon(self) -> float:
        cfg = self.config.dqn
        frac = min(1.0, self._total_env_steps / max(1, cfg.eps_decay_steps))
        return cfg.eps_start + frac * (cfg.eps_end - cfg.eps_start)

    def train(self) -> dict:
        ray = self._ray
        t0 = time.perf_counter()
        eps = self._epsilon()
        weights_ref = ray.put(self.learner.params)
        samples = ray.get([r.sample.remote(weights_ref, eps)
                           for r in self._runners])
        n = self.config.dqn.n_step
        for s in samples:
            if n > 1:
                t = nstep_transitions(
                    s["obs"], s["actions"], s["rewards"], s["next_obs"],
                    s["dones"], s["rollout_len"], s["num_envs"], n,
                    self.config.dqn.gamma, ends=s.get("ends"))
                self.buffer.add_batch(t["obs"], t["actions"],
                                      t["rewards"], t["next_obs"],
                                      t["dones"], gammas=t["gammas"])
            else:
                self.buffer.add_batch(s["obs"], s["actions"],
                                      s["rewards"], s["next_obs"],
                                      s["dones"])
        mean_ret = self._note_returns(
            [r for s in samples for r in s["episode_returns"]])
        steps = sum(len(s["actions"]) for s in samples)
        self._total_env_steps += steps

        stats = {}
        if self._total_env_steps >= self.config.dqn.learning_starts:
            stats = self.learner.update_from_buffer(self.buffer,
                                                    self._np_rng)
        self.iteration += 1
        dt = time.perf_counter() - t0
        return {
            "training_iteration": self.iteration,
            "episode_return_mean": mean_ret,
            "epsilon": eps,
            "num_env_steps_sampled": steps,
            "num_env_steps_sampled_lifetime": self._total_env_steps,
            "env_steps_per_sec": steps / dt,
            "buffer_size": self.buffer.size,
            **{f"learner/{k}": v for k, v in stats.items()},
        }

    def _extra_state(self) -> dict:
        return {"target_params": self.learner.target_params}

    def _load_extra_state(self, state: dict) -> None:
        import jax
        import jax.numpy as jnp
        self.learner.target_params = jax.tree.map(
            jnp.asarray, state["target_params"])


class DQNAlgorithmConfig(AlgorithmConfigBase):
    """Fluent config for the DQN family (base: AlgorithmConfigBase)."""

    HPARAM_FIELD = "dqn"
    HPARAM_FACTORY = DQNConfig

    @property
    def ALGO_CLS(self):
        return DQN
