"""EnvRunner: sampling actor over a gymnasium vector env.

Reference parity: rllib/env/single_agent_env_runner.py:68 (sample :149 —
vectorized gym envs stepped with the module's exploration forward) and
env_runner_group.py:71 (the actor group fanning sample() out). TPU-first
split: env runners are cheap CPU actors; the policy forward inside them is
a jitted JAX function on host CPU, while the learner's copy of the same
module trains on the accelerator mesh. Weights flow runner-ward through the
object store once per iteration (the reference broadcasts torch state dicts
the same way).
"""
from __future__ import annotations

from typing import Callable, Optional

import numpy as np

from . import module as module_lib


class EnvRunner:
    """Collects fixed-length rollout fragments from a vector env.

    Returned sample batch layout (numpy, time-major):
      obs      [T, E, obs_dim]   observations BEFORE each step
      actions  [T, E]
      logp     [T, E]            behaviour log-probs (for the PPO ratio)
      values   [T, E]            value estimates at obs
      rewards  [T, E]
      dones    [T, E]            episode terminated/truncated after step t
      last_obs [E, obs_dim]      for bootstrap value
    """

    def __init__(self, env_fn: Callable, num_envs: int, rollout_len: int,
                 seed: int = 0, connectors=None):
        import gymnasium as gym

        # SAME_STEP autoreset: the env resets within the step() that ends an
        # episode, so every recorded transition is real. gymnasium 1.x's
        # NEXT_STEP default would make the post-done step a phantom
        # transition (action ignored, reward 0) that biases GAE.
        self._venv = gym.vector.SyncVectorEnv(
            [_make_env(env_fn) for _ in range(num_envs)],
            autoreset_mode=gym.vector.AutoresetMode.SAME_STEP)
        self._num_envs = num_envs
        self._rollout_len = rollout_len
        self._obs, _ = self._venv.reset(seed=seed)
        self._rng = np.random.default_rng(seed + 1)
        # env-to-module connector pipeline (reference:
        # connectors/env_to_module/ applied in env_runner sample); obs are
        # stored POST-transform so the learner trains on what the policy saw
        self._connectors = connectors
        self._sample_fn = None
        # per-env running episode returns for metrics
        self._ep_return = np.zeros(num_envs, np.float64)
        self._ep_len = np.zeros(num_envs, np.int64)
        self._completed: list[tuple[float, int]] = []

    def _transform(self, obs, update: bool = True) -> np.ndarray:
        ob = np.asarray(obs, np.float32)
        if self._connectors is None:
            return ob
        return self._connectors(ob, update=update)

    def get_connector_state(self):
        return (self._connectors.get_state()
                if self._connectors is not None else None)

    def set_connector_state(self, state) -> None:
        if self._connectors is not None:
            self._connectors.set_state(state)

    def _policy(self):
        if self._sample_fn is None:
            import jax
            self._sample_fn = jax.jit(module_lib.sample_action)
            self._value_fn = jax.jit(
                lambda p, o: module_lib.logits_and_value(p, o)[1])
        return self._sample_fn

    def sample(self, params) -> dict:
        """One rollout fragment with the given module params."""
        import jax

        T, E = self._rollout_len, self._num_envs
        policy = self._policy()
        obs_buf = None  # allocated from the first TRANSFORMED obs shape
        act_buf = np.empty((T, E), np.int64)
        logp_buf = np.empty((T, E), np.float32)
        val_buf = np.empty((T, E), np.float32)
        rew_buf = np.empty((T, E), np.float32)
        done_buf = np.empty((T, E), np.bool_)

        key = jax.random.PRNGKey(int(self._rng.integers(2**31)))
        for t in range(T):
            key, sub = jax.random.split(key)
            ob = self._transform(self._obs)
            if obs_buf is None:
                obs_buf = np.empty((T, E) + ob.shape[1:], np.float32)
            action, logp, value = policy(params, ob, sub)
            action = np.asarray(action)
            obs_buf[t] = ob
            act_buf[t] = action
            logp_buf[t] = np.asarray(logp)
            val_buf[t] = np.asarray(value)
            nxt, rew, term, trunc, _ = self._venv.step(action)
            done = np.logical_or(term, trunc)
            rew_buf[t] = rew
            done_buf[t] = done
            self._ep_return += rew
            self._ep_len += 1
            for i in np.nonzero(done)[0]:
                self._completed.append(
                    (float(self._ep_return[i]), int(self._ep_len[i])))
                self._ep_return[i] = 0.0
                self._ep_len[i] = 0
            self._obs = nxt

        episodes, self._completed = self._completed, []
        # boundary obs is a READ: the next sample()'s t=0 will accumulate
        # this same observation — updating here would double-weight it
        last_ob = self._transform(self._obs, update=False)
        last_value = np.asarray(self._value_fn(params, last_ob))
        return {
            "obs": obs_buf, "actions": act_buf, "logp": logp_buf,
            "values": val_buf, "rewards": rew_buf, "dones": done_buf,
            "last_obs": last_ob,
            "last_value": last_value,
            "episode_returns": [r for r, _ in episodes],
            "episode_lens": [n for _, n in episodes],
        }

    def evaluate(self, params, num_episodes: int = 5) -> dict:
        """Greedy-policy evaluation episodes (fresh env, no training state)."""
        import gymnasium as gym
        import jax

        det = jax.jit(module_lib.deterministic_action)
        env = self._venv.envs[0]
        returns = []
        for ep in range(num_episodes):
            obs, _ = env.reset(seed=10_000 + ep)
            total, done = 0.0, False
            while not done:
                # frozen stats: evaluation must not contaminate training
                # normalization state
                ob = self._transform(obs[None], update=False)[0]
                a = int(np.asarray(det(params, ob)))
                obs, rew, term, trunc, _ = env.step(a)
                total += float(rew)
                done = bool(term or trunc)
        # note: env state is shared with sampling; reset on exit
            returns.append(total)
        self._obs, _ = self._venv.reset()
        # in-progress episodes were discarded with the reset
        self._ep_return[:] = 0.0
        self._ep_len[:] = 0
        return {"episode_returns": returns,
                "mean_return": float(np.mean(returns))}


def _make_env(env_fn):
    return lambda: env_fn()


def make_gym_env(env_id: str, **kwargs) -> Callable:
    """Picklable env constructor for gymnasium registry ids."""
    import functools

    return functools.partial(_gym_make, env_id, kwargs)


def _gym_make(env_id, kwargs):
    import gymnasium as gym

    return gym.make(env_id, **kwargs)
