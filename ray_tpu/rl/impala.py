"""IMPALA: asynchronous actor-critic with V-trace off-policy correction.

Reference parity: rllib/algorithms/impala/ (async EnvRunner sampling
decoupled from the learner, V-trace per Espeholt et al. 2018 correcting
the policy lag). The driver keeps every runner busy via ray.wait —
sample fragments stream in as they finish, the learner updates on each,
and refreshed weights ship to a runner only when it starts its next
fragment (so behaviour policies genuinely lag, which V-trace corrects
with clipped importance ratios).

TPU-first: the whole V-trace computation (reverse scan over the fragment)
+ policy/value update is ONE jitted program; runners stay cheap CPU
actors (rl/env_runner.py).
"""
from __future__ import annotations

import dataclasses
import time
from typing import Callable, Optional

import numpy as np

from . import module as module_lib
from .base import AlgorithmBase, AlgorithmConfigBase
from .env_runner import EnvRunner, make_gym_env
from .module import MLPConfig


@dataclasses.dataclass(frozen=True)
class ImpalaConfig:
    """(reference: impala.py IMPALAConfig.training)"""
    lr: float = 5e-4
    gamma: float = 0.99
    rho_bar: float = 1.0          # importance-ratio clip for targets
    c_bar: float = 1.0            # trace-cutting clip
    vf_coeff: float = 0.5
    entropy_coeff: float = 0.01
    grad_clip: float = 40.0


def vtrace(behaviour_logp, target_logp, rewards, values, dones,
           bootstrap_value, gamma, rho_bar, c_bar):
    """V-trace targets + pg advantages (time-major [T, B] arrays).

    Returns (vs [T, B], pg_adv [T, B]) per Espeholt et al. eq. (1).
    """
    import jax
    import jax.numpy as jnp

    rhos = jnp.exp(target_logp - behaviour_logp)
    clipped_rhos = jnp.minimum(rho_bar, rhos)
    cs = jnp.minimum(c_bar, rhos)
    discounts = gamma * (1.0 - dones)

    values_tp1 = jnp.concatenate(
        [values[1:], bootstrap_value[None]], axis=0)
    deltas = clipped_rhos * (rewards + discounts * values_tp1 - values)

    def step(acc, xs):
        delta, discount, c = xs
        acc = delta + discount * c * acc
        return acc, acc

    _, vs_minus_v = jax.lax.scan(
        step, jnp.zeros_like(bootstrap_value),
        (deltas, discounts, cs), reverse=True)
    vs = vs_minus_v + values
    vs_tp1 = jnp.concatenate([vs[1:], bootstrap_value[None]], axis=0)
    pg_adv = clipped_rhos * (rewards + discounts * vs_tp1 - values)
    return jax.lax.stop_gradient(vs), jax.lax.stop_gradient(pg_adv)


class ImpalaLearner:
    def __init__(self, module_cfg: MLPConfig, cfg: ImpalaConfig,
                 seed: int = 0):
        import jax
        import optax
        self.cfg = cfg
        self.params = module_lib.init(jax.random.PRNGKey(seed), module_cfg)
        self.optimizer = optax.chain(
            optax.clip_by_global_norm(cfg.grad_clip),
            optax.rmsprop(cfg.lr, decay=0.99, eps=0.1))
        self.opt_state = self.optimizer.init(self.params)
        self._update = jax.jit(self._build_update())

    def _build_update(self):
        import jax
        import jax.numpy as jnp
        import optax
        cfg = self.cfg

        def loss_fn(params, batch):
            logits, values = module_lib.logits_and_value(
                params, batch["obs"])                       # [T, B, A]/[T, B]
            logp_all = jax.nn.log_softmax(logits, axis=-1)
            target_logp = jnp.take_along_axis(
                logp_all, batch["actions"][..., None], axis=-1)[..., 0]
            vs, pg_adv = vtrace(
                batch["logp"], target_logp, batch["rewards"], values,
                batch["dones"], batch["bootstrap_value"],
                cfg.gamma, cfg.rho_bar, cfg.c_bar)
            pg_loss = -jnp.mean(target_logp * pg_adv)
            vf_loss = 0.5 * jnp.mean((vs - values) ** 2)
            entropy = -jnp.mean(
                jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1))
            total = (pg_loss + cfg.vf_coeff * vf_loss
                     - cfg.entropy_coeff * entropy)
            return total, (pg_loss, vf_loss, entropy)

        def update(params, opt_state, batch):
            (loss, aux), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, batch)
            updates, opt_state = self.optimizer.update(
                grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, loss, aux

        return update

    def update(self, sample: dict) -> dict:
        import jax.numpy as jnp
        batch = {
            "obs": jnp.asarray(sample["obs"]),
            "actions": jnp.asarray(sample["actions"]),
            "logp": jnp.asarray(sample["logp"]),
            "rewards": jnp.asarray(sample["rewards"]),
            "dones": jnp.asarray(sample["dones"], jnp.float32),
            "bootstrap_value": jnp.asarray(sample["last_value"]),
        }
        # shapes only for flops_estimate(): lower() needs abstract
        # shapes, and keeping the live arrays would pin a whole rollout
        # batch in device memory for the learner's lifetime; shapes are
        # static per run, so derive them once, not per SGD update
        if getattr(self, "_last_batch_shapes", None) is None:
            import jax
            self._last_batch_shapes = jax.tree_util.tree_map(
                lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), batch)
        self.params, self.opt_state, loss, (pg, vf, ent) = self._update(
            self.params, self.opt_state, batch)
        return {"loss": float(loss), "pg_loss": float(pg),
                "vf_loss": float(vf), "entropy": float(ent)}

    def flops_estimate(self):
        """FLOPs of one V-trace update at the last batch's shapes via
        XLA cost_analysis (one extra out-of-band compile); None before
        the first update or when XLA won't say."""
        shapes = getattr(self, "_last_batch_shapes", None)
        if shapes is None:
            return None
        from ..util.profiling import compiled_flops
        return compiled_flops(self._update, self.params,
                              self.opt_state, shapes)


class IMPALA(AlgorithmBase):
    """The async driver loop (reference: impala.py training_step)."""

    HPARAM_FIELD = "impala"

    def __init__(self, config: "ImpalaAlgorithmConfig"):
        self._setup(config, EnvRunner)
        self.learner = ImpalaLearner(self.module_cfg, config.impala,
                                     seed=config.seed)
        # async pipeline: every runner always has a sample in flight,
        # started with the weights current at ITS dispatch time
        self._inflight: dict = {}
        weights_ref = self._ray.put(self.learner.params)
        for r in self._runners:
            self._inflight[r.sample.remote(weights_ref)] = r

    def train(self) -> dict:
        """One iteration = one learner update per runner fragment, taken
        in completion order (true IMPALA asynchrony)."""
        ray = self._ray
        t0 = time.perf_counter()
        stats: dict = {}
        fragments = 0
        while fragments < len(self._runners):
            done, _ = ray.wait(list(self._inflight), num_returns=1,
                               timeout=30.0)
            if not done:
                break
            ref = done[0]
            runner = self._inflight.pop(ref)
            sample = ray.get(ref)
            # redispatch IMMEDIATELY with fresh weights — the learner
            # update below overlaps the runner's next fragment
            weights_ref = ray.put(self.learner.params)
            self._inflight[runner.sample.remote(weights_ref)] = runner
            stats = self.learner.update(sample)
            fragments += 1
            steps = int(np.prod(sample["actions"].shape))
            self._total_env_steps += steps
            self._note_returns(sample["episode_returns"])
        self._sync_connector_state()
        mean_ret = self._note_returns(())
        self.iteration += 1
        dt = time.perf_counter() - t0
        return {
            "training_iteration": self.iteration,
            "episode_return_mean": mean_ret,
            "num_env_steps_sampled_lifetime": self._total_env_steps,
            "env_steps_per_sec": (
                fragments * self.config.rollout_len
                * self.config.num_envs_per_runner / max(dt, 1e-9)),
            **{f"learner/{k}": v for k, v in stats.items()},
        }



class ImpalaAlgorithmConfig(AlgorithmConfigBase):
    """Fluent config for IMPALA (base: AlgorithmConfigBase)."""

    HPARAM_FIELD = "impala"
    HPARAM_FACTORY = ImpalaConfig

    @property
    def ALGO_CLS(self):
        return IMPALA
