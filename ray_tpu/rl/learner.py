"""PPO Learner: jitted loss + GAE + minibatch SGD over an optional mesh.

Reference parity: rllib/core/learner/learner.py:108 (update :978 — the
gradient step over a sample batch) and rllib/algorithms/ppo's torch loss.
TPU-first inversion: instead of DDP-wrapped torch modules on learner actors
(rllib/core/learner/torch/torch_learner.py:67), the whole update — GAE,
advantage normalization, E epochs x M minibatches of clipped-surrogate
SGD — is ONE jitted function. With a mesh, the batch axis is sharded dp and
XLA inserts the gradient psums (the NCCL allreduce analog).
"""
from __future__ import annotations

import dataclasses
import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from . import module as module_lib


@dataclasses.dataclass(frozen=True)
class PPOConfig:
    lr: float = 3e-4
    gamma: float = 0.99
    gae_lambda: float = 0.95
    clip_eps: float = 0.2
    vf_coeff: float = 0.5
    entropy_coeff: float = 0.01
    num_epochs: int = 4
    num_minibatches: int = 4
    max_grad_norm: float = 0.5
    # anneal entropy/lr could be added by the algorithm; kept static here


def compute_gae(rewards, values, dones, last_value, gamma, lam):
    """Time-major GAE. rewards/values/dones [T, E], last_value [E].
    Returns (advantages [T, E], returns [T, E])."""
    def step(carry, xs):
        rew, val, done = xs
        next_val, gae = carry
        nonterminal = 1.0 - done
        delta = rew + gamma * next_val * nonterminal - val
        gae = delta + gamma * lam * nonterminal * gae
        return (val, gae), gae

    (_, _), adv_rev = jax.lax.scan(
        step, (last_value, jnp.zeros_like(last_value)),
        (rewards[::-1], values[::-1], dones[::-1].astype(jnp.float32)))
    adv = adv_rev[::-1]
    return adv, adv + values


def _ppo_loss(params, batch, cfg: PPOConfig):
    logits, value = module_lib.logits_and_value(params, batch["obs"])
    logp_all = jax.nn.log_softmax(logits, axis=-1)
    logp = jnp.take_along_axis(
        logp_all, batch["actions"][..., None], axis=-1)[..., 0]
    ratio = jnp.exp(logp - batch["logp"])
    adv = batch["advantages"]
    pg1 = ratio * adv
    pg2 = jnp.clip(ratio, 1 - cfg.clip_eps, 1 + cfg.clip_eps) * adv
    pg_loss = -jnp.mean(jnp.minimum(pg1, pg2))
    vf_loss = 0.5 * jnp.mean((value - batch["returns"]) ** 2)
    entropy = -jnp.mean(
        jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1))
    loss = pg_loss + cfg.vf_coeff * vf_loss - cfg.entropy_coeff * entropy
    stats = {
        "policy_loss": pg_loss, "vf_loss": vf_loss, "entropy": entropy,
        "approx_kl": jnp.mean(batch["logp"] - logp),
        "clip_frac": jnp.mean(
            (jnp.abs(ratio - 1.0) > cfg.clip_eps).astype(jnp.float32)),
    }
    return loss, stats


def _update_step(params, opt_state, batch, rng, cfg: PPOConfig, optimizer):
    """E epochs x M shuffled minibatches, all inside jit via lax.scan."""
    n = batch["obs"].shape[0]
    mb = n // cfg.num_minibatches

    def epoch(carry, key):
        params, opt_state = carry
        perm = jax.random.permutation(key, n)

        def minibatch(carry, idx):
            params, opt_state = carry
            sel = jax.lax.dynamic_slice_in_dim(perm, idx * mb, mb)
            mb_batch = {k: v[sel] for k, v in batch.items()}
            (loss, stats), grads = jax.value_and_grad(
                _ppo_loss, has_aux=True)(params, mb_batch, cfg)
            updates, opt_state = optimizer.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            stats["total_loss"] = loss
            return (params, opt_state), stats

        (params, opt_state), stats = jax.lax.scan(
            minibatch, (params, opt_state),
            jnp.arange(cfg.num_minibatches))
        return (params, opt_state), stats

    keys = jax.random.split(rng, cfg.num_epochs)
    (params, opt_state), stats = jax.lax.scan(
        epoch, (params, opt_state), keys)
    mean_stats = {k: jnp.mean(v) for k, v in stats.items()}
    return params, opt_state, mean_stats


class PPOLearner:
    """Owns module params + optimizer state; `update(samples)` is one PPO
    iteration. With `mesh`, the flattened batch is sharded over the "dp"
    axis and params are replicated — XLA inserts gradient all-reduces."""

    def __init__(self, module_cfg: module_lib.MLPConfig,
                 cfg: Optional[PPOConfig] = None, seed: int = 0,
                 mesh=None):
        self.cfg = cfg or PPOConfig()
        self.module_cfg = module_cfg
        self.params = module_lib.init(jax.random.PRNGKey(seed), module_cfg)
        self.optimizer = optax.chain(
            optax.clip_by_global_norm(self.cfg.max_grad_norm),
            optax.adam(self.cfg.lr),
        )
        self.opt_state = self.optimizer.init(self.params)
        self._rng = jax.random.PRNGKey(seed + 1)
        self.mesh = mesh
        self._jit_update = None
        self._jit_gae = jax.jit(functools.partial(
            compute_gae, gamma=self.cfg.gamma, lam=self.cfg.gae_lambda))

    def _build_update(self):
        cfg, optimizer = self.cfg, self.optimizer
        fn = functools.partial(_update_step, cfg=cfg, optimizer=optimizer)
        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            batch_sh = NamedSharding(self.mesh, P("dp"))
            repl = NamedSharding(self.mesh, P())
            return jax.jit(
                fn,
                in_shardings=(repl, repl,
                              jax.tree.map(lambda _: batch_sh, {
                                  "obs": 0, "actions": 0, "logp": 0,
                                  "advantages": 0, "returns": 0}),
                              repl),
                out_shardings=(repl, repl, repl))
        return jax.jit(fn)

    def update(self, samples: list[dict]) -> dict:
        """samples: list of env-runner fragments (time-major numpy)."""
        cfg = self.cfg
        gae = self._jit_gae
        obs, acts, logps, advs, rets = [], [], [], [], []
        for s in samples:
            adv, ret = gae(jnp.asarray(s["rewards"]),
                           jnp.asarray(s["values"]),
                           jnp.asarray(s["dones"]),
                           jnp.asarray(s["last_value"]))
            obs.append(np.asarray(s["obs"]).reshape(-1, s["obs"].shape[-1]))
            acts.append(np.asarray(s["actions"]).reshape(-1))
            logps.append(np.asarray(s["logp"]).reshape(-1))
            advs.append(np.asarray(adv).reshape(-1))
            rets.append(np.asarray(ret).reshape(-1))
        batch = {
            "obs": np.concatenate(obs),
            "actions": np.concatenate(acts),
            "logp": np.concatenate(logps),
            "advantages": np.concatenate(advs),
            "returns": np.concatenate(rets),
        }
        # advantage normalization over the full batch (rllib default)
        a = batch["advantages"]
        batch["advantages"] = (a - a.mean()) / (a.std() + 1e-8)
        # trim so num_minibatches divides the batch (static shapes for jit)
        n = (len(a) // cfg.num_minibatches) * cfg.num_minibatches
        batch = {k: jnp.asarray(v[:n]) for k, v in batch.items()}

        if self._jit_update is None:
            self._jit_update = self._build_update()
        self._rng, sub = jax.random.split(self._rng)
        self.params, self.opt_state, stats = self._jit_update(
            self.params, self.opt_state, batch, sub)
        return {k: float(v) for k, v in stats.items()}

    def get_params(self):
        return jax.device_get(self.params)

    def set_params(self, params):
        self.params = jax.tree.map(jnp.asarray, params)
