"""RLModule: the policy/value network container, pure-pytree JAX.

Reference parity: rllib/core/rl_module/rl_module.py:258 (RLModule holds the
networks and exposes forward_exploration / forward_train). TPU-first design:
params are a plain pytree and every forward is a pure function, so the same
module runs jitted on a learner mesh and on CPU inside env-runner actors
with no framework glue (the reference needs torch DDP wrapping instead).
"""
from __future__ import annotations

import dataclasses
import math
from typing import Sequence

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class MLPConfig:
    obs_dim: int
    num_actions: int
    hidden: Sequence[int] = (64, 64)
    # separate value trunk (rllib's vf_share_layers=False default for PPO)
    shared_trunk: bool = False


def _dense_init(key, in_dim, out_dim, scale):
    w_key, _ = jax.random.split(key)
    std = scale / math.sqrt(in_dim)
    return {
        "w": jax.random.normal(w_key, (in_dim, out_dim), jnp.float32) * std,
        "b": jnp.zeros((out_dim,), jnp.float32),
    }


def _mlp_init(key, dims, out_dim, out_scale):
    keys = jax.random.split(key, len(dims))
    layers = []
    for i in range(len(dims) - 1):
        layers.append(_dense_init(keys[i], dims[i], dims[i + 1], 1.0))
    head = _dense_init(keys[-1], dims[-1], out_dim, out_scale)
    return {"layers": layers, "head": head}


def _mlp_apply(p, x):
    for layer in p["layers"]:
        x = jnp.tanh(x @ layer["w"] + layer["b"])
    return x @ p["head"]["w"] + p["head"]["b"]


def init(rng: jax.Array, cfg: MLPConfig) -> dict:
    k_pi, k_v = jax.random.split(rng)
    dims = (cfg.obs_dim, *cfg.hidden)
    return {
        # small-scale policy head init stabilizes early PPO updates
        "pi": _mlp_init(k_pi, dims, cfg.num_actions, 0.01),
        "vf": _mlp_init(k_v, dims, 1, 1.0),
    }


def logits_and_value(params: dict, obs: jax.Array):
    """obs [..., obs_dim] -> (logits [..., A], value [...])."""
    logits = _mlp_apply(params["pi"], obs)
    value = _mlp_apply(params["vf"], obs)[..., 0]
    return logits, value


def sample_action(params: dict, obs: jax.Array, rng: jax.Array):
    """Exploration forward: (action, logp, value), all [...]."""
    logits, value = logits_and_value(params, obs)
    action = jax.random.categorical(rng, logits, axis=-1)
    logp = jax.nn.log_softmax(logits, axis=-1)
    logp_a = jnp.take_along_axis(logp, action[..., None], axis=-1)[..., 0]
    return action, logp_a, value


def deterministic_action(params: dict, obs: jax.Array):
    logits, _ = logits_and_value(params, obs)
    return jnp.argmax(logits, axis=-1)
