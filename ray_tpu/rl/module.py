"""RLModule: the policy/value network container, pure-pytree JAX.

Reference parity: rllib/core/rl_module/rl_module.py:258 (RLModule holds the
networks and exposes forward_exploration / forward_train). TPU-first design:
params are a plain pytree and every forward is a pure function, so the same
module runs jitted on a learner mesh and on CPU inside env-runner actors
with no framework glue (the reference needs torch DDP wrapping instead).
"""
from __future__ import annotations

import dataclasses
import math
from typing import Sequence

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class MLPConfig:
    obs_dim: int
    num_actions: int
    hidden: Sequence[int] = (64, 64)
    # separate value trunk (rllib's vf_share_layers=False default for PPO)
    shared_trunk: bool = False


def _dense_init(key, in_dim, out_dim, scale):
    w_key, _ = jax.random.split(key)
    std = scale / math.sqrt(in_dim)
    return {
        "w": jax.random.normal(w_key, (in_dim, out_dim), jnp.float32) * std,
        "b": jnp.zeros((out_dim,), jnp.float32),
    }


def _mlp_init(key, dims, out_dim, out_scale):
    keys = jax.random.split(key, len(dims))
    layers = []
    for i in range(len(dims) - 1):
        layers.append(_dense_init(keys[i], dims[i], dims[i + 1], 1.0))
    head = _dense_init(keys[-1], dims[-1], out_dim, out_scale)
    return {"layers": layers, "head": head}


def _mlp_apply(p, x):
    for layer in p["layers"]:
        x = jnp.tanh(x @ layer["w"] + layer["b"])
    return x @ p["head"]["w"] + p["head"]["b"]


def init(rng: jax.Array, cfg: MLPConfig) -> dict:
    k_pi, k_v = jax.random.split(rng)
    dims = (cfg.obs_dim, *cfg.hidden)
    return {
        # small-scale policy head init stabilizes early PPO updates
        "pi": _mlp_init(k_pi, dims, cfg.num_actions, 0.01),
        "vf": _mlp_init(k_v, dims, 1, 1.0),
    }


def logits_and_value(params: dict, obs: jax.Array):
    """obs [..., obs_dim] -> (logits [..., A], value [...])."""
    logits = _mlp_apply(params["pi"], obs)
    value = _mlp_apply(params["vf"], obs)[..., 0]
    return logits, value


def sample_action(params: dict, obs: jax.Array, rng: jax.Array):
    """Exploration forward: (action, logp, value), all [...]."""
    logits, value = logits_and_value(params, obs)
    action = jax.random.categorical(rng, logits, axis=-1)
    logp = jax.nn.log_softmax(logits, axis=-1)
    logp_a = jnp.take_along_axis(logp, action[..., None], axis=-1)[..., 0]
    return action, logp_a, value


def deterministic_action(params: dict, obs: jax.Array):
    logits, _ = logits_and_value(params, obs)
    return jnp.argmax(logits, axis=-1)


# ---------------------------------------------------------------------------
# Continuous control (SAC): tanh-squashed Gaussian policy + twin Q critics
# (reference: rllib/core sac catalog / sac_torch_model)
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class ContinuousMLPConfig:
    obs_dim: int
    action_dim: int
    hidden: Sequence[int] = (128, 128)
    # scalar or per-dimension tuple (asymmetric Box bounds supported)
    action_low: float | Sequence[float] = -1.0
    action_high: float | Sequence[float] = 1.0
    log_std_min: float = -10.0
    log_std_max: float = 2.0


def init_sac(rng: jax.Array, cfg: ContinuousMLPConfig) -> dict:
    k_pi, k_q1, k_q2 = jax.random.split(rng, 3)
    pi_dims = (cfg.obs_dim, *cfg.hidden)
    q_dims = (cfg.obs_dim + cfg.action_dim, *cfg.hidden)
    return {
        "pi": _mlp_init(k_pi, pi_dims, 2 * cfg.action_dim, 0.01),
        "q1": _mlp_init(k_q1, q_dims, 1, 1.0),
        "q2": _mlp_init(k_q2, q_dims, 1, 1.0),
    }


def _bounds(cfg: ContinuousMLPConfig):
    low = jnp.asarray(cfg.action_low, jnp.float32)
    high = jnp.asarray(cfg.action_high, jnp.float32)
    return (high - low) / 2.0, (high + low) / 2.0


def _squash(cfg: ContinuousMLPConfig, u: jax.Array) -> jax.Array:
    """tanh squash then scale into [low, high] (per-dim bounds ok)."""
    half, mid = _bounds(cfg)
    return jnp.tanh(u) * half + mid


def sample_action_continuous(params: dict, obs: jax.Array, rng: jax.Array,
                             cfg: ContinuousMLPConfig):
    """(action in env bounds, logp) with the tanh-Gaussian correction."""
    out = _mlp_apply(params["pi"], obs)
    mu, log_std = jnp.split(out, 2, axis=-1)
    log_std = jnp.clip(log_std, cfg.log_std_min, cfg.log_std_max)
    std = jnp.exp(log_std)
    u = mu + std * jax.random.normal(rng, mu.shape)
    # base normal logp
    logp = -0.5 * (((u - mu) / std) ** 2 + 2 * log_std
                   + math.log(2 * math.pi))
    # tanh change of variables (numerically stable softplus form)
    logp = logp - 2.0 * (math.log(2.0) - u - jax.nn.softplus(-2.0 * u))
    half, _ = _bounds(cfg)
    logp = logp - jnp.log(half)
    return _squash(cfg, u), jnp.sum(logp, axis=-1)


def deterministic_action_continuous(params: dict, obs: jax.Array,
                                    cfg: ContinuousMLPConfig) -> jax.Array:
    mu, _ = jnp.split(_mlp_apply(params["pi"], obs), 2, axis=-1)
    return _squash(cfg, mu)


def q_values_continuous(params: dict, obs: jax.Array, action: jax.Array):
    """(q1, q2) for obs/action batches."""
    x = jnp.concatenate([obs, action], axis=-1)
    return (_mlp_apply(params["q1"], x)[..., 0],
            _mlp_apply(params["q2"], x)[..., 0])
