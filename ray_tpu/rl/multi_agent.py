"""Multi-agent RL: env API, runner, and multi-policy PPO.

Reference parity: rllib/env/multi_agent_env.py (MultiAgentEnv — dict
obs/action/reward keyed by agent id, "__all__" termination),
rllib/env/multi_agent_env_runner.py:68 (MultiAgentEnvRunner — steps ONE
multi-agent env, routes each agent through policy_mapping_fn to its
module), and the multi-policy training loop of algorithm.py (one learner
update per policy over its agents' transitions).

TPU-first shape: simultaneous-action envs (every agent acts each step)
let each policy's fragment keep the single-agent time-major [T, E]
layout with E = (#agents mapped to the policy) x (#runners) — so the
standard jitted PPOLearner (epochs x minibatches in one compiled
program, optional dp-mesh sharding) trains each policy unchanged. Agents
are just extra batch columns to the compiler.
"""
from __future__ import annotations

from typing import Callable, Optional, Union

import numpy as np

from . import module as module_lib
from .learner import PPOConfig, PPOLearner
from .module import MLPConfig


class MultiAgentEnv:
    """Simultaneous multi-agent env API (reference: multi_agent_env.py;
    the dict convention matches PettingZoo parallel envs).

    Subclasses set ``possible_agents`` plus per-agent
    ``observation_spaces`` / ``action_spaces`` dicts and implement:

      reset(seed) -> (obs_dict, info_dict)
      step(action_dict) -> (obs, rewards, terminations, truncations,
                            infos) — terminations may carry "__all__"
    """

    possible_agents: list = []
    observation_spaces: dict = {}
    action_spaces: dict = {}

    def reset(self, seed: Optional[int] = None):
        raise NotImplementedError

    def step(self, action_dict: dict):
        raise NotImplementedError

    def close(self):
        pass


class MultiAgentEnvRunner:
    """Samples fragments from ONE multi-agent env, batching each policy's
    agents into the columns of a single-agent-shaped fragment
    (reference: multi_agent_env_runner.py:68 sample())."""

    def __init__(self, env_fn: Callable, policy_mapping: dict,
                 rollout_len: int, seed: int = 0):
        self._env = env_fn()
        self._mapping = dict(policy_mapping)      # agent_id -> policy_id
        self._agents = list(self._env.possible_agents)
        self._rollout_len = rollout_len
        # stable per-policy agent column order
        self._cols: dict[str, list] = {}
        for a in self._agents:
            self._cols.setdefault(self._mapping[a], []).append(a)
        self._obs, _ = self._env.reset(seed=seed)
        self._rng = np.random.default_rng(seed + 1)
        self._fns = None
        self._ep_return = 0.0
        self._completed: list[float] = []

    def _policy_fns(self):
        if self._fns is None:
            import jax
            self._fns = (jax.jit(module_lib.sample_action),
                         jax.jit(lambda p, o:
                                 module_lib.logits_and_value(p, o)[1]),
                         jax.jit(module_lib.deterministic_action))
        return self._fns

    def _stack_obs(self, pid: str) -> np.ndarray:
        return np.stack([np.asarray(self._obs[a], np.float32).reshape(-1)
                         for a in self._cols[pid]])

    def sample(self, weights: dict) -> dict:
        """{policy_id: fragment} — each fragment is the single-agent
        layout (obs/actions/logp/values/rewards/dones [T, E], last_obs/
        last_value [E]) with one column per mapped agent."""
        import jax
        sample_fn, value_fn, _ = self._policy_fns()
        T = self._rollout_len
        bufs = {
            pid: {
                "obs": np.empty(
                    (T, len(cols)) + self._stack_obs(pid).shape[1:],
                    np.float32),
                "actions": np.empty((T, len(cols)), np.int64),
                "logp": np.empty((T, len(cols)), np.float32),
                "values": np.empty((T, len(cols)), np.float32),
                "rewards": np.empty((T, len(cols)), np.float32),
                "dones": np.empty((T, len(cols)), np.bool_),
            }
            for pid, cols in self._cols.items()
        }
        key = jax.random.PRNGKey(int(self._rng.integers(2**31)))
        for t in range(T):
            acts: dict = {}
            for pid, cols in self._cols.items():
                key, sub = jax.random.split(key)
                ob = self._stack_obs(pid)
                a, logp, val = sample_fn(weights[pid], ob, sub)
                a = np.asarray(a)
                bufs[pid]["obs"][t] = ob
                bufs[pid]["actions"][t] = a
                bufs[pid]["logp"][t] = np.asarray(logp)
                bufs[pid]["values"][t] = np.asarray(val)
                for j, agent in enumerate(cols):
                    acts[agent] = int(a[j])
            nxt, rews, terms, truncs, _ = self._env.step(acts)
            done = bool(terms.get("__all__", False)
                        or truncs.get("__all__", False)
                        or (self._agents
                            and all(terms.get(a, False)
                                    or truncs.get(a, False)
                                    for a in self._agents)))
            step_rew = 0.0
            for pid, cols in self._cols.items():
                for j, agent in enumerate(cols):
                    r = float(rews.get(agent, 0.0))
                    bufs[pid]["rewards"][t, j] = r
                    bufs[pid]["dones"][t, j] = done
                    step_rew += r
            self._ep_return += step_rew
            if done:
                self._completed.append(self._ep_return)
                self._ep_return = 0.0
                self._obs, _ = self._env.reset()
            else:
                self._obs = nxt
        out = {}
        episodes, self._completed = self._completed, []
        for pid in self._cols:
            last_obs = self._stack_obs(pid)
            out[pid] = {
                **bufs[pid],
                "last_obs": last_obs,
                "last_value": np.asarray(value_fn(weights[pid], last_obs)),
                # joint return (sum over agents) is the episode metric,
                # like the reference's default episode_return_mean
                "episode_returns": list(episodes),
                "episode_lens": [],
            }
        return out

    def evaluate(self, weights: dict, num_episodes: int = 5) -> dict:
        _, _, det = self._policy_fns()
        returns = []
        for ep in range(num_episodes):
            obs, _ = self._env.reset(seed=20_000 + ep)
            self._obs = obs
            total, done, steps = 0.0, False, 0
            while not done and steps < 10_000:
                acts = {}
                for pid, cols in self._cols.items():
                    a = np.asarray(det(weights[pid], self._stack_obs(pid)))
                    for j, agent in enumerate(cols):
                        acts[agent] = int(a[j])
                self._obs, rews, terms, truncs, _ = self._env.step(acts)
                total += sum(float(r) for r in rews.values())
                done = bool(terms.get("__all__", False)
                            or truncs.get("__all__", False))
                steps += 1
            returns.append(total)
        self._obs, _ = self._env.reset()
        self._ep_return = 0.0
        return {"episode_returns": returns,
                "mean_return": float(np.mean(returns))}


class MultiAgentPPOConfig:
    """Fluent config (reference: AlgorithmConfig.multi_agent —
    algorithm_config.py policies/policy_mapping_fn)."""

    def __init__(self):
        self.env_fn: Optional[Callable] = None
        self.num_env_runners = 2
        self.rollout_len = 32
        self.hidden = (64, 64)
        self.seed = 0
        self.ppo = PPOConfig()
        self.policies: list = []
        self.policy_mapping: Union[dict, Callable, None] = None

    def environment(self, env_fn: Callable):
        self.env_fn = env_fn
        return self

    def env_runners(self, num_env_runners: Optional[int] = None,
                    rollout_fragment_length: Optional[int] = None):
        if num_env_runners is not None:
            self.num_env_runners = num_env_runners
        if rollout_fragment_length is not None:
            self.rollout_len = rollout_fragment_length
        return self

    def training(self, **kwargs):
        import dataclasses
        self.ppo = dataclasses.replace(self.ppo, **kwargs)
        return self

    def multi_agent(self, policies: list,
                    policy_mapping=None):
        """``policies``: policy ids. ``policy_mapping``: agent_id ->
        policy_id (dict, or a picklable callable applied to each agent at
        build time). Default: every agent shares policies[0]."""
        self.policies = list(policies)
        self.policy_mapping = policy_mapping
        return self

    def build(self):
        return MultiAgentPPO(self)


class MultiAgentPPO:
    """Multi-policy PPO: one jitted PPOLearner per policy, one sample/
    update/broadcast loop (reference: the multi-agent half of
    algorithm.py training_step + learner_group keyed by module id)."""

    def __init__(self, config: MultiAgentPPOConfig):
        import ray_tpu as ray

        from ..core.usage import record_library_usage
        record_library_usage("rl")
        if config.env_fn is None:
            raise ValueError("config.environment(...) is required")
        self.config = config
        probe = config.env_fn()
        agents = list(probe.possible_agents)
        policies = config.policies or ["default_policy"]
        mapping = config.policy_mapping
        if mapping is None:
            mapping = {a: policies[0] for a in agents}
        elif callable(mapping):
            mapping = {a: mapping(a) for a in agents}
        unknown = sorted(set(mapping.values()) - set(policies))
        if unknown:
            raise ValueError(f"policy_mapping names unknown policies "
                             f"{unknown}; declared: {policies}")
        self._mapping = mapping
        # per-policy module config from the spaces of a mapped agent
        self.learners: dict[str, PPOLearner] = {}
        for i, pid in enumerate(policies):
            agent = next((a for a in agents if mapping[a] == pid), None)
            if agent is None:
                continue  # declared but unused policy
            mcfg = MLPConfig(
                obs_dim=int(np.prod(
                    probe.observation_spaces[agent].shape)),
                num_actions=int(probe.action_spaces[agent].n),
                hidden=tuple(config.hidden))
            self.learners[pid] = PPOLearner(mcfg, config.ppo,
                                            seed=config.seed + i)
        probe.close()
        Runner = ray.remote(MultiAgentEnvRunner)
        self._runners = [
            Runner.remote(config.env_fn, mapping, config.rollout_len,
                          seed=config.seed + 1000 * (i + 1))
            for i in range(config.num_env_runners)]
        self._ray = ray
        self.iteration = 0
        self._total_env_steps = 0
        self._recent_returns: list[float] = []

    def get_weights(self) -> dict:
        return {pid: lrn.get_params() if hasattr(lrn, "get_params")
                else lrn.params for pid, lrn in self.learners.items()}

    def train(self) -> dict:
        ray = self._ray
        weights_ref = ray.put(self.get_weights())
        samples = ray.get([r.sample.remote(weights_ref)
                           for r in self._runners], timeout=600)
        stats = {}
        for pid, lrn in self.learners.items():
            stats[pid] = lrn.update([s[pid] for s in samples])
        self.iteration += 1
        self._total_env_steps += (self.config.rollout_len
                                  * len(self._mapping)
                                  * len(self._runners))
        for s in samples:
            frag = next(iter(s.values()))
            self._recent_returns.extend(frag["episode_returns"])
        self._recent_returns = self._recent_returns[-100:]
        mean_ret = (float(np.mean(self._recent_returns))
                    if self._recent_returns else float("nan"))
        return {
            "training_iteration": self.iteration,
            "episode_return_mean": mean_ret,
            "num_env_steps_sampled_lifetime": self._total_env_steps,
            **{f"learner/{pid}/{k}": v
               for pid, st in stats.items() for k, v in st.items()},
        }

    def evaluate(self, num_episodes: int = 5) -> dict:
        ray = self._ray
        weights_ref = ray.put(self.get_weights())
        return ray.get(self._runners[0].evaluate.remote(
            weights_ref, num_episodes), timeout=600)

    def stop(self) -> None:
        for r in self._runners:
            try:
                self._ray.kill(r)
            except Exception:
                pass  # runner already dead
