"""Offline RL: behavior cloning + discrete conservative Q-learning over
logged ``ray_tpu.data`` datasets.

Reference parity: rllib/algorithms/bc/ (BC — marwil.py with beta=0:
plain imitation of the dataset policy) and rllib/algorithms/cql/
(CQL — TD learning plus the conservative regularizer
``alpha * (logsumexp_a Q(s,a) - Q(s, a_data))`` keeping learned values
pessimistic off-dataset; Kumar et al. 2020). The reference trains from
offline input readers (rllib/offline/); here the input is a
``ray_tpu.data.Dataset`` of transition rows — the same Data-to-RL bridge
its OfflineData loader provides.

TPU-first: the whole per-iteration update (K minibatches) is ONE jitted
``lax.scan`` over pre-sampled minibatch indices, so train() costs one
device round-trip regardless of K (same shape as dqn.py's updater).
"""
from __future__ import annotations

import dataclasses
from typing import Callable, Optional

import numpy as np

from . import module as module_lib
from .base import AlgorithmBase, AlgorithmConfigBase
from .env_runner import EnvRunner
from .module import MLPConfig


# --------------------------------------------------------------------------
# logged-transition datasets
# --------------------------------------------------------------------------

def collect_transitions(env_fn: Callable, n_steps: int,
                        policy: Optional[Callable] = None,
                        seed: int = 0):
    """Roll a (scripted or random) policy and return a
    ``ray_tpu.data.Dataset`` of transition rows {obs, action, reward,
    next_obs, done} — the offline-RL input format (reference:
    rllib/offline/ SampleBatch json episodes)."""
    from .. import data as rdata
    env = env_fn()
    rng = np.random.default_rng(seed)
    obs, _ = env.reset(seed=seed)
    rows = []
    for _ in range(n_steps):
        if policy is None:
            action = int(env.action_space.sample())
        else:
            action = int(policy(np.asarray(obs, np.float32), rng))
        nxt, rew, term, trunc, _ = env.step(action)
        rows.append({"obs": np.asarray(obs, np.float32).tolist(),
                     "action": action,
                     "reward": float(rew),
                     "next_obs": np.asarray(nxt, np.float32).tolist(),
                     "done": bool(term or trunc)})
        obs = nxt
        if term or trunc:
            obs, _ = env.reset()
    env.close()
    return rdata.from_items(rows)


def _materialize(dataset) -> dict:
    """Dataset rows -> stacked numpy arrays (offline data is bounded; the
    learner samples minibatches from host memory like DQN's replay)."""
    rows = dataset.take_all() if hasattr(dataset, "take_all") else \
        list(dataset)
    return {
        "obs": np.asarray([r["obs"] for r in rows], np.float32),
        "actions": np.asarray([r["action"] for r in rows], np.int32),
        "rewards": np.asarray([r["reward"] for r in rows], np.float32),
        "next_obs": np.asarray([r["next_obs"] for r in rows], np.float32),
        "dones": np.asarray([float(r["done"]) for r in rows], np.float32),
    }


class _OfflineAlgoBase(AlgorithmBase):
    """Shared offline scaffolding: no sampling runners drive training;
    one env runner exists only for evaluate()."""

    def _setup_offline(self, config):
        if config.dataset is None:
            raise ValueError("config.offline_data(dataset=...) is required")
        self._data = _materialize(config.dataset)
        if len(self._data["obs"]) == 0:
            raise ValueError("offline dataset is empty")
        config.num_env_runners = max(1, config.num_env_runners)
        self._setup(config, EnvRunner)
        self._np_rng = np.random.default_rng(config.seed)

    def _minibatch_indices(self, k: int, batch: int) -> np.ndarray:
        n = len(self._data["obs"])
        return self._np_rng.integers(0, n, size=(k, batch))


# --------------------------------------------------------------------------
# BC
# --------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class BCHparams:
    """(reference: bc.py BCConfig.training(...))"""
    lr: float = 1e-3
    batch_size: int = 256
    updates_per_iter: int = 64


class BC(_OfflineAlgoBase):
    """Behavior cloning: maximize log-likelihood of dataset actions
    (reference: rllib/algorithms/bc/bc.py)."""

    HPARAM_FIELD = "bc"

    def __init__(self, config: "BCConfig"):
        import jax
        import jax.numpy as jnp
        import optax

        self._setup_offline(config)
        hp = config.bc
        params = module_lib.init(jax.random.PRNGKey(config.seed),
                                 self.module_cfg)
        opt = optax.adam(hp.lr)

        data = {k: jnp.asarray(v) for k, v in self._data.items()
                if k in ("obs", "actions")}

        def loss_fn(p, idx):
            obs = data["obs"][idx]
            acts = data["actions"][idx]
            logits, _ = module_lib.logits_and_value(p, obs)
            logp = jax.nn.log_softmax(logits, axis=-1)
            nll = -jnp.take_along_axis(
                logp, acts[:, None].astype(jnp.int32), axis=-1)[:, 0]
            return nll.mean()

        def one_update(carry, idx):
            p, o = carry
            loss, grads = jax.value_and_grad(loss_fn)(p, idx)
            upd, o = opt.update(grads, o, p)
            return (optax.apply_updates(p, upd), o), loss

        @jax.jit
        def run_updates(p, o, all_idx):
            (p, o), losses = jax.lax.scan(one_update, (p, o), all_idx)
            return p, o, losses.mean()

        class _Learner:
            pass
        self.learner = _Learner()
        self.learner.params = params
        self.learner.opt_state = opt.init(params)
        self._run_updates = run_updates

    def train(self) -> dict:
        import jax.numpy as jnp
        hp = self.config.bc
        idx = jnp.asarray(self._minibatch_indices(hp.updates_per_iter,
                                                  hp.batch_size))
        p, o, loss = self._run_updates(self.learner.params,
                                       self.learner.opt_state, idx)
        self.learner.params = p
        self.learner.opt_state = o
        self.iteration += 1
        return {"training_iteration": self.iteration,
                "bc_loss": float(loss),
                "num_gradient_updates": self.iteration * hp.updates_per_iter}


class BCConfig(AlgorithmConfigBase):
    HPARAM_FIELD = "bc"
    HPARAM_FACTORY = BCHparams

    @property
    def ALGO_CLS(self):
        return BC

    def __init__(self):
        super().__init__()
        self.dataset = None
        self.num_env_runners = 1

    def offline_data(self, dataset=None):
        self.dataset = dataset
        return self


# --------------------------------------------------------------------------
# MARWIL
# --------------------------------------------------------------------------

def _discounted_returns(rewards: np.ndarray, dones: np.ndarray,
                        gamma: float) -> np.ndarray:
    """Per-step discounted return-to-go, resetting at episode ends (the
    dataset rows are in logging order; collect_transitions guarantees
    that). The final partial episode is bootstrapped with 0 — the same
    truncation the reference accepts for offline return targets."""
    g, out = 0.0, np.zeros_like(rewards)
    for i in range(len(rewards) - 1, -1, -1):
        g = rewards[i] + gamma * (1.0 - dones[i]) * g
        out[i] = g
    return out


@dataclasses.dataclass(frozen=True)
class MARWILHparams:
    """(reference: marwil.py MARWILConfig.training(...))"""
    lr: float = 1e-3
    beta: float = 1.0                  # 0 => plain BC
    gamma: float = 0.99
    vf_coeff: float = 1.0
    batch_size: int = 256
    updates_per_iter: int = 64
    # decay of the moving average of E[adv^2] normalizing the exponent
    # (reference: MARWIL's ma_adv_norm update in its loss)
    adv_norm_decay: float = 0.99


class MARWIL(_OfflineAlgoBase):
    """Monotonic advantage re-weighted imitation learning (Wang et al.
    2018): imitation weighted by ``exp(beta * advantage)`` so the clone
    prefers the dataset's better-than-average actions, plus a value head
    regression that supplies the advantages. BC is exactly beta=0
    (reference: rllib/algorithms/marwil/marwil.py — its BC subclasses
    MARWIL the same way)."""

    HPARAM_FIELD = "marwil"

    def __init__(self, config: "MARWILConfig"):
        import jax
        import jax.numpy as jnp
        import optax

        self._setup_offline(config)
        hp = config.marwil
        params = module_lib.init(jax.random.PRNGKey(config.seed),
                                 self.module_cfg)
        opt = optax.adam(hp.lr)

        returns = _discounted_returns(self._data["rewards"],
                                      self._data["dones"], hp.gamma)
        # scale-stabilize value targets (CartPole returns are O(100);
        # raw-scale MSE would drown the policy term)
        self._ret_scale = float(np.abs(returns).mean() + 1e-6)
        data = {"obs": jnp.asarray(self._data["obs"]),
                "actions": jnp.asarray(self._data["actions"]),
                "returns": jnp.asarray(returns / self._ret_scale,
                                       jnp.float32)}

        def loss_fn(p, ma_norm, idx):
            obs = data["obs"][idx]
            acts = data["actions"][idx].astype(jnp.int32)
            ret = data["returns"][idx]
            logits, value = module_lib.logits_and_value(p, obs)
            logp = jnp.take_along_axis(
                jax.nn.log_softmax(logits, axis=-1), acts[:, None],
                axis=-1)[:, 0]
            adv = jax.lax.stop_gradient(ret - value)
            ma_norm = hp.adv_norm_decay * ma_norm + \
                (1.0 - hp.adv_norm_decay) * jnp.mean(adv ** 2)
            # normalized exponent, clipped: one outlier advantage must
            # not blow the exp into inf (reference normalizes by the
            # moving RMS the same way)
            expn = jnp.clip(hp.beta * adv * jax.lax.rsqrt(ma_norm + 1e-8),
                            -20.0, 10.0)
            pol = -(jnp.exp(expn) * logp).mean()
            vf = 0.5 * ((value - ret) ** 2).mean()
            return pol + hp.vf_coeff * vf, (ma_norm, pol, vf)

        def one_update(carry, idx):
            p, o, ma = carry
            (loss, (ma, pol, vf)), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(p, ma, idx)
            upd, o = opt.update(grads, o, p)
            return (optax.apply_updates(p, upd), o, ma), (loss, pol, vf)

        @jax.jit
        def run_updates(p, o, ma, all_idx):
            (p, o, ma), (losses, pols, vfs) = jax.lax.scan(
                one_update, (p, o, ma), all_idx)
            return p, o, ma, losses.mean(), pols.mean(), vfs.mean()

        class _Learner:
            pass
        self.learner = _Learner()
        self.learner.params = params
        self.learner.opt_state = opt.init(params)
        self._ma_norm = jnp.asarray(1.0, jnp.float32)
        self._run_updates = run_updates

    def _extra_state(self) -> dict:
        return {"ma_norm": np.asarray(self._ma_norm)}

    def _load_extra_state(self, state: dict) -> None:
        import jax.numpy as jnp
        self._ma_norm = jnp.asarray(state["ma_norm"])

    def train(self) -> dict:
        import jax.numpy as jnp
        hp = self.config.marwil
        idx = jnp.asarray(self._minibatch_indices(hp.updates_per_iter,
                                                  hp.batch_size))
        p, o, ma, loss, pol, vf = self._run_updates(
            self.learner.params, self.learner.opt_state, self._ma_norm,
            idx)
        self.learner.params = p
        self.learner.opt_state = o
        self._ma_norm = ma
        self.iteration += 1
        return {"training_iteration": self.iteration,
                "marwil_loss": float(loss), "policy_loss": float(pol),
                "vf_loss": float(vf),
                "num_gradient_updates": self.iteration * hp.updates_per_iter}


class MARWILConfig(AlgorithmConfigBase):
    HPARAM_FIELD = "marwil"
    HPARAM_FACTORY = MARWILHparams

    @property
    def ALGO_CLS(self):
        return MARWIL

    def __init__(self):
        super().__init__()
        self.dataset = None
        self.num_env_runners = 1

    def offline_data(self, dataset=None):
        self.dataset = dataset
        return self


# --------------------------------------------------------------------------
# CQL (discrete)
# --------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class CQLHparams:
    """(reference: cql.py CQLConfig.training(...) — discrete reduction)"""
    lr: float = 5e-4
    gamma: float = 0.99
    batch_size: int = 256
    updates_per_iter: int = 64
    target_update_freq: int = 8        # in train() iterations
    cql_alpha: float = 1.0             # conservative penalty weight
    huber_delta: float = 1.0


class CQL(_OfflineAlgoBase):
    """Discrete CQL: double-DQN TD loss on dataset transitions plus the
    conservative term alpha * (logsumexp_a Q(s,a) - Q(s, a_data))
    (reference: rllib/algorithms/cql/cql.py)."""

    HPARAM_FIELD = "cql"

    def __init__(self, config: "CQLConfig"):
        import jax
        import jax.numpy as jnp
        import optax

        self._setup_offline(config)
        hp = config.cql
        params = module_lib.init(jax.random.PRNGKey(config.seed),
                                 self.module_cfg)
        opt = optax.adam(hp.lr)
        data = {k: jnp.asarray(v) for k, v in self._data.items()}

        def q_of(p, obs):
            # the module's "pi" head doubles as the Q head (same shape:
            # one scalar per discrete action)
            logits, _ = module_lib.logits_and_value(p, obs)
            return logits

        def loss_fn(p, target_p, idx):
            obs = data["obs"][idx]
            acts = data["actions"][idx].astype(jnp.int32)
            rew = data["rewards"][idx]
            nxt = data["next_obs"][idx]
            done = data["dones"][idx]
            q = q_of(p, obs)
            q_a = jnp.take_along_axis(q, acts[:, None], axis=-1)[:, 0]
            # double-Q target: online argmax, target net value
            next_online = q_of(p, nxt)
            next_target = q_of(target_p, nxt)
            a_star = jnp.argmax(next_online, axis=-1)
            q_next = jnp.take_along_axis(
                next_target, a_star[:, None], axis=-1)[:, 0]
            target = rew + hp.gamma * (1.0 - done) * \
                jax.lax.stop_gradient(q_next)
            td = q_a - target
            huber = jnp.where(
                jnp.abs(td) <= hp.huber_delta, 0.5 * td ** 2,
                hp.huber_delta * (jnp.abs(td) - 0.5 * hp.huber_delta))
            # conservative regularizer: push down unseen actions' values
            cql = jax.scipy.special.logsumexp(q, axis=-1) - q_a
            return huber.mean() + hp.cql_alpha * cql.mean(), (
                huber.mean(), cql.mean())

        def one_update(carry, idx):
            p, o, tp = carry
            (loss, (td, cql)), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(p, tp, idx)
            upd, o = opt.update(grads, o, p)
            return (optax.apply_updates(p, upd), o, tp), (loss, td, cql)

        @jax.jit
        def run_updates(p, o, tp, all_idx):
            (p, o, tp), (losses, tds, cqls) = jax.lax.scan(
                one_update, (p, o, tp), all_idx)
            return p, o, losses.mean(), tds.mean(), cqls.mean()

        class _Learner:
            pass
        self.learner = _Learner()
        self.learner.params = params
        self.learner.opt_state = opt.init(params)
        self._target_params = params
        self._run_updates = run_updates

    def _extra_state(self) -> dict:
        return {"target_params": self._target_params}

    def _load_extra_state(self, state: dict) -> None:
        import jax
        import jax.numpy as jnp
        self._target_params = jax.tree.map(jnp.asarray,
                                           state["target_params"])

    def train(self) -> dict:
        import jax.numpy as jnp
        hp = self.config.cql
        idx = jnp.asarray(self._minibatch_indices(hp.updates_per_iter,
                                                  hp.batch_size))
        p, o, loss, td, cql = self._run_updates(
            self.learner.params, self.learner.opt_state,
            self._target_params, idx)
        self.learner.params = p
        self.learner.opt_state = o
        self.iteration += 1
        if self.iteration % hp.target_update_freq == 0:
            self._target_params = self.learner.params
        return {"training_iteration": self.iteration,
                "cql_loss": float(loss), "td_loss": float(td),
                "cql_gap": float(cql),
                "num_gradient_updates": self.iteration * hp.updates_per_iter}


class CQLConfig(AlgorithmConfigBase):
    HPARAM_FIELD = "cql"
    HPARAM_FACTORY = CQLHparams

    @property
    def ALGO_CLS(self):
        return CQL

    def __init__(self):
        super().__init__()
        self.dataset = None
        self.num_env_runners = 1

    def offline_data(self, dataset=None):
        self.dataset = dataset
        return self
