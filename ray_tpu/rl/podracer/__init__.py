"""ray_tpu.rl.podracer — Podracer rollout substrate (Sebulba + Anakin).

Reference: "Podracer architectures for scalable Reinforcement Learning"
(PAPERS.md). Two architectures over the repo's actor/channel substrate:

- **Sebulba** (sebulba.py): N vectorized env-runner actors stream
  time-major rollout fragments into a multi-producer RolloutQueue built
  on sealed ring channels (queue.py over dag/channel.MultiRingReader) —
  zero control-plane dispatches per fragment in steady state; V-trace
  corrects the behaviour-policy lag; weights broadcast runner-ward via
  one objstore put per iteration.
- **Anakin** (anakin.py): env step + update fused into ONE jitted
  shard_map program over the mesh, for jittable envs (jax_env.py).

``PodracerTrainer`` (trainer.py) drives either with CheckpointManager
save/resume; telemetry.py's ``rtpu_rl_*`` series feed
``metrics_summary()``.

Lazy exports (PEP 562): importing this package must not pay for jax /
gymnasium / optax — workers and the GL005 import-hygiene gate rely on
``import ray_tpu`` (and cheap ``ray_tpu.rl`` subimports) staying light.
"""
import importlib

_EXPORTS = {
    "RolloutQueue": "queue", "RolloutQueueSpec": "queue",
    "RolloutProducer": "queue", "ChannelClosed": "queue",
    "SebulbaConfig": "sebulba", "SebulbaTrainer": "sebulba",
    "SebulbaEnvRunner": "sebulba", "WeightBroadcast": "sebulba",
    "WeightSubscriber": "sebulba",
    "AnakinConfig": "anakin", "AnakinTrainer": "anakin",
    "JaxCartPole": "jax_env",
    "PodracerTrainer": "trainer",
    "ReplayIngestor": "replay", "ReplayIngestConfig": "replay",
    "train_dqn_offline": "replay",
    "metrics_summary": "telemetry",
}
_MODULES = ("queue", "sebulba", "anakin", "jax_env", "telemetry",
            "trainer", "replay")

__all__ = list(_EXPORTS) + list(_MODULES)


def __getattr__(name):
    if name in _EXPORTS:
        mod = importlib.import_module(f".{_EXPORTS[name]}", __name__)
        return getattr(mod, name)
    if name in _MODULES:
        return importlib.import_module(f".{name}", __name__)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
