"""Anakin: env step + learner update fused into ONE jitted program.

Reference: "Podracer architectures for scalable Reinforcement Learning"
(PAPERS.md) §2 — when the environment itself is jittable (jax_env.py
protocol), the fastest architecture keeps EVERYTHING on the accelerator:
each mesh slice steps a batch of envs, unrolls a rollout with lax.scan,
computes the V-trace actor-critic update and applies pmean'd gradients,
all inside one XLA program per iteration. Zero hosts in the loop, zero
object-store traffic, zero dispatches — the control plane only launches
the compiled computation.

Built over ``ray_tpu.parallel`` shard_map (the repo's mesh substrate):
env state/obs shard over the ``dp`` axis, params/optimizer state stay
replicated (gradients are ``lax.pmean``'d across ``dp``, so every device
applies the identical update — the pmap idiom, expressed over the mesh).
On-policy V-trace degenerates to n-step actor-critic (importance ratios
are 1), so Sebulba and Anakin share one loss implementation
(rl/impala.py vtrace)."""
from __future__ import annotations

import dataclasses
import time
from typing import Any

import numpy as np

from ..impala import ImpalaConfig
from . import telemetry as tm
from .jax_env import JaxCartPole


@dataclasses.dataclass
class AnakinConfig:
    """Anakin knobs. ``env`` must follow the jax_env.py protocol
    (pure reset/step, auto-reset on done)."""

    env: Any = dataclasses.field(default_factory=JaxCartPole)
    batch_per_device: int = 32    # vectorized envs per mesh slice
    rollout_len: int = 16
    hidden: tuple = (64, 64)
    seed: int = 0
    impala: ImpalaConfig = dataclasses.field(default_factory=ImpalaConfig)
    mesh: Any = None              # jax Mesh with a dp axis; None = all
    #                               devices on dp (build_mesh(dp=-1))


class AnakinTrainer:
    """The fused trainer: ``train()`` = one jitted shard_map call."""

    def __init__(self, config: AnakinConfig):
        import jax
        import optax
        from ...core.usage import record_library_usage
        from ...parallel import MeshSpec, build_mesh
        from .. import module as module_lib
        record_library_usage("rl.podracer")
        self.config = config
        self.env = config.env
        self.mesh = config.mesh if config.mesh is not None \
            else build_mesh(MeshSpec(dp=-1, keep_unit_axes=False))
        if "dp" not in self.mesh.axis_names:
            raise ValueError("Anakin needs a mesh with a 'dp' axis")
        sizes = dict(zip(self.mesh.axis_names, self.mesh.devices.shape))
        if int(np.prod(self.mesh.devices.shape)) != sizes["dp"]:
            raise ValueError(
                "Anakin shards envs over dp only; other mesh axes must "
                f"be size 1, got {sizes}")
        self._num_devices = sizes["dp"]
        self.module_cfg = module_lib.MLPConfig(
            obs_dim=self.env.obs_dim, num_actions=self.env.num_actions,
            hidden=tuple(config.hidden))
        key = jax.random.PRNGKey(config.seed)
        key, pkey = jax.random.split(key)
        self.params = module_lib.init(pkey, self.module_cfg)
        cfg = config.impala
        self.optimizer = optax.chain(
            optax.clip_by_global_norm(cfg.grad_clip),
            optax.rmsprop(cfg.lr, decay=0.99, eps=0.1))
        self.opt_state = self.optimizer.init(self.params)
        self._init_env_state(key)
        self._run = self._build_run()
        self.iteration = 0
        self._total_env_steps = 0
        # trailing (return_sum, episode_count) pairs for the mean window
        self._ret_window: list[tuple[float, float]] = []

    def _init_env_state(self, key) -> None:
        import jax
        n = self._num_devices * self.config.batch_per_device
        key, ekey, *dkeys = jax.random.split(key, 2 + self._num_devices)
        self._env_state, self._obs = jax.vmap(self.env.reset)(
            jax.random.split(ekey, n))
        self._keys = jax.numpy.stack(dkeys)        # [D, 2] one per device
        import jax.numpy as jnp
        self._ep_ret = jnp.zeros((n,), jnp.float32)

    def _build_run(self):
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P
        from ...parallel._compat import shard_map
        from .. import module as module_lib
        from ..impala import vtrace
        env, cfg = self.env, self.config.impala
        T = self.config.rollout_len
        optimizer = self.optimizer

        def device_fn(params, opt_state, env_state, obs, key, ep_ret):
            key = key[0]     # [1, 2] shard of the per-device key stack

            def step_fn(carry, _):
                env_state, obs, key, ep_ret, csum, cnt = carry
                key, sub = jax.random.split(key)
                action, logp, value = module_lib.sample_action(
                    params, obs, sub)
                env_state, next_obs, reward, done = jax.vmap(env.step)(
                    env_state, action)
                ep_ret = ep_ret + reward
                csum = csum + jnp.sum(jnp.where(done, ep_ret, 0.0))
                cnt = cnt + jnp.sum(done.astype(jnp.float32))
                ep_ret = jnp.where(done, 0.0, ep_ret)
                carry = (env_state, next_obs, key, ep_ret, csum, cnt)
                return carry, (obs, action, logp, value, reward, done)

            (env_state, obs, key, ep_ret, csum, cnt), traj = jax.lax.scan(
                step_fn,
                (env_state, obs, key, ep_ret,
                 jnp.zeros(()), jnp.zeros(())),
                None, length=T)
            t_obs, t_act, t_logp, _t_val, t_rew, t_done = traj
            bootstrap = module_lib.logits_and_value(params, obs)[1]

            def loss_fn(p):
                logits, values = module_lib.logits_and_value(p, t_obs)
                logp_all = jax.nn.log_softmax(logits, axis=-1)
                target_logp = jnp.take_along_axis(
                    logp_all, t_act[..., None], axis=-1)[..., 0]
                # on-policy: behaviour == target, so the V-trace ratios
                # are 1 and this is n-step actor-critic — one loss shared
                # with the Sebulba/IMPALA learner
                vs, pg_adv = vtrace(
                    jax.lax.stop_gradient(t_logp), target_logp, t_rew,
                    values, t_done.astype(jnp.float32), bootstrap,
                    cfg.gamma, cfg.rho_bar, cfg.c_bar)
                pg_loss = -jnp.mean(target_logp * pg_adv)
                vf_loss = 0.5 * jnp.mean((vs - values) ** 2)
                entropy = -jnp.mean(
                    jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1))
                total = (pg_loss + cfg.vf_coeff * vf_loss
                         - cfg.entropy_coeff * entropy)
                return total, (pg_loss, vf_loss, entropy)

            (loss, (pg, vf, ent)), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            grads = jax.lax.pmean(grads, "dp")
            updates, opt_state = optimizer.update(grads, opt_state,
                                                  params)
            import optax as _optax
            params = _optax.apply_updates(params, updates)
            metrics = {
                "loss": jax.lax.pmean(loss, "dp"),
                "pg_loss": jax.lax.pmean(pg, "dp"),
                "vf_loss": jax.lax.pmean(vf, "dp"),
                "entropy": jax.lax.pmean(ent, "dp"),
                "return_sum": jax.lax.psum(csum, "dp"),
                "episodes": jax.lax.psum(cnt, "dp"),
            }
            return (params, opt_state, env_state, obs, key[None],
                    ep_ret, metrics)

        fn = shard_map(
            device_fn, mesh=self.mesh,
            in_specs=(P(), P(), P("dp"), P("dp"), P("dp"), P("dp")),
            out_specs=(P(), P(), P("dp"), P("dp"), P("dp"), P("dp"),
                       P()),
            check_vma=False)
        return jax.jit(fn)

    def train(self) -> dict:
        """One iteration = one compiled program: rollout_len fused
        env-step/sample steps on every device, one pmean'd update."""
        t0 = time.perf_counter()
        (self.params, self.opt_state, self._env_state, self._obs,
         self._keys, self._ep_ret, metrics) = self._run(
            self.params, self.opt_state, self._env_state, self._obs,
            self._keys, self._ep_ret)
        metrics = {k: float(v) for k, v in metrics.items()}
        dt = time.perf_counter() - t0
        steps = (self._num_devices * self.config.batch_per_device
                 * self.config.rollout_len)
        self._total_env_steps += steps
        self.iteration += 1
        self._ret_window.append(
            (metrics.pop("return_sum"), metrics.pop("episodes")))
        self._ret_window = self._ret_window[-20:]
        ret_sum = sum(s for s, _ in self._ret_window)
        ret_n = sum(n for _, n in self._ret_window)
        try:
            tm.env_steps().inc(float(steps), tags={"arch": "anakin"})
            tm.learner_update().observe(dt, tags={"arch": "anakin"})
        except Exception:
            pass  # telemetry must never fail training
        return {
            "training_iteration": self.iteration,
            "episode_return_mean": (ret_sum / ret_n if ret_n
                                    else float("nan")),
            "num_env_steps_sampled_lifetime": self._total_env_steps,
            "env_steps_per_sec": steps / max(dt, 1e-9),
            "num_devices": self._num_devices,
            **{f"learner/{k}": v for k, v in metrics.items()},
        }

    def flops_estimate(self):
        """FLOPs of one fused iteration via XLA cost_analysis on the
        compiled program (one extra out-of-band compile; the MFU input
        for PodracerTrainer(profile=True) and the ROADMAP TPU goal)."""
        from ...util.profiling import compiled_flops
        return compiled_flops(self._run, self.params, self.opt_state,
                              self._env_state, self._obs, self._keys,
                              self._ep_ret)

    # -- checkpoint ------------------------------------------------------ #

    def save_state(self) -> dict:
        import jax
        return {"params": jax.device_get(self.params),
                "opt_state": jax.device_get(self.opt_state),
                "iteration": self.iteration,
                "total_env_steps": self._total_env_steps}

    def restore_state(self, state: dict) -> None:
        import jax
        import jax.numpy as jnp
        self.params = jax.tree.map(jnp.asarray, state["params"])
        self.opt_state = jax.tree.map(jnp.asarray, state["opt_state"])
        self.iteration = int(state["iteration"])
        self._total_env_steps = int(state["total_env_steps"])

    def stop(self) -> None:
        pass  # no actors, no channels: nothing to tear down
