"""Jittable environments for the Anakin architecture.

Anakin (PAPERS.md: "Podracer architectures for scalable Reinforcement
Learning" §2) fuses env step + learner update into ONE jitted program,
which requires the environment itself to be a pure JAX function. The
protocol (duck-typed, no base class needed):

    env.obs_dim     : int          flat observation size
    env.num_actions : int          discrete action count
    env.reset(key)  -> (state, obs)
    env.step(state, action) -> (state, obs, reward, done)

``state`` is a pytree carrying EVERYTHING mutable (physics, step count,
PRNG key); both methods must be traceable (vmap/scan/jit-safe) and
``step`` must AUTO-RESET when the episode ends — a terminated env in a
vectorized batch immediately restarts, so the batch never blocks on
episode boundaries (the Anakin convention; the returned ``done`` flag
still marks the boundary for bootstrapping).

``JaxCartPole`` is the reference implementation: the classic-control
cart-pole (Barto, Sutton & Anderson 1983) with gymnasium's CartPole-v1
constants, Euler integration and the 500-step truncation — so Anakin
convergence numbers compare directly against the gym-based trainers.
"""
from __future__ import annotations

import dataclasses


@dataclasses.dataclass(frozen=True)
class JaxCartPole:
    """Pure-JAX CartPole-v1 (gymnasium-equivalent dynamics/limits)."""

    gravity: float = 9.8
    masscart: float = 1.0
    masspole: float = 0.1
    length: float = 0.5          # half the pole's length
    force_mag: float = 10.0
    tau: float = 0.02            # integration step, seconds
    x_threshold: float = 2.4
    theta_threshold: float = 0.20943951023931953   # 12 degrees
    max_steps: int = 500         # CartPole-v1 truncation

    obs_dim: int = 4
    num_actions: int = 2

    def _spawn(self, key):
        import jax
        return jax.random.uniform(key, (4,), minval=-0.05, maxval=0.05)

    def reset(self, key):
        import jax
        key, sub = jax.random.split(key)
        phys = self._spawn(sub)
        import jax.numpy as jnp
        state = {"phys": phys, "t": jnp.zeros((), jnp.int32), "key": key}
        return state, phys

    def step(self, state, action):
        import jax
        import jax.numpy as jnp
        x, x_dot, theta, theta_dot = state["phys"]
        force = jnp.where(action == 1, self.force_mag, -self.force_mag)
        costheta, sintheta = jnp.cos(theta), jnp.sin(theta)
        total_mass = self.masscart + self.masspole
        polemass_length = self.masspole * self.length
        temp = (force + polemass_length * theta_dot ** 2 * sintheta) \
            / total_mass
        thetaacc = (self.gravity * sintheta - costheta * temp) / (
            self.length * (4.0 / 3.0
                           - self.masspole * costheta ** 2 / total_mass))
        xacc = temp - polemass_length * thetaacc * costheta / total_mass
        phys = jnp.stack([x + self.tau * x_dot,
                          x_dot + self.tau * xacc,
                          theta + self.tau * theta_dot,
                          theta_dot + self.tau * thetaacc])
        t = state["t"] + 1
        terminated = (jnp.abs(phys[0]) > self.x_threshold) \
            | (jnp.abs(phys[2]) > self.theta_threshold)
        done = terminated | (t >= self.max_steps)
        # auto-reset: the batch never blocks on an episode boundary
        key, sub = jax.random.split(state["key"])
        fresh = self._spawn(sub)
        phys = jnp.where(done, fresh, phys)
        t = jnp.where(done, 0, t)
        new_state = {"phys": phys, "t": t, "key": key}
        return new_state, phys, jnp.float32(1.0), done
