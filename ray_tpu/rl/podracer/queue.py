"""RolloutQueue: multi-producer fragment transport on sealed ring channels.

The Sebulba data plane (PAPERS.md: "Podracer architectures for scalable
Reinforcement Learning" §3 — actor/learner split with rollout fragments
streaming from many env-runner actors into the learner). Built on
dag/channel.py's sealed-channel protocol + the os_wait_sealed multi-oid
primitive (PR 3/5 machinery):

- Each producer owns its own (data, ack) id-base pair; message ``seq``
  seals at ``base[:12] + uint32(seq)`` — ids are never reused, so
  zero-copy reads stay safe and nothing is delete-and-recreated.
- The consumer parks in ONE futex wait spanning every producer's
  next-expected slot plus the shared stop flag
  (``dag.channel.MultiRingReader``) and services whichever seals first:
  **zero control-plane dispatches per fragment** in steady state — the
  only actor calls are the one loop-start per producer and teardown.
- **Backpressure is credit-based per producer**: a producer writing
  ``seq`` first waits on its own ``ack[seq - ring]``, so a slow learner
  throttles sampling to the ring window instead of flooding the store,
  and one stalled producer never steals another's credits.
- Teardown seals the stop flag: every parked producer write and the
  consumer wait wake instantly and sweep their slot/ack windows, so a
  closed queue leaves the store at its pre-queue object count.

Producers on own-store nodes cannot share the consumer's shm store; the
producer constructor raises there so callers fall back to the actor-call
transport (SebulbaConfig.transport="actor" — also the bench A/B).
"""
from __future__ import annotations

import dataclasses
import os
import time
from typing import Any, Optional

from ...core import flight as _fl
from ...core.ids import ObjectID
from ...dag.channel import (ChannelClosed, MultiRingReader, RingWriter,
                            drain_stale_slots)
from . import telemetry as tm

__all__ = ["RolloutQueueSpec", "RolloutQueue", "RolloutProducer",
           "ChannelClosed"]


@dataclasses.dataclass(frozen=True)
class RolloutQueueSpec:
    """Picklable wiring for one queue: ships to producer actors as a
    plain value (the id bases ARE the channel — no handles to plumb)."""

    bases: tuple  # one data id-base per producer
    stop: bytes   # shared stop-flag oid bytes
    ring: int     # per-producer credit window (in-flight fragments)

    @classmethod
    def create(cls, num_producers: int, ring: int = 2) -> "RolloutQueueSpec":
        if num_producers < 1:
            raise ValueError("need at least one producer")
        return cls(bases=tuple(os.urandom(16) for _ in range(num_producers)),
                   stop=os.urandom(16), ring=max(1, ring))

    def stop_oid(self) -> ObjectID:
        return ObjectID(self.stop[:ObjectID.SIZE])


def _local_store():
    from ...core import runtime as rt_mod
    rt = rt_mod.get_runtime_if_exists()
    store = getattr(rt, "store", None)
    if store is None:
        raise RuntimeError(
            "rollout queue needs a running shm object store "
            "(ray_tpu.init(); local_mode has none)")
    return store


class RolloutQueue:
    """Consumer end (learner side). ``get()`` blocks in one futex wait
    across all producers and returns ``(producer_index, fragment)``."""

    def __init__(self, spec: RolloutQueueSpec, store=None):
        self.spec = spec
        self.store = store if store is not None else _local_store()
        self._reader = MultiRingReader(self.store, list(spec.bases),
                                       spec.stop_oid(), spec.ring)
        self._closed = False

    def get(self, timeout_s: Optional[float] = None,
            on_idle=None) -> tuple[int, Any]:
        """Next fragment from ANY producer (round-robin-fair among the
        ready ones). Raises ChannelClosed after close(), GetTimeoutError
        past the deadline; ``on_idle`` runs between wait slices — the
        trainer's producer-liveness probe hooks in there so a dead
        env-runner actor raises promptly instead of hanging the learner."""
        t0 = time.perf_counter()
        idx, val = self._reader.read_any(timeout_s, on_idle)
        _fl.evt(_fl.FRAG_GET, idx)
        try:
            tm.fragment_wait().observe(time.perf_counter() - t0,
                                       tags={"transport": "chan"})
            tm.fragments().inc(1.0, tags={"transport": "chan"})
        except Exception:
            pass  # telemetry must never fail the data plane
        return idx, val

    def depth(self) -> int:
        """Sealed-but-unread fragments across producers (bounded probe:
        ring slots per producer); also feeds the queue-depth gauge."""
        d = self._reader.depth()
        try:
            tm.queue_depth().set(float(d))
        except Exception:
            pass  # telemetry must never fail the data plane
        return d

    def close(self) -> None:
        """Seal the stop flag and sweep the consumer-side windows. Every
        producer parked in a credit wait (and any in-flight ``get``)
        wakes with ChannelClosed. Idempotent — a second call re-sweeps
        the windows, which teardown uses to catch slots a straggling
        producer sealed after the first sweep. Call ``release()`` only
        once no producer can still be running (joined or force-killed)
        to drop the stop object itself."""
        self._closed = True
        self._reader.close()

    def release(self) -> None:
        """Drop the stop flag object once every producer has observed it
        (deleting it earlier would strand a producer's closed() probe)."""
        try:
            self.store.delete(self.spec.stop_oid())
        except Exception:
            pass  # store closing: the flag dies with it


class RolloutProducer:
    """Producer end, constructed INSIDE an env-runner actor from the
    picklable spec. ``write()`` seals one fragment and blocks on the
    producer's own credit window when the learner lags."""

    def __init__(self, spec: RolloutQueueSpec, index: int, store=None):
        if os.environ.get("RTPU_OWN_STORE") == "1":
            raise RuntimeError(
                "sealed-channel rollout transport needs a store shared "
                "with the learner; this runner sits on an own-store node "
                "— use SebulbaConfig(transport='actor')")
        self.spec = spec
        self.index = index
        store = store if store is not None else _local_store()
        self._writer = RingWriter(store, spec.bases[index],
                                  spec.stop_oid(), spec.ring)
        self._store = store

    def write(self, fragment: Any,
              timeout_s: Optional[float] = None) -> None:
        """Seal the next fragment (raises ChannelClosed on teardown)."""
        _fl.evt(_fl.FRAG_PUT, self.index, self._writer.seq)
        self._writer.write(fragment, timeout_s)

    def closed(self) -> bool:
        return self._writer.closed()

    def sweep(self) -> None:
        """Producer-exit cleanup: when the queue was torn down (stop
        sealed), delete this producer's unconsumed slots and trailing
        acks so nothing outlives the loop."""
        w = self._writer
        if self._store.contains(w.stop):
            drain_stale_slots(self._store, [w.base, w.ack_base],
                              w.seq - self.spec.ring - 1,
                              w.seq + self.spec.ring)
