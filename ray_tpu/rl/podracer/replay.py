"""data.streaming → ReplayBuffer ingestion: offline transitions feed
podracer DQN/SAC.

The missing half of the Podracer data plane (the PR-6 remainder):
Sebulba streams FRESH rollouts through sealed channels; this adapter
streams STORED transitions — offline RL corpora, logged production
trajectories, d4rl-style datasets — through the same substrate. A
``Dataset`` of transition rows rides the streaming executor
(data/streaming: stage actors on sealed rings, ~zero control dispatches
per block, credit-bounded memory) straight into a ``ReplayBuffer``,
so replay ingestion at dataset scale costs a handful of actor calls
total instead of one per block, and a learner can start sampling while
ingestion is still streaming the tail.

Works with both buffer families: ``rl.ReplayBuffer`` (discrete actions
— DQN) and ``rl.sac.ContinuousReplayBuffer`` (action vectors — SAC)
share ``add_batch``.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Optional

import numpy as np


@dataclasses.dataclass
class ReplayIngestConfig:
    """Column mapping from transition rows to ReplayBuffer.add_batch."""

    obs_column: str = "obs"
    action_column: str = "action"
    reward_column: str = "reward"
    next_obs_column: str = "next_obs"
    done_column: str = "done"
    gamma_column: Optional[str] = None   # per-row effective discount
    batch_size: int = 1024               # rows per add_batch call


class ReplayIngestor:
    """Streams a Dataset of transitions into a ReplayBuffer.

    ``ingest()`` consumes ``ds.iter_batches`` — under the default
    streaming executor that is a channel pipeline (read/decode stages
    stream shm-to-shm into this process), under the task executor a
    bounded-window task stream; either way the buffer fills in
    plan order, batch by batch."""

    def __init__(self, buffer: Any,
                 config: Optional[ReplayIngestConfig] = None):
        self.buffer = buffer
        self.config = config or ReplayIngestConfig()

    def ingest(self, ds, limit: Optional[int] = None) -> int:
        """Feed transitions from ``ds`` into the buffer; returns rows
        ingested. ``limit`` stops early (tears the stream down cleanly —
        the pipeline sweeps itself, the PR 5/6 contract)."""
        cfg = self.config
        total = 0
        it = ds.iter_batches(batch_size=cfg.batch_size,
                             batch_format="numpy")
        for batch in it:
            obs = np.asarray(batch[cfg.obs_column], np.float32)
            nxt = np.asarray(batch[cfg.next_obs_column], np.float32)
            act = np.asarray(batch[cfg.action_column])
            rew = np.asarray(batch[cfg.reward_column], np.float32)
            done = np.asarray(batch[cfg.done_column], np.float32)
            gam = None
            if cfg.gamma_column is not None:
                gam = np.asarray(batch[cfg.gamma_column], np.float32)
            if limit is not None and total + len(act) > limit:
                take = limit - total
                obs, nxt, act = obs[:take], nxt[:take], act[:take]
                rew, done = rew[:take], done[:take]
                gam = gam[:take] if gam is not None else None
            self.buffer.add_batch(obs, act, rew, nxt, done, gammas=gam)
            total += len(act)
            try:
                from . import telemetry as tm
                tm.replay_ingested().inc(float(len(act)))
            except Exception:
                pass  # telemetry must never fail the data plane
            if limit is not None and total >= limit:
                it.close()   # generator close -> pipeline teardown
                break
        return total


def train_dqn_offline(ds, *, obs_dim: int, num_actions: int,
                      dqn_config=None, ingest: Optional[
                          ReplayIngestConfig] = None,
                      iterations: int = 10, hidden: tuple = (64, 64),
                      seed: int = 0) -> dict:
    """Offline DQN on a transition Dataset: stream the dataset into a
    ReplayBuffer via the streaming executor, then run ``iterations``
    learner updates (no environment in the loop — the offline-RL shape).
    Returns the last update's stats plus ingestion counts."""
    from ..dqn import DQNConfig, DQNLearner, ReplayBuffer
    from ..module import MLPConfig
    cfg = dqn_config or DQNConfig()
    icfg = ingest or ReplayIngestConfig()
    buf = ReplayBuffer(cfg.buffer_size, obs_dim, gamma=cfg.gamma)
    n = ReplayIngestor(buf, icfg).ingest(ds)
    if n == 0:
        raise ValueError("empty transition dataset")
    learner = DQNLearner(
        MLPConfig(obs_dim=obs_dim, num_actions=num_actions,
                  hidden=tuple(hidden)), cfg, seed=seed)
    rng = np.random.default_rng(seed)
    stats: dict = {}
    for _ in range(max(1, iterations)):
        stats = learner.update_from_buffer(buf, rng)
    return {"transitions_ingested": n, "buffer_size": buf.size,
            "iterations": max(1, iterations), **stats}
