"""Sebulba: decoupled actor/learner RL on the sealed-channel substrate.

Reference: "Podracer architectures for scalable Reinforcement Learning"
(PAPERS.md) §3 — the Sebulba split: N vectorized env-runner actors
sample rollout fragments continuously while the learner consumes them
and trains; behaviour policies lag the learner by design, and V-trace
(rl/impala.py, Espeholt et al. 2018) corrects the off-policy gap.

Delta from rl/impala.py's driver (and why this subsystem exists): IMPALA
still pays one blocking actor call per fragment — exactly the per-call
control-plane cost PRs 3/5 built the machinery to eliminate. Here each
runner executes ONE long-lived ``run_loop`` actor call for the whole
training run and streams fragments through a RolloutQueue (sealed ring
channels + one os_wait_sealed futex wait on the learner side): **zero
control dispatches per fragment in steady state**, counter-verified by
rtpu_rl_{dispatches,fragments}_total the same way bench_serve.py
--decode-plan verifies the static decode plan.

Weights flow runner-ward through ONE objstore put per publication: the
learner seals version ``v`` at a fixed id-base + uint32(v) slot (ids
never reused — the channel invariant); every runner probes forward with
a non-blocking wait_sealed between fragments and fetches only the
newest, tagging fragments with the version it sampled under (the
staleness histogram + V-trace's correction input).
"""
from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Optional

import numpy as np

from ...core import flight as _fl
from ..env_runner import EnvRunner
from ..impala import ImpalaConfig, ImpalaLearner
from ..module import MLPConfig
from .queue import (ChannelClosed, RolloutProducer, RolloutQueue,
                    RolloutQueueSpec)
from . import telemetry as tm


def _slot(base: bytes, seq: int):
    # the weight channel uses the SAME slot-id layout as the data
    # channels (one id scheme per store, defined once in dag/channel.py)
    from ...dag.channel import slot_oid
    return slot_oid(base, seq)


# --------------------------------------------------------------------- #
# weight broadcast: one objstore put per published version
# --------------------------------------------------------------------- #

def _boot_oid(base: bytes):
    """1-byte beacon sealed alongside version 0: the subscriber's
    bootstrap anchor. Slot 0 itself is reclaimed by the keep-window
    delete, so a runner that starts >= keep publications late must have
    something PERMANENT to wake on before it can tile-scan for the live
    window (and the scan itself may only run once a version is known to
    exist, or it would hop forever on an unpublished channel)."""
    import hashlib
    from ...core.ids import ObjectID
    return ObjectID(hashlib.sha1(base + b"/boot").digest()[:16])


class WeightBroadcast:
    """Learner end of the weight path. ``publish()`` is ONE store put of
    ``(version, publish_ts, params)`` under the version's slot id;
    versions older than the keep window are deleted (lazily if a
    runner's zero-copy view still pins one — ids are never reused, so a
    lazy delete is harmless, the channel invariant)."""

    def __init__(self, store, base: Optional[bytes] = None, keep: int = 8):
        import os
        self.store = store
        self.base = base if base is not None else os.urandom(16)
        # keep >= 2: a runner that just observed version v sealed must
        # still be able to get() it after the learner publishes v+1
        self.keep = max(2, keep)
        self.version = -1

    def publish(self, params: Any) -> int:
        v = self.version + 1
        _fl.evt(_fl.WEIGHT_PUB, v)
        self.store.put(_slot(self.base, v), (v, time.time(), params))
        if v == 0:
            try:
                self.store.put(_boot_oid(self.base), True)
            except FileExistsError:
                pass  # republish after restore on a reused base
        self.version = v
        if v >= self.keep:
            try:
                self.store.delete(_slot(self.base, v - self.keep))
            except Exception:
                pass  # already gone (store pressure eviction)
        try:
            tm.weight_broadcasts().inc(1.0)
        except Exception:
            pass  # telemetry must never fail the data plane
        return v

    def sweep(self) -> None:
        """Teardown: drop the trailing keep-window of versions."""
        try:
            self.store.delete(_boot_oid(self.base))
        except Exception:
            return  # store closing; slots die with it
        for v in range(max(0, self.version - self.keep),
                       self.version + 1):
            try:
                self.store.delete(_slot(self.base, v))
            except Exception:
                return  # store closing; slots die with it


class WeightSubscriber:
    """Runner end: tracks the newest published version with non-blocking
    wait_sealed probes (a couple of native calls per fragment, zero
    control dispatches). ``current()`` blocks only for version 0 —
    stop-aware, so teardown before the first publish can't hang a
    runner."""

    # versions probed per bulk wait_sealed while scanning forward; a
    # tuning knob only — blocks tile contiguously, so the scan lands in
    # the publisher's live keep-window whatever either side's size is
    _SCAN_BLOCK = 8

    def __init__(self, store, base: bytes, stop_oid):
        self.store = store
        self.base = base
        self.stop = stop_oid
        self.version = -1
        self._params = None
        self._ts = 0.0

    def _newest_sealed(self) -> int:
        """Newest version observable now (>= self.version): scan forward
        in contiguous _SCAN_BLOCK-sized tiles, one non-blocking
        wait_sealed each. A subscriber that lagged past the publisher's
        keep window sees only deleted slots nearby — tiling hops over
        the gap until it lands in the live window (the publisher always
        keeps its newest versions sealed, so the scan terminates)."""
        W = self._SCAN_BLOCK
        newest = self.version
        v = max(0, self.version + 1)
        while True:
            idxs = self.store.wait_sealed_indices(
                [_slot(self.base, u) for u in range(v, v + W)], 0, 0)
            if idxs:
                newest = v + idxs[-1]
                v = newest + 1
                continue
            if newest > self.version:
                return newest       # scanned past the window's end
            if self.version >= 0 and self.store.contains(
                    _slot(self.base, self.version)):
                return newest       # current still live: nothing newer
            if self.store.contains(self.stop):
                # teardown swept the slots while we scanned: the "a
                # newer version is always sealed" termination argument
                # no longer holds, so exit instead of hot-spinning
                raise ChannelClosed("queue stopped during weight scan")
            v += W                  # reclaimed under us: window is ahead

    def _fetch(self, v: int) -> bool:
        from ...core.object_store import GetTimeoutError
        try:
            got = self.store.get(_slot(self.base, v), timeout_ms=5000)
        except GetTimeoutError:
            return False  # deleted under us (we lagged past the keep
            # window); the caller advances to a newer version
        if not (isinstance(got, tuple) and len(got) == 3):
            # wrong payload shape = an id-collision/corruption class bug;
            # fail HERE with the evidence, not downstream in the policy
            raise RuntimeError(
                f"weight slot {v} holds a {type(got).__name__}, not the "
                f"(version, ts, params) triple: {got!r}"[:300])
        ver, ts, params = got
        if isinstance(params, (str, bytes)) or not isinstance(ver, int):
            # the one corrupted shape the triple check can't see: a
            # str/bytes params leaf surfaces later as an opaque
            # TypeError inside the jitted policy (params["pi"] on a
            # str) — fail here, naming the slot and payload instead
            raise RuntimeError(
                f"weight slot {v} payload corrupt: version "
                f"{ver!r}, params {type(params).__name__}"[:300])
        self.version, self._ts, self._params = ver, ts, params
        _fl.evt(_fl.WEIGHT_FETCH, ver)
        return True

    def current(self):
        """(params, version, publish_ts) of the newest published
        version, skipping past any we missed. Blocks (stop-aware) only
        while no version exists yet."""
        # bootstrap: one futex wait over {boot beacon, stop}. The beacon
        # (not slot 0) is the anchor — slot 0 is reclaimed by the keep
        # window, so a runner starting >= keep publications late would
        # otherwise wait on a permanently deleted id forever; once the
        # beacon sealed, a version exists and the tile scan terminates
        while self.version < 0:
            sealed = self.store.wait_sealed(
                [_boot_oid(self.base), self.stop], 1, 500)
            if sealed[0]:
                break
            if sealed[1]:
                raise ChannelClosed("queue stopped before first weights")
        while True:
            target = self._newest_sealed()
            if target == self.version and self._params is not None:
                return self._params, self.version, self._ts
            if self._fetch(max(target, 0)):
                return self._params, self.version, self._ts
            # raced the keep-window delete: the learner moved on while
            # we fetched — rescan, a newer version is sealed by now


# --------------------------------------------------------------------- #
# runner actor
# --------------------------------------------------------------------- #

class SebulbaEnvRunner(EnvRunner):
    """EnvRunner + the Sebulba producer loop: ONE actor call samples
    fragments forever, streaming them through the rollout queue until
    the learner tears the queue down. Returns the fragment count."""

    def run_loop(self, spec: RolloutQueueSpec, index: int,
                 weight_base: bytes,
                 max_fragments: Optional[int] = None) -> int:
        from ...core import runtime as rt_mod
        rt = rt_mod.get_runtime_if_exists()
        store = rt.store
        producer = RolloutProducer(spec, index, store=store)
        weights = WeightSubscriber(store, weight_base, spec.stop_oid())
        steps_per_frag = float(self._rollout_len * self._num_envs)
        frags = 0
        try:
            while max_fragments is None or frags < max_fragments:
                if producer.closed():
                    break
                params, version, ts = weights.current()
                _fl.evt(_fl.SAMPLE_BEGIN, index)
                sample = self.sample(params)
                _fl.evt(_fl.SAMPLE_END, index, frags)
                sample["param_version"] = version
                sample["param_ts"] = ts
                sample["runner"] = index
                producer.write(sample)
                frags += 1
                try:
                    tm.env_steps().inc(steps_per_frag,
                                       tags={"arch": "sebulba"})
                except Exception:
                    pass  # telemetry must never fail the data plane
        except ChannelClosed:
            pass  # teardown: queue stop flag sealed mid-wait
        finally:
            producer.sweep()
        return frags


# --------------------------------------------------------------------- #
# config + trainer
# --------------------------------------------------------------------- #

@dataclasses.dataclass
class SebulbaConfig:
    """Sebulba architecture knobs. ``transport`` picks the fragment
    path: "chan" (sealed-channel RolloutQueue, zero dispatches per
    fragment) or "actor" (one actor call per fragment, the IMPALA shape
    — the bench A/B baseline and the own-store fallback)."""

    env: Any = "CartPole-v1"          # gym id or picklable env factory
    num_env_runners: int = 4
    num_envs_per_runner: int = 4
    rollout_len: int = 32
    ring: int = 2                     # per-runner in-flight credit window
    hidden: tuple = (64, 64)
    seed: int = 0
    impala: ImpalaConfig = dataclasses.field(default_factory=ImpalaConfig)
    transport: str = "chan"
    # fragments consumed per train() call; None = one per runner
    fragments_per_iteration: Optional[int] = None
    runner_resources: Optional[dict] = None

    def env_fn(self) -> Callable:
        from ..env_runner import make_gym_env
        return make_gym_env(self.env) if isinstance(self.env, str) \
            else self.env


class SebulbaTrainer:
    """The Sebulba driver: owns the V-trace learner, the rollout queue
    and the weight broadcast; ``train()`` consumes one iteration's worth
    of fragments and publishes fresh weights once (one objstore put)."""

    def __init__(self, config: SebulbaConfig):
        import ray_tpu as ray
        from ...core.usage import record_library_usage
        record_library_usage("rl.podracer")
        if config.transport not in ("chan", "actor"):
            raise ValueError(
                f"unknown transport {config.transport!r} "
                "(expected 'chan' or 'actor')")
        self.config = config
        self._ray = ray
        env_fn = config.env_fn()
        probe = env_fn()
        self.module_cfg = MLPConfig(
            obs_dim=int(np.prod(probe.observation_space.shape)),
            num_actions=int(probe.action_space.n),
            hidden=tuple(config.hidden))
        probe.close()
        self.learner = ImpalaLearner(self.module_cfg, config.impala,
                                     seed=config.seed)
        self.iteration = 0
        self._total_env_steps = 0
        self._recent_returns: list[float] = []
        self._frags_per_iter = (config.fragments_per_iteration
                                or config.num_env_runners)
        self._tags = {"transport": config.transport}
        res = (config.runner_resources or {"CPU": 1}).get("CPU", 1)
        RunnerCls = ray.remote(SebulbaEnvRunner)
        self._runners = [
            RunnerCls.options(num_cpus=res).remote(
                env_fn, config.num_envs_per_runner, config.rollout_len,
                seed=config.seed + 1000 * (i + 1))
            for i in range(config.num_env_runners)]
        self._stopped = False
        if config.transport == "chan":
            self._start_channel_plane()
        else:
            self._start_actor_plane()

    # -- transports ------------------------------------------------------ #

    def _start_channel_plane(self) -> None:
        from ...core.api import _runtime
        store = _runtime().store
        n = self.config.num_env_runners
        self.spec = RolloutQueueSpec.create(n, ring=self.config.ring)
        self.queue = RolloutQueue(self.spec, store=store)
        self._weights = WeightBroadcast(store)
        self._weights.publish(self.learner.params)
        # the only control dispatches of the whole run: one loop start
        # per runner (teardown rides the stop flag, not an actor call)
        self._loop_refs = [
            r.run_loop.remote(self.spec, i, self._weights.base)
            for i, r in enumerate(self._runners)]
        self._count_dispatches(n)

    def _start_actor_plane(self) -> None:
        ray = self._ray
        # ref -> (runner, version, publish_ts) AT DISPATCH: staleness is
        # how far the learner moved while the fragment was in flight, so
        # the tag must be the version the weights were shipped with, not
        # the counter at receive time
        self._inflight: dict = {}
        weights_ref = ray.put(self.learner.params)
        self._actor_version = 0
        ts = time.time()
        for r in self._runners:
            self._inflight[r.sample.remote(weights_ref)] = (r, 0, ts)
        self._count_dispatches(len(self._runners))

    def _count_dispatches(self, n: int) -> None:
        try:
            tm.dispatches().inc(float(n), tags=self._tags)
        except Exception:
            pass  # telemetry must never fail the data plane

    def _probe_runners(self) -> None:
        """Queue on_idle hook: a producer loop that EXITED while the
        queue is live means a dead/failed env-runner — raise instead of
        letting the learner park forever on a channel nobody feeds."""
        if self._stopped:
            return
        ready, _ = self._ray.wait(self._loop_refs, num_returns=1,
                                  timeout=0)
        if ready:
            val = self._ray.get(ready[0])  # raises ActorDiedError & co.
            raise RuntimeError(
                f"sebulba env-runner loop exited mid-run "
                f"(returned {val!r}); stop() the trainer")

    def _next_fragment(self, timeout_s: float) -> dict:
        if self.config.transport == "chan":
            _, frag = self.queue.get(timeout_s,
                                     on_idle=self._probe_runners)
            return frag
        ray = self._ray
        t0 = time.perf_counter()
        done, _ = ray.wait(list(self._inflight), num_returns=1,
                           timeout=timeout_s)
        if not done:
            from ...core.object_store import GetTimeoutError
            raise GetTimeoutError("timed out waiting for a fragment")
        ref = done[0]
        runner, sent_version, sent_ts = self._inflight.pop(ref)
        frag = ray.get(ref)
        frag["param_version"] = sent_version
        frag["param_ts"] = sent_ts
        # redispatch with fresh weights: one put + one actor call per
        # fragment — the dispatch cost the channel transport retires
        weights_ref = ray.put(self.learner.params)
        self._actor_version += 1
        self._inflight[runner.sample.remote(weights_ref)] = (
            runner, self._actor_version, time.time())
        self._count_dispatches(1)
        try:
            tm.fragment_wait().observe(time.perf_counter() - t0,
                                       tags=self._tags)
            tm.fragments().inc(1.0, tags=self._tags)
            tm.env_steps().inc(
                float(np.prod(frag["actions"].shape)),
                tags={"arch": "sebulba"})
        except Exception:
            pass  # telemetry must never fail the data plane
        return frag

    # -- training -------------------------------------------------------- #

    def train(self, timeout_s: float = 120.0) -> dict:
        """One iteration: consume ``fragments_per_iteration`` fragments
        (completion order — true asynchrony), one V-trace update per
        fragment, then publish fresh weights ONCE (one objstore put)."""
        t0 = time.perf_counter()
        stats: dict = {}
        staleness: list[float] = []
        steps = 0
        for _ in range(self._frags_per_iter):
            frag = self._next_fragment(timeout_s)
            lag_v = max(0, self._current_version() -
                        int(frag.get("param_version", 0)))
            staleness.append(float(lag_v))
            try:
                tm.param_staleness().observe(float(lag_v))
                tm.weight_sync_lag().observe(
                    max(0.0, time.time() - float(frag.get("param_ts", 0))))
            except Exception:
                pass  # telemetry must never fail the data plane
            t1 = time.perf_counter()
            stats = self.learner.update(frag)
            try:
                tm.learner_update().observe(time.perf_counter() - t1,
                                            tags={"arch": "sebulba"})
            except Exception:
                pass  # telemetry must never fail the data plane
            steps += int(np.prod(frag["actions"].shape))
            self._recent_returns.extend(frag["episode_returns"])
        self._recent_returns = self._recent_returns[-100:]
        if self.config.transport == "chan":
            self._weights.publish(self.learner.params)
            depth = self.queue.depth()
        else:
            depth = len(self._inflight)
        self._total_env_steps += steps
        self.iteration += 1
        dt = time.perf_counter() - t0
        return {
            "training_iteration": self.iteration,
            "episode_return_mean": (
                float(np.mean(self._recent_returns))
                if self._recent_returns else float("nan")),
            "num_env_steps_sampled_lifetime": self._total_env_steps,
            "env_steps_per_sec": steps / max(dt, 1e-9),
            "fragments": self._frags_per_iter,
            "queue_depth": depth,
            "param_staleness_mean": float(np.mean(staleness)),
            "weight_version": self._current_version(),
            **{f"learner/{k}": v for k, v in stats.items()},
        }

    def _current_version(self) -> int:
        return (self._weights.version
                if self.config.transport == "chan"
                else self._actor_version)

    def flops_estimate(self):
        """FLOPs of one iteration = learner-update FLOPs x fragments
        consumed per train() (rollout compute runs in the env-runner
        actors and is latency-, not FLOP-, bound)."""
        fl = self.learner.flops_estimate()
        return fl * self._frags_per_iter if fl else None

    def evaluate(self, num_episodes: int = 5) -> dict:
        """Greedy evaluation in the DRIVER process (a channel runner is
        busy inside its one long run_loop call for the whole training
        run, so an eval actor call would queue behind it forever)."""
        import jax
        from .. import module as module_lib
        det = jax.jit(module_lib.deterministic_action)
        env = self.config.env_fn()()
        params = self.learner.params
        returns = []
        try:
            for ep in range(num_episodes):
                obs, _ = env.reset(seed=10_000 + ep)
                total, done = 0.0, False
                while not done:
                    a = int(np.asarray(det(
                        params, np.asarray(obs, np.float32))))
                    obs, rew, term, trunc, _ = env.step(a)
                    total += float(rew)
                    done = bool(term or trunc)
                returns.append(total)
        finally:
            env.close()
        return {"episode_returns": returns,
                "mean_return": float(np.mean(returns))}

    # -- checkpoint ------------------------------------------------------ #

    def save_state(self) -> dict:
        import jax
        return {"params": jax.device_get(self.learner.params),
                "opt_state": jax.device_get(self.learner.opt_state),
                "iteration": self.iteration,
                "total_env_steps": self._total_env_steps,
                "recent_returns": list(self._recent_returns)}

    def restore_state(self, state: dict) -> None:
        import jax
        import jax.numpy as jnp
        self.learner.params = jax.tree.map(jnp.asarray, state["params"])
        self.learner.opt_state = jax.tree.map(jnp.asarray,
                                              state["opt_state"])
        self.iteration = int(state["iteration"])
        self._total_env_steps = int(state["total_env_steps"])
        self._recent_returns = list(state.get("recent_returns", []))
        if self.config.transport == "chan":
            # restored weights must reach the runners before the next
            # fragment (they'd otherwise keep sampling the init policy)
            self._weights.publish(self.learner.params)

    def stop(self, timeout_s: float = 30.0) -> None:
        if self._stopped:
            return
        self._stopped = True
        ray = self._ray
        joined = True
        if self.config.transport == "chan":
            self.queue.close()  # every producer wakes with ChannelClosed
            try:
                ray.get(self._loop_refs, timeout=timeout_s)
            except Exception:
                joined = False  # straggler (slow env step / dead loop):
                # the stop flag must stay sealed until it can't write
        for r in self._runners:
            try:
                ray.kill(r)
            except Exception:
                pass  # runner already dead
        if self.config.transport == "chan":
            if not joined:
                # let the force-kills land, then re-sweep anything a
                # straggler sealed between the first sweep and its death
                time.sleep(0.5)
                self.queue.close()
            self.queue.release()
            self._weights.sweep()
