"""Podracer telemetry: rtpu_rl_* metrics + metrics_summary().

TorchTitan-style training observability (PAPERS.md: "TorchTitan" §3.3 —
throughput/MFU/comm logging as a first-class part of the trainer) over the
repo's metric pipeline (ray_tpu.util.metrics): every series merges on the
head and renders on /metrics with zero new transport, exactly like
rtpu_llm_* / rtpu_serve_*.

Metric names and label sets:
  rtpu_rl_env_steps_total{arch}                counter (arch=sebulba|anakin)
  rtpu_rl_fragments_total{transport}           counter (transport=chan|actor)
  rtpu_rl_dispatches_total{transport}          counter — control-plane actor
      calls the trainer issues for fragment delivery; the Sebulba
      channel transport's headline is dispatches/fragment -> ~0 in
      steady state (loop-start + teardown calls only), the actor-call
      transport pays >= 1 per fragment (bench_rl.py A/B reads this)
  rtpu_rl_fragment_wait_seconds{transport}     histogram — learner blocked
      waiting for the next fragment (queue starvation signal)
  rtpu_rl_queue_depth                          gauge — sealed-but-unread
      fragments across all producers (sampled per iteration)
  rtpu_rl_learner_update_seconds{arch}         histogram — one SGD update
  rtpu_rl_weight_sync_lag_seconds              histogram — publish-to-consume
      age of the params a fragment was sampled with
  rtpu_rl_param_staleness                      histogram — how many weight
      versions behind the learner a fragment's behaviour policy was
      (the off-policy gap V-trace corrects; buckets 0..32)
  rtpu_rl_weight_broadcasts_total              counter
  rtpu_rl_checkpoints_total{kind}              counter (kind=save|restore)

``metrics_summary()`` condenses the merged store into the numbers a run
report cites (env steps/s needs a wall-clock denominator, so trainers
report it in their result dicts; the summary exposes totals/quantiles).
"""
from __future__ import annotations

from typing import Optional

from ...util.metrics import (LATENCY_BUCKETS as _LAT, Counter, Gauge,
                             Histogram, cached_metric as _metric,
                             collect_store as _collect_store,
                             histogram_stats as _hist_stats)

# version-lag buckets: 0 = on-policy, small powers of two cover the
# plausible lag of a credit-bounded queue (ring x producers)
_STALENESS = (0.0, 1.0, 2.0, 4.0, 8.0, 16.0, 32.0)


def env_steps() -> Counter:
    return _metric(Counter, "rtpu_rl_env_steps_total",
                   "environment steps sampled", tag_keys=("arch",))


def fragments() -> Counter:
    return _metric(Counter, "rtpu_rl_fragments_total",
                   "rollout fragments delivered to the learner",
                   tag_keys=("transport",))


def dispatches() -> Counter:
    return _metric(Counter, "rtpu_rl_dispatches_total",
                   "control-plane actor calls issued for fragment "
                   "delivery", tag_keys=("transport",))


def fragment_wait() -> Histogram:
    return _metric(Histogram, "rtpu_rl_fragment_wait_seconds",
                   "learner time blocked waiting for a fragment",
                   boundaries=_LAT, tag_keys=("transport",))


def queue_depth() -> Gauge:
    return _metric(Gauge, "rtpu_rl_queue_depth",
                   "sealed-but-unread fragments across producers")


def learner_update() -> Histogram:
    return _metric(Histogram, "rtpu_rl_learner_update_seconds",
                   "one learner SGD update", boundaries=_LAT,
                   tag_keys=("arch",))


def weight_sync_lag() -> Histogram:
    return _metric(Histogram, "rtpu_rl_weight_sync_lag_seconds",
                   "publish-to-consume age of a fragment's params",
                   boundaries=_LAT)


def param_staleness() -> Histogram:
    return _metric(Histogram, "rtpu_rl_param_staleness",
                   "weight versions behind the learner a fragment's "
                   "behaviour policy was", boundaries=_STALENESS)


def weight_broadcasts() -> Counter:
    return _metric(Counter, "rtpu_rl_weight_broadcasts_total",
                   "weight versions published runner-ward")


def checkpoints() -> Counter:
    return _metric(Counter, "rtpu_rl_checkpoints_total",
                   "trainer checkpoint events", tag_keys=("kind",))


def replay_ingested() -> Counter:
    return _metric(Counter, "rtpu_rl_replay_ingested_total",
                   "transitions streamed from datasets into replay "
                   "buffers (replay.py ingestion adapter)")


# --------------------------------------------------------------------- #
# summary
# --------------------------------------------------------------------- #

def _by_tag(rec: Optional[dict], tag: str) -> dict:
    out: dict = {}
    for key, val in (rec or {}).get("series", {}).items():
        label = next((v for k, v in key if k == tag), "")
        out[label] = out.get(label, 0.0) + val
    return out


def metrics_summary() -> dict:
    """Condense the merged rtpu_rl_* store: per-transport fragment /
    dispatch totals with the dispatches_per_fragment headline (~0 for
    the Sebulba channel transport in steady state), env-step totals per
    architecture, queue depth, and quantiles for fragment wait, learner
    update, weight-sync lag and param staleness. Store merge + histogram
    fold are the util/metrics.py helpers serve.metrics_summary() uses."""
    store = _collect_store()
    out: dict = {}
    frags = _by_tag(store.get("rtpu_rl_fragments_total"), "transport")
    disp = _by_tag(store.get("rtpu_rl_dispatches_total"), "transport")
    if frags or disp:
        transports: dict = {}
        for tr in set(frags) | set(disp):
            rec = {"fragments": frags.get(tr, 0.0),
                   "dispatches": disp.get(tr, 0.0)}
            if rec["fragments"]:
                rec["dispatches_per_fragment"] = (
                    rec["dispatches"] / rec["fragments"])
            transports[tr] = rec
        out["transport"] = transports
    steps = _by_tag(store.get("rtpu_rl_env_steps_total"), "arch")
    if steps:
        out["env_steps"] = steps
    rec = store.get("rtpu_rl_queue_depth")
    if rec and rec["series"]:
        out["queue_depth"] = max(rec["series"].values())
    for key, name in (
            ("fragment_wait", "rtpu_rl_fragment_wait_seconds"),
            ("learner_update", "rtpu_rl_learner_update_seconds"),
            ("weight_sync_lag", "rtpu_rl_weight_sync_lag_seconds"),
            ("param_staleness", "rtpu_rl_param_staleness")):
        stats = _hist_stats(store.get(name))
        if stats is not None:
            out[key] = stats
    bcasts = _by_tag(store.get("rtpu_rl_weight_broadcasts_total"), "")
    if bcasts:
        out["weight_broadcasts"] = sum(bcasts.values())
    ckpts = _by_tag(store.get("rtpu_rl_checkpoints_total"), "kind")
    if ckpts:
        out["checkpoints"] = ckpts
    return out
