"""PodracerTrainer: one driver over both Podracer architectures.

Wraps :class:`SebulbaTrainer` (actor/learner split over the rollout
queue) or :class:`AnakinTrainer` (fused jitted env+update) — picked by
config type — and adds the TorchTitan-style production loop (PAPERS.md:
"TorchTitan" §3.2 checkpointing): periodic checkpoints through
``train.CheckpointManager``, automatic resume from the latest checkpoint
in ``storage_dir`` (kill the process mid-run, start a new trainer on the
same directory, training continues from the last save), and the
``rtpu_rl_*`` telemetry surfaced through
``rl.podracer.metrics_summary()``.

    cfg = SebulbaConfig(env="CartPole-v1", num_env_runners=4)
    trainer = PodracerTrainer(cfg, storage_dir="/ckpts/run1",
                              checkpoint_every=10)
    result = trainer.fit(num_iterations=200, target_return=450)
"""
from __future__ import annotations

import math
from typing import Any, Optional

from . import telemetry as tm
from .anakin import AnakinConfig, AnakinTrainer
from .sebulba import SebulbaConfig, SebulbaTrainer


class PodracerTrainer:
    def __init__(self, config: Any, storage_dir: Optional[str] = None,
                 checkpoint_every: int = 10,
                 num_to_keep: Optional[int] = 2,
                 score_attribute: Optional[str] = None,
                 resume: bool = True, profile: bool = False):
        if isinstance(config, SebulbaConfig):
            self.arch = "sebulba"
            self._inner = SebulbaTrainer(config)
        elif isinstance(config, AnakinConfig):
            self.arch = "anakin"
            self._inner = AnakinTrainer(config)
        else:
            raise TypeError(
                f"config must be a SebulbaConfig or AnakinConfig, got "
                f"{type(config).__name__}")
        self.config = config
        self.checkpoint_every = max(1, checkpoint_every)
        # step profiler (util/profiling.py): compile-vs-execute split of
        # the training step, always on (two clock reads per train());
        # profile=True additionally estimates the update program's FLOPs
        # on the first iteration so summary()/results carry an MFU
        from ...util.profiling import StepProfiler
        self.profiler = StepProfiler(f"podracer-{self.arch}")
        self._profile_flops = profile
        self._last_saved = -1   # iteration of the newest checkpoint
        self._manager = None
        if storage_dir:
            from ...train import CheckpointManager
            self._manager = CheckpointManager(
                storage_dir, num_to_keep=num_to_keep,
                score_attribute=score_attribute)
            if self._manager.scan_existing() and resume:
                # newest first; a SIGKILL mid-write can leave a truncated
                # checkpoint behind, so fall back until one loads
                for ckpt, _ in reversed(self._manager.history):
                    try:
                        self._restore(ckpt)
                        break
                    except Exception:
                        continue  # partial/corrupt checkpoint: try older

    # -- training loop --------------------------------------------------- #

    @property
    def iteration(self) -> int:
        return self._inner.iteration

    def train(self) -> dict:
        """One inner iteration + the periodic checkpoint. The step
        profiler wraps the whole iteration (the first one, which jit-
        compiles the update/fused program, books as compile time); its
        rolling summary rides the result under ``profile/``."""
        with self.profiler.step("train"):
            result = self._inner.train()
        if self._profile_flops:
            # at most ONE out-of-band compile, even when the estimate
            # comes back unknown — retrying every train() would serialize
            # an XLA compile into each iteration
            self._profile_flops = False
            self.profiler.attach_flops("train",
                                       self._inner_flops_estimate())
        if self._manager is not None and \
                self._inner.iteration % self.checkpoint_every == 0:
            self.save(result)
        prof = self.profiler.summary()
        result["profile/step_wall_s"] = prof["step_wall_s"]
        result["profile/compile_s"] = prof["compile_s"]
        if prof["mfu"] is not None:
            result["profile/mfu"] = prof["mfu"]
        return result

    def _inner_flops_estimate(self):
        """FLOPs of one training step via XLA cost_analysis on the
        inner trainer's jitted program (one extra compile, once)."""
        try:
            return self._inner.flops_estimate()
        except Exception:
            return None  # profiling must never fail training

    def fit(self, num_iterations: int,
            target_return: Optional[float] = None) -> dict:
        """Train until ``num_iterations`` TOTAL iterations have run
        (resume-aware: a restored trainer only runs the remainder) or
        the trailing mean return reaches ``target_return``. Saves a
        final checkpoint for any progress not already covered by the
        periodic one, returns the last result."""
        result = {"training_iteration": self._inner.iteration}
        while self._inner.iteration < num_iterations:
            result = self.train()
            ret = result.get("episode_return_mean")
            if target_return is not None and ret is not None \
                    and not math.isnan(ret) and ret >= target_return:
                break
        if self._manager is not None and \
                self._last_saved != self._inner.iteration:
            self.save(result)
        return result

    def evaluate(self, num_episodes: int = 5) -> dict:
        if not hasattr(self._inner, "evaluate"):
            raise NotImplementedError(
                f"{self.arch} has no evaluation path")
        return self._inner.evaluate(num_episodes)

    # -- checkpointing --------------------------------------------------- #

    def save(self, metrics: Optional[dict] = None):
        """Checkpoint now (also called by the periodic hook). Returns
        the managed Checkpoint."""
        if self._manager is None:
            raise RuntimeError("no storage_dir configured")
        from ...train import Checkpoint
        meta = {"arch": self.arch,
                "iteration": self._inner.iteration}
        for k, v in (metrics or {}).items():
            if isinstance(v, (int, float, str)) and not (
                    isinstance(v, float) and math.isnan(v)):
                meta[k] = v
        ckpt = Checkpoint.from_state(self._inner.save_state(),
                                     metadata=meta)
        managed = self._manager.register(ckpt, meta)
        self._last_saved = self._inner.iteration
        try:
            tm.checkpoints().inc(1.0, tags={"kind": "save"})
        except Exception:
            pass  # telemetry must never fail a checkpoint
        return managed

    def _restore(self, ckpt) -> None:
        self._inner.restore_state(ckpt.load_state())
        self._last_saved = self._inner.iteration  # already on disk
        try:
            tm.checkpoints().inc(1.0, tags={"kind": "restore"})
        except Exception:
            pass  # telemetry must never fail a restore
        self.restored_from = ckpt.path

    def stop(self) -> None:
        self._inner.stop()
