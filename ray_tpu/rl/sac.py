"""SAC: soft actor-critic for continuous control.

Reference parity: rllib/algorithms/sac/ (off-policy replay, twin Q
critics with min-target, tanh-Gaussian policy, entropy temperature;
Haarnoja et al. 2018). Mirrors the DQN driver shape: runners collect
transitions, the learner does K jitted minibatch updates per train()
(one device round-trip), targets track via polyak averaging.
"""
from __future__ import annotations

import dataclasses
import time
from typing import Callable, Optional

import numpy as np

from . import module as module_lib
from .base import AlgorithmBase, AlgorithmConfigBase
from .dqn import ReplayBuffer
from .module import ContinuousMLPConfig


@dataclasses.dataclass(frozen=True)
class SACConfig:
    """(reference: sac.py SACConfig.training)"""
    actor_lr: float = 3e-4
    critic_lr: float = 3e-4
    gamma: float = 0.99
    tau: float = 0.005            # polyak target rate
    alpha: float = 0.2            # entropy temperature (fixed)
    buffer_size: int = 100_000
    batch_size: int = 128
    num_updates_per_iter: int = 32
    learning_starts: int = 1_000
    random_steps: int = 500       # uniform exploration before the policy


class ContinuousReplayBuffer(ReplayBuffer):
    """ReplayBuffer with float action vectors."""

    def __init__(self, capacity: int, obs_dim: int, action_dim: int):
        super().__init__(capacity, obs_dim)
        self.actions = np.empty((capacity, action_dim), np.float32)


class SACRunner:
    """Transition collector sampling from the tanh-Gaussian policy."""

    def __init__(self, env_fn: Callable, num_envs: int, rollout_len: int,
                 seed: int = 0):
        import gymnasium as gym
        self._venv = gym.vector.SyncVectorEnv(
            [(lambda f=env_fn: f()) for _ in range(num_envs)],
            autoreset_mode=gym.vector.AutoresetMode.SAME_STEP)
        self._num_envs = num_envs
        self._rollout_len = rollout_len
        self._obs, _ = self._venv.reset(seed=seed)
        self._rng = np.random.default_rng(seed + 1)
        self._sample_fn = None
        self._det_fn = None
        self._cfg = None
        self._ep_return = np.zeros(num_envs, np.float64)
        self._completed: list[float] = []
        self._steps = 0

    def sample(self, params, cfg: ContinuousMLPConfig,
               random_steps: int) -> dict:
        import jax
        if self._sample_fn is None:
            self._cfg = cfg
            self._sample_fn = jax.jit(
                lambda p, o, k: module_lib.sample_action_continuous(
                    p, o, k, cfg))
        T, E = self._rollout_len, self._num_envs
        obs_dim = self._obs.shape[1]
        adim = int(np.prod(self._venv.single_action_space.shape))
        obs_b = np.empty((T * E, obs_dim), np.float32)
        nxt_b = np.empty((T * E, obs_dim), np.float32)
        act_b = np.empty((T * E, adim), np.float32)
        rew_b = np.empty((T * E,), np.float32)
        done_b = np.empty((T * E,), np.float32)
        key = jax.random.PRNGKey(int(self._rng.integers(2 ** 31)))
        space = self._venv.single_action_space
        for t in range(T):
            if self._steps < random_steps:
                action = self._rng.uniform(
                    space.low, space.high,
                    size=(E,) + space.shape).astype(np.float32)
            else:
                key, sub = jax.random.split(key)
                action, _ = self._sample_fn(
                    params, self._obs.astype(np.float32), sub)
                action = np.asarray(action)
            nxt, rew, term, trunc, info = self._venv.step(action)
            nxt_td = nxt
            ended = np.logical_or(term, trunc)
            final = info.get("final_obs") if isinstance(info, dict) else None
            if final is not None and ended.any():
                nxt_td = nxt.copy()
                for i in np.nonzero(ended)[0]:
                    if final[i] is not None:
                        nxt_td[i] = final[i]
                done_for_td = term.astype(np.float32)
            else:
                done_for_td = ended.astype(np.float32)
            sl = slice(t * E, (t + 1) * E)
            obs_b[sl] = self._obs
            nxt_b[sl] = nxt_td
            act_b[sl] = action.reshape(E, adim)
            rew_b[sl] = rew
            done_b[sl] = done_for_td
            self._ep_return += rew
            for i in np.nonzero(ended)[0]:
                self._completed.append(float(self._ep_return[i]))
                self._ep_return[i] = 0.0
            self._obs = nxt
            self._steps += E
        episodes, self._completed = self._completed, []
        return {"obs": obs_b, "actions": act_b, "rewards": rew_b,
                "next_obs": nxt_b, "dones": done_b,
                "episode_returns": episodes}

    def evaluate(self, params, num_episodes: int = 5,
                 cfg: Optional[ContinuousMLPConfig] = None) -> dict:
        import jax
        cfg = cfg or self._cfg
        if self._det_fn is None:
            self._det_fn = jax.jit(
                lambda p, o: module_lib.deterministic_action_continuous(
                    p, o, cfg))
        det = self._det_fn
        env = self._venv.envs[0]
        returns = []
        for ep in range(num_episodes):
            obs, _ = env.reset(seed=30_000 + ep)
            total, done = 0.0, False
            while not done:
                a = np.asarray(det(params, obs.astype(np.float32)))
                obs, rew, term, trunc, _ = env.step(a)
                total += float(rew)
                done = bool(term or trunc)
            returns.append(total)
        self._obs, _ = self._venv.reset()
        self._ep_return[:] = 0.0
        return {"episode_returns": returns,
                "mean_return": float(np.mean(returns))}


class SACLearner:
    def __init__(self, module_cfg: ContinuousMLPConfig, cfg: SACConfig,
                 seed: int = 0):
        import jax
        import optax
        self.cfg = cfg
        self.module_cfg = module_cfg
        self.params = module_lib.init_sac(jax.random.PRNGKey(seed),
                                          module_cfg)
        self.target_q = {"q1": self.params["q1"], "q2": self.params["q2"]}
        self.actor_opt = optax.adam(cfg.actor_lr)
        self.critic_opt = optax.adam(cfg.critic_lr)
        self.actor_state = self.actor_opt.init(self.params["pi"])
        self.critic_state = self.critic_opt.init(
            {"q1": self.params["q1"], "q2": self.params["q2"]})
        self._update = jax.jit(self._build_update())

    @property
    def opt_state(self):  # AlgorithmBase checkpoint contract
        return {"actor": self.actor_state, "critic": self.critic_state}

    @opt_state.setter
    def opt_state(self, v):
        self.actor_state = v["actor"]
        self.critic_state = v["critic"]

    def _build_update(self):
        import jax
        import jax.numpy as jnp
        import optax
        cfg, mcfg = self.cfg, self.module_cfg

        def critic_loss(qs, pi, target_q, batch, key):
            a_next, logp_next = module_lib.sample_action_continuous(
                {"pi": pi}, batch["next_obs"], key, mcfg)
            tq1, tq2 = module_lib.q_values_continuous(
                target_q | {"pi": pi}, batch["next_obs"], a_next)
            target_v = jnp.minimum(tq1, tq2) - cfg.alpha * logp_next
            target = batch["rewards"] + cfg.gamma * (
                1.0 - batch["dones"]) * target_v
            target = jax.lax.stop_gradient(target)
            q1, q2 = module_lib.q_values_continuous(
                qs | {"pi": pi}, batch["obs"], batch["actions"])
            return ((q1 - target) ** 2 + (q2 - target) ** 2).mean(), (
                q1.mean())

        def actor_loss(pi, qs, batch, key):
            a, logp = module_lib.sample_action_continuous(
                {"pi": pi}, batch["obs"], key, mcfg)
            q1, q2 = module_lib.q_values_continuous(
                qs | {"pi": pi}, batch["obs"], a)
            return (cfg.alpha * logp - jnp.minimum(q1, q2)).mean(), (
                -logp.mean())

        def make_one(data):
            def one(carry, xs):
                params, target_q, a_state, c_state = carry
                idx, key = xs
                kc, ka = jax.random.split(key)
                batch = {k: v[idx] for k, v in data.items()}
                qs = {"q1": params["q1"], "q2": params["q2"]}
                (closs, qmean), cgrads = jax.value_and_grad(
                    critic_loss, has_aux=True)(qs, params["pi"], target_q,
                                               batch, kc)
                cupd, c_state = self.critic_opt.update(cgrads, c_state, qs)
                qs = optax.apply_updates(qs, cupd)
                params = params | qs
                (aloss, ent), agrads = jax.value_and_grad(
                    actor_loss, has_aux=True)(params["pi"], qs, batch, ka)
                aupd, a_state = self.actor_opt.update(
                    agrads, a_state, params["pi"])
                params = params | {"pi": optax.apply_updates(
                    params["pi"], aupd)}
                target_q = jax.tree.map(
                    lambda t, o: (1 - cfg.tau) * t + cfg.tau * o,
                    target_q, qs)
                return (params, target_q, a_state, c_state), (
                    closs, aloss, ent, qmean)
            return one

        def update(params, target_q, a_state, c_state, data, idx, key):
            keys = jax.random.split(key, idx.shape[0])
            (params, target_q, a_state, c_state), (cl, al, ent, qm) = \
                jax.lax.scan(make_one(data),
                             (params, target_q, a_state, c_state),
                             (idx, keys))
            return (params, target_q, a_state, c_state,
                    cl.mean(), al.mean(), ent.mean(), qm.mean())

        return update

    def update_from_buffer(self, buf, rng: np.random.Generator) -> dict:
        import jax
        import jax.numpy as jnp
        cfg = self.cfg
        idx = buf.sample_indices(rng, cfg.batch_size,
                                 cfg.num_updates_per_iter)
        data = {"obs": jnp.asarray(buf.obs),
                "actions": jnp.asarray(buf.actions),
                "rewards": jnp.asarray(buf.rewards),
                "next_obs": jnp.asarray(buf.next_obs),
                "dones": jnp.asarray(buf.dones)}
        key = jax.random.PRNGKey(int(rng.integers(2 ** 31)))
        (self.params, self.target_q, self.actor_state, self.critic_state,
         cl, al, ent, qm) = self._update(
            self.params, self.target_q, self.actor_state,
            self.critic_state, data, jnp.asarray(idx), key)
        return {"critic_loss": float(cl), "actor_loss": float(al),
                "entropy": float(ent), "q_mean": float(qm)}


class SAC(AlgorithmBase):
    """The Algorithm driver (reference: sac.py training_step)."""

    HPARAM_FIELD = "sac"

    def _make_module_cfg(self, probe):
        space = probe.action_space
        return ContinuousMLPConfig(
            obs_dim=int(np.prod(probe.observation_space.shape)),
            action_dim=int(np.prod(space.shape)),
            hidden=tuple(self.config.hidden),
            # PER-DIM bounds: asymmetric Box spaces squash correctly
            action_low=tuple(np.asarray(space.low).reshape(-1).tolist()),
            action_high=tuple(np.asarray(space.high).reshape(-1).tolist()))

    def __init__(self, config: "SACAlgorithmConfig"):
        self._setup(config, SACRunner)
        self.learner = SACLearner(self.module_cfg, config.sac,
                                  seed=config.seed)
        self.buffer = ContinuousReplayBuffer(
            config.sac.buffer_size, self.module_cfg.obs_dim,
            self.module_cfg.action_dim)
        self._np_rng = np.random.default_rng(config.seed)

    def train(self) -> dict:
        ray = self._ray
        t0 = time.perf_counter()
        weights_ref = ray.put(self.learner.params)
        samples = ray.get([
            r.sample.remote(weights_ref, self.module_cfg,
                            self.config.sac.random_steps)
            for r in self._runners])
        for s in samples:
            self.buffer.add_batch(s["obs"], s["actions"], s["rewards"],
                                  s["next_obs"], s["dones"])
        mean_ret = self._note_returns(
            [r for s in samples for r in s["episode_returns"]])
        steps = sum(len(s["rewards"]) for s in samples)
        self._total_env_steps += steps
        stats = {}
        if self._total_env_steps >= self.config.sac.learning_starts:
            stats = self.learner.update_from_buffer(self.buffer,
                                                    self._np_rng)
        self.iteration += 1
        dt = time.perf_counter() - t0
        return {
            "training_iteration": self.iteration,
            "episode_return_mean": mean_ret,
            "num_env_steps_sampled_lifetime": self._total_env_steps,
            "env_steps_per_sec": steps / dt,
            "buffer_size": self.buffer.size,
            **{f"learner/{k}": v for k, v in stats.items()},
        }

    def evaluate(self, num_episodes: int = 5) -> dict:
        ray = self._ray
        weights_ref = ray.put(self.learner.params)
        return ray.get(self._runners[0].evaluate.remote(
            weights_ref, num_episodes, self.module_cfg))

    def _extra_state(self) -> dict:
        return {"target_q": self.learner.target_q}

    def _load_extra_state(self, state: dict) -> None:
        import jax
        import jax.numpy as jnp
        self.learner.target_q = jax.tree.map(
            jnp.asarray, state["target_q"])


class SACAlgorithmConfig(AlgorithmConfigBase):
    """Fluent config for SAC (base: AlgorithmConfigBase)."""

    HPARAM_FIELD = "sac"
    HPARAM_FACTORY = SACConfig

    def __init__(self):
        super().__init__()
        self.num_env_runners = 1
        self.hidden = (128, 128)

    @property
    def ALGO_CLS(self):
        return SAC
