"""ray_tpu.serve — model serving.

Reference parity: python/ray/serve (controller _private/controller.py:88,
deployment state machine deployment_state.py, pow-2 router
request_router/pow_2_router.py:27, replicas replica.py:945, HTTP proxy
proxy.py:709, autoscaling autoscaling_policy.py:12, public api serve/api.py).

Shape here: a singleton ServeController actor reconciles declarative
deployment specs into replica actors; DeploymentHandles route requests with
power-of-two-choices over per-handle in-flight counts; a controller-managed
FLEET of aiohttp proxy actors exposes HTTP behind a shared route table with
SLO-aware admission control and a cluster-wide prefix-cache directory
(serve/frontdoor/); queue-based autoscaling adds/removes replicas between
min/max. LLM serving (serve.llm analog) lives in ray_tpu.llm on top of this.

    @serve.deployment(num_replicas=2)
    class Model:
        def __call__(self, x): ...

    handle = serve.run(Model.bind(), name="app")
    out = handle.remote(x).result()
"""
from .api import (
    Application,
    Deployment,
    delete,
    deployment,
    get_app_handle,
    run,
    shutdown,
    status,
    update_user_config,
)
from .batching import batch
from .context import get_multiplexed_model_id, get_request_context
from .handle import (DeploymentHandle, DeploymentResponse,
                     DeploymentResponseGenerator)
from .grpc_proxy import start_grpc_proxy
from .metrics import metrics_summary
from .multiplex import multiplexed

__all__ = [
    "Application", "Deployment", "deployment", "run", "shutdown", "delete",
    "status", "get_app_handle", "DeploymentHandle", "DeploymentResponse",
    "DeploymentResponseGenerator", "batch", "multiplexed",
    "get_multiplexed_model_id", "get_request_context", "metrics_summary",
    "start_grpc_proxy", "update_user_config",
]
