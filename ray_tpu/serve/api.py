"""Public Serve API: @deployment, bind, run, status, shutdown.

Reference parity: python/ray/serve/api.py (run :691, deployment decorator,
Application/BuiltApplication model) and serve/deployment.py. Deployments are
declarative specs; `.bind()` composes them into an application DAG whose
non-ingress nodes are injected into their parents as DeploymentHandles
(reference: model composition via handle passing).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional

from .handle import DeploymentHandle

CONTROLLER_NAME = "rtpu:serve:controller"


@dataclasses.dataclass
class AutoscalingConfig:
    """(reference: serve/config.py AutoscalingConfig)"""
    min_replicas: int = 1
    max_replicas: int = 4
    target_ongoing_requests: float = 2.0
    upscale_delay_s: float = 0.5
    downscale_delay_s: float = 2.0


@dataclasses.dataclass
class DeploymentSpec:
    name: str
    func_or_class: Any
    num_replicas: int = 1
    max_ongoing_requests: int = 16
    ray_actor_options: dict = dataclasses.field(default_factory=dict)
    autoscaling_config: Optional[AutoscalingConfig] = None
    init_args: tuple = ()
    init_kwargs: dict = dataclasses.field(default_factory=dict)
    # pushed to replicas' reconfigure(user_config) at boot and on
    # update_user_config — lightweight updates without restarts
    user_config: Any = None
    # MPMD stage role within the app (e.g. "prefill"/"decode"): the
    # controller pairs same-app role groups after reconcile — each
    # prefill replica gets a sealed KV ring to its paired decode
    # replica (llm/pd_disagg.py channel handoff)
    role: Optional[str] = None


class Application:
    """A bound deployment DAG; `ingress` is the root (reference:
    serve/_private/build_app.py BuiltApplication)."""

    def __init__(self, ingress: "BoundDeployment"):
        self.ingress = ingress

    def specs(self) -> list[DeploymentSpec]:
        out: dict[str, DeploymentSpec] = {}

        def visit(node: BoundDeployment):
            if node.spec.name in out:
                return
            out[node.spec.name] = node.spec
            for dep in node.children():
                visit(dep)
        visit(self.ingress)
        return list(out.values())


class BoundDeployment:
    def __init__(self, spec: DeploymentSpec, args: tuple, kwargs: dict):
        self.spec = dataclasses.replace(spec, init_args=args,
                                        init_kwargs=kwargs)

    def children(self) -> list["BoundDeployment"]:
        found = []
        for a in list(self.spec.init_args) + list(
                self.spec.init_kwargs.values()):
            if isinstance(a, BoundDeployment):
                found.append(a)
        return found


class Deployment:
    """Declarative deployment template (reference: serve/deployment.py
    Deployment). Call .bind(*init_args) to place it in an application."""

    def __init__(self, spec: DeploymentSpec):
        self._spec = spec

    @property
    def name(self) -> str:
        return self._spec.name

    def options(self, **kwargs) -> "Deployment":
        allowed = {"name", "num_replicas", "max_ongoing_requests",
                   "ray_actor_options", "autoscaling_config",
                   "user_config", "role"}
        bad = set(kwargs) - allowed
        if bad:
            raise ValueError(f"unknown deployment options {sorted(bad)}")
        return Deployment(dataclasses.replace(self._spec, **kwargs))

    def bind(self, *args, **kwargs) -> Application:
        """Returns an Application rooted at this deployment. Bound child
        applications passed as args become handles at runtime."""
        args = tuple(a.ingress if isinstance(a, Application) else a
                     for a in args)
        kwargs = {k: (v.ingress if isinstance(v, Application) else v)
                  for k, v in kwargs.items()}
        return Application(BoundDeployment(self._spec, args, kwargs))


def deployment(_func_or_class=None, *, name: Optional[str] = None,
               num_replicas: int = 1, max_ongoing_requests: int = 16,
               ray_actor_options: Optional[dict] = None,
               autoscaling_config: Optional[dict | AutoscalingConfig] = None,
               user_config: Any = None, role: Optional[str] = None,
               **_ignored) -> Any:
    """@serve.deployment decorator (reference: serve/api.py:deployment)."""
    if isinstance(autoscaling_config, dict):
        autoscaling_config = AutoscalingConfig(**autoscaling_config)

    def wrap(fc):
        n = num_replicas
        if n == "auto":
            n = 1
        return Deployment(DeploymentSpec(
            name=name or getattr(fc, "__name__", "deployment"),
            func_or_class=fc,
            num_replicas=n,
            max_ongoing_requests=max_ongoing_requests,
            ray_actor_options=ray_actor_options or {},
            autoscaling_config=autoscaling_config,
            user_config=user_config,
            role=role,
        ))
    if _func_or_class is not None:
        return wrap(_func_or_class)
    return wrap


# ---------------------------------------------------------------------------
# run / status / shutdown
# ---------------------------------------------------------------------------

def _ray():
    import ray_tpu
    if not ray_tpu.is_initialized():
        ray_tpu.init()
    return ray_tpu


def _controller(create: bool = True):
    ray = _ray()
    from .controller import ServeController
    try:
        return ray.get_actor(CONTROLLER_NAME)
    except ValueError:
        if not create:
            raise
    cls = ray.remote(ServeController)
    return cls.options(name=CONTROLLER_NAME, max_concurrency=512).remote()


def run(app: Application, *, name: str = "default",
        route_prefix: Optional[str] = "/", blocking: bool = False,
        http_port: Optional[int] = None,
        num_proxies: Optional[int] = None,
        local_testing_mode: bool = False,
        _local_testing_mode: bool = False) -> DeploymentHandle:
    """Deploy an application; returns the ingress handle
    (reference: serve/api.py:691). With ``local_testing_mode=True`` the
    whole application runs in-process with no cluster — unit-test speed
    for composition/async/streaming logic (reference:
    serve/_private/local_testing_mode.py; also accepted under the
    reference's ``_local_testing_mode`` spelling).

    ``num_proxies`` (default cfg.serve_num_proxies) scales the HTTP
    front door: the controller keeps N proxy actors alive on ports
    http_port..http_port+N-1, each applying SLO-aware admission control
    from the shared route table (serve/frontdoor/)."""
    import cloudpickle
    from ..core.usage import record_library_usage
    record_library_usage("serve")
    if local_testing_mode or _local_testing_mode:
        from .local_mode import build_local_app
        return build_local_app(app, name)
    # a cluster deploy supersedes any local-mode app of the same name —
    # otherwise get_app_handle/delete keep shadowing the cluster app with
    # the stale in-process one
    from .local_mode import delete_local_app
    delete_local_app(name)
    ray = _ray()
    ctrl = _controller()
    specs_blob = cloudpickle.dumps(
        (app.specs(), app.ingress.spec.name, route_prefix))
    ray.get(ctrl.deploy_application.remote(name, specs_blob, http_port,
                                           num_proxies))
    handle = DeploymentHandle(app.ingress.spec.name, name, ctrl)
    if blocking:  # pragma: no cover - interactive use
        import time
        while True:
            time.sleep(1)
    return handle


def get_app_handle(name: str = "default") -> DeploymentHandle:
    from .local_mode import get_local_app
    local = get_local_app(name)
    if local is not None:
        return local
    ray = _ray()
    ctrl = _controller(create=False)
    ingress = ray.get(ctrl.get_ingress.remote(name))
    return DeploymentHandle(ingress, name, ctrl)


def update_user_config(app: str, deployment_name: str,
                       user_config: Any) -> None:
    """Push a new user_config to a deployment's live replicas without
    restarting them (reference: lightweight config updates via
    reconfigure())."""
    ray = _ray()
    ctrl = _controller(create=False)
    ray.get(ctrl.update_user_config.remote(app, deployment_name,
                                           user_config))


def status() -> dict:
    ray = _ray()
    try:
        ctrl = _controller(create=False)
    except ValueError:
        return {"applications": {}}
    return ray.get(ctrl.status.remote())


def delete(name: str = "default") -> None:
    import ray_tpu

    from .local_mode import delete_local_app
    delete_local_app(name)
    if not ray_tpu.is_initialized():
        # nothing cluster-side to delete — and NEVER boot a whole cluster
        # just to tear down an app (a test-teardown delete() after
        # ray.shutdown() used to do exactly that, leaking a live Runtime
        # + prestarted worker pool into the rest of the process)
        return
    ray = ray_tpu
    try:
        ctrl = _controller(create=False)
    except ValueError:
        return
    ray.get(ctrl.delete_application.remote(name))


def shutdown() -> None:
    import ray_tpu

    from .local_mode import _REGISTRY
    _REGISTRY.clear()
    if not ray_tpu.is_initialized():
        return  # nothing cluster-side to stop; never BOOT one to shut down
    ray = _ray()
    try:
        gp = ray.get_actor("rtpu:serve:grpc-proxy")
        try:
            ray.get(gp.stop.remote())
        except Exception:
            pass  # proxy dying; kill below finishes it
        ray.kill(gp)
    except ValueError:
        pass
    try:
        ctrl = _controller(create=False)
    except ValueError:
        return
    try:
        ray.get(ctrl.shutdown.remote())
    except Exception:
        pass  # controller dying; kill below finishes it
    try:
        ray.kill(ctrl)
    except Exception:
        pass  # already dead
