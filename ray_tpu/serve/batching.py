"""@serve.batch — transparent request batching.

Reference parity: serve/batching.py (@serve.batch, _BatchQueue): single
calls enqueue; a background coroutine drains up to ``max_batch_size``
items (waiting at most ``batch_wait_timeout_s`` after the first), invokes
the wrapped function ONCE with the list, and fans results back out to the
callers' futures. The wrapped function must take a list and return a list
of equal length (or raise — the exception fans out to every caller in the
batch).

TPU relevance: batching is how a serving replica feeds the MXU efficiently
— one forward over a [B, ...] batch instead of B tiny forwards.
"""
from __future__ import annotations

import asyncio
import functools
from typing import Any, Callable, Optional


class _BatchQueue:
    def __init__(self, fn: Callable, max_batch_size: int,
                 batch_wait_timeout_s: float, fn_name: str = ""):
        self.fn = fn
        self.fn_name = fn_name
        self.max_batch_size = max_batch_size
        self.timeout_s = batch_wait_timeout_s
        self.queue: asyncio.Queue = asyncio.Queue()
        self._worker: Optional[asyncio.Task] = None

    def ensure_worker(self):
        if self._worker is None or self._worker.done():
            self._worker = asyncio.get_event_loop().create_task(
                self._loop())

    async def _loop(self):
        while True:
            item = await self.queue.get()
            batch = [item]
            if self.timeout_s > 0:
                deadline = asyncio.get_event_loop().time() + self.timeout_s
                while len(batch) < self.max_batch_size:
                    remain = deadline - asyncio.get_event_loop().time()
                    if remain <= 0:
                        break
                    try:
                        batch.append(await asyncio.wait_for(
                            self.queue.get(), remain))
                    except asyncio.TimeoutError:
                        break
            else:
                while len(batch) < self.max_batch_size:
                    try:
                        batch.append(self.queue.get_nowait())
                    except asyncio.QueueEmpty:
                        break
            args = [a for a, _, _ in batch]
            futs = [f for _, f, _ in batch]
            try:
                from . import metrics as sm
                sm.batch_size().observe(len(batch),
                                        tags={"fn": self.fn_name})
                # FIFO queue: batch[0] is the oldest item
                sm.batch_wait().observe(
                    max(asyncio.get_event_loop().time() - batch[0][2], 0.0),
                    tags={"fn": self.fn_name})
            except Exception:
                pass  # telemetry must never fail the batch
            try:
                results = await self.fn(args)
                if results is None or len(results) != len(args):
                    raise TypeError(
                        f"@serve.batch function must return a list of "
                        f"len {len(args)}, got "
                        f"{type(results).__name__}")
                for f, r in zip(futs, results):
                    if not f.done():
                        f.set_result(r)
            except BaseException as e:  # noqa: BLE001 — fan the error out
                for f in futs:
                    if not f.done():
                        f.set_exception(e)


def batch(_fn=None, *, max_batch_size: int = 10,
          batch_wait_timeout_s: float = 0.01):
    """Decorate an async function/method taking a LIST of requests.

        @serve.batch(max_batch_size=32, batch_wait_timeout_s=0.005)
        async def forward(self, inputs: list) -> list: ...

    Callers invoke it with a SINGLE request and await a single result.
    """
    def wrap(fn):
        if not asyncio.iscoroutinefunction(fn):
            raise TypeError("@serve.batch requires an async def function")
        queues: dict[int, _BatchQueue] = {}  # per bound instance

        @functools.wraps(fn)
        async def wrapper(*args) -> Any:
            if len(args) == 2:        # bound method: (self, request)
                owner, request = args
                key = id(owner)
                call = functools.partial(fn, owner)
            elif len(args) == 1:      # free function: (request,)
                owner, request = None, args[0]
                key = 0
                call = fn
            else:
                raise TypeError(
                    "@serve.batch functions take exactly one request arg")
            q = queues.get(key)
            if q is None:
                q = queues[key] = _BatchQueue(
                    call, max_batch_size, batch_wait_timeout_s,
                    fn_name=getattr(fn, "__qualname__", fn.__name__))
            q.ensure_worker()
            loop = asyncio.get_event_loop()
            fut: asyncio.Future = loop.create_future()
            q.queue.put_nowait((request, fut, loop.time()))
            return await fut

        wrapper._rtpu_batch_queues = queues  # introspection/tests
        return wrapper

    if _fn is not None:
        return wrap(_fn)
    return wrap
