"""Per-request serve context.

Reference parity: serve/context.py — _serve_request_context contextvar
carrying request id / multiplexed model id into user code.
"""
from __future__ import annotations

import contextvars
import dataclasses


@dataclasses.dataclass
class RequestContext:
    request_id: str = ""
    multiplexed_model_id: str = ""
    app_name: str = ""
    deployment: str = ""


_request_context: contextvars.ContextVar[RequestContext] = \
    contextvars.ContextVar("rtpu_serve_request_context",
                           default=RequestContext())


def get_request_context() -> RequestContext:
    return _request_context.get()


def set_request_context(**fields) -> contextvars.Token:
    return _request_context.set(RequestContext(**fields))


def reset_request_context(token: contextvars.Token) -> None:
    _request_context.reset(token)


def get_multiplexed_model_id() -> str:
    """Inside a deployment: the model id the current request was routed
    with (reference: serve.get_multiplexed_model_id)."""
    return _request_context.get().multiplexed_model_id
