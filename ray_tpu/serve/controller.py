"""ServeController + ReplicaActor: the reconciling control loop.

Reference parity: serve/_private/controller.py:88 (singleton controller,
deploy_application :783), deployment_state.py (replica state machine),
replica.py:945 (ReplicaActor), autoscaling_state.py + autoscaling_policy.py
:12 (_calculate_desired_num_replicas over queue metrics).

The controller is an async actor: `deploy_application` materializes replica
actors for every deployment spec; a reconcile task keeps replica counts at
target, replaces dead replicas, and autoscales queue-length-based between
min/max replicas.
"""
from __future__ import annotations

import asyncio
import math
import time
from typing import Any, Optional

from ..core import flight as _fl
from .api import AutoscalingConfig, DeploymentSpec


class ReplicaActor:
    """Hosts one replica of a deployment's callable (reference:
    replica.py:945 — async execution with max_ongoing_requests enforced by
    actor max_concurrency; here requests are counted for autoscaling
    stats)."""

    def __init__(self, spec_blob: bytes):
        import cloudpickle
        spec, handle_args, handle_kwargs = cloudpickle.loads(spec_blob)
        fc = spec.func_or_class
        self._ongoing = 0
        self._total = 0
        self._streams: dict[int, Any] = {}
        self._pending: dict[int, Any] = {}  # parked __anext__ futures
        self._stream_seq = 0
        if isinstance(fc, type):
            self._callable = fc(*handle_args, **handle_kwargs)
        else:
            if handle_args or handle_kwargs:
                raise TypeError("function deployments take no init args")
            self._callable = fc
        # user_config is applied by the controller through the async
        # reconfigure() path right after creation (supports async def
        # reconfigure too; a sync __init__ could not await it)

    async def _invoke(self, method: str, args: tuple, kwargs: dict,
                      context: Optional[dict]):
        from .context import reset_request_context, set_request_context
        token = set_request_context(**(context or {}))
        try:
            # "__call__" covers both function deployments and class __call__
            target = (self._callable if method == "__call__"
                      else getattr(self._callable, method))
            if asyncio.iscoroutinefunction(getattr(target, "__call__",
                                                   target)) or \
                    asyncio.iscoroutinefunction(target):
                out = target(*args, **kwargs)
            else:
                # sync callables must not block the replica's event loop
                # (reference: replica.py runs sync user code in a thread);
                # the contextvar copies into the executor thread via
                # a captured Context
                import contextvars
                ctx = contextvars.copy_context()
                loop = asyncio.get_event_loop()
                out = await loop.run_in_executor(
                    None, lambda: ctx.run(target, *args, **kwargs))
            # inspect.iscoroutine, NOT asyncio.iscoroutine: on py<3.12 the
            # asyncio one also accepts PLAIN GENERATORS (legacy @coroutine
            # support), and awaiting a sync-generator deployment's return
            # value raises TypeError instead of streaming it
            import inspect
            if inspect.iscoroutine(out):
                out = await out
            return out
        finally:
            reset_request_context(token)

    @staticmethod
    def _observe(context: Optional[dict], t0: float, outcome: str):
        """Replica-side telemetry (reference: serve/_private replica
        processing-latency + request counters). Never raises."""
        import time
        try:
            from . import metrics as sm
            tags = {"app": (context or {}).get("app_name", ""),
                    "deployment": (context or {}).get("deployment", "")}
            sm.replica_latency().observe(time.perf_counter() - t0,
                                         tags=tags)
            sm.replica_requests().inc(
                1.0, tags={**tags, "outcome": outcome})
        except Exception:
            pass  # telemetry must never fail a request

    async def handle_request(self, method: str, args: tuple, kwargs: dict,
                             context: Optional[dict] = None):
        import time
        self._ongoing += 1
        self._total += 1
        req = self._total
        t0 = time.perf_counter()
        outcome = "ok"
        _fl.evt(_fl.SRV_REQ_BEGIN, req)
        try:
            return await self._invoke(method, args, kwargs, context)
        except BaseException:
            outcome = "error"
            raise
        finally:
            self._ongoing -= 1
            _fl.evt(_fl.SRV_REQ_END, req, int(outcome == "ok"))
            self._observe(context, t0, outcome)

    # -- streaming responses (reference: replica.py handles generator
    # results via ray streaming generators; here the replica retains the
    # generator and the caller drains it in batched stream_next calls) ----

    async def handle_request_streaming(self, method: str, args: tuple,
                                       kwargs: dict,
                                       context: Optional[dict] = None,
                                       chan: Optional[dict] = None):
        import time
        self._ongoing += 1
        self._total += 1
        req = self._total
        t0 = time.perf_counter()
        _fl.evt(_fl.SRV_REQ_BEGIN, req)
        try:
            out = await self._invoke(method, args, kwargs, context)
            if not hasattr(out, "__anext__") and \
                    not hasattr(out, "__next__"):
                raise TypeError(
                    f"streaming call to {method!r} returned "
                    f"{type(out).__name__}, not a generator")
        except BaseException:
            self._ongoing -= 1
            _fl.evt(_fl.SRV_REQ_END, req, 0)
            self._observe(context, t0, "error")
            raise
        # latency here covers the call that produced the generator; the
        # drain is accounted at the proxy's e2e histogram
        _fl.evt(_fl.SRV_REQ_END, req, 1)
        self._observe(context, t0, "ok")
        self._stream_seq += 1
        sid = self._stream_seq
        self._streams[sid] = out
        if chan is not None and self._start_stream_channel(sid, out, chan,
                                                           context):
            # static decode plan accepted: the caller reads items from
            # the ring channel; no stream_next dispatches will follow
            return {"chan": sid}
        return sid

    def _start_stream_channel(self, sid: int, gen, chan: dict,
                              context: Optional[dict]) -> bool:
        """Serve this stream over a sealed ring channel: a drain thread
        pulls the generator and seals each item into shm; the handle
        reads them directly — zero control-plane dispatches per item
        (reference analog: compiling the decode step into a static plan
        instead of one stream_next RPC per chunk). Returns False when
        this replica can't share a store with the caller (own-store
        node) so the handle falls back to the poll transport."""
        import os
        if os.environ.get("RTPU_OWN_STORE") == "1":
            return False
        from ..core import runtime as rt_mod
        from ..core.ids import ObjectID
        from ..dag.channel import (ChannelClosed, RingWriter,
                                   drain_stale_slots)
        rt = rt_mod.get_runtime_if_exists()
        store = getattr(rt, "store", None)
        if store is None:
            return False
        import asyncio as _aio
        import threading
        loop = _aio.get_running_loop()
        stop_oid = ObjectID(chan["stop"])
        writer = RingWriter(store, chan["base"], stop_oid,
                            int(chan["ring"]))
        is_async = hasattr(gen, "__anext__")

        def drain():
            # items are counted by the CONSUMING handle (symmetric with
            # the poll transport) — no replica-side inc, or the series
            # would double
            _fl.evt(_fl.SRV_DRAIN_BEGIN, sid)
            try:
                while True:
                    if writer.closed():
                        break  # consumer cancelled: stop pulling
                    try:
                        if is_async:
                            item = _aio.run_coroutine_threadsafe(
                                gen.__anext__(), loop).result()
                        else:
                            item = next(gen)
                    except (StopIteration, StopAsyncIteration):
                        writer.write(("e", None))
                        break
                    except BaseException as e:  # noqa: BLE001 — shipped
                        writer.write(("x", e))
                        break
                    writer.write(("i", item))
            except ChannelClosed:
                pass  # consumer cancelled mid-write
            except Exception:
                import traceback
                traceback.print_exc()
            finally:
                _fl.evt(_fl.SRV_DRAIN_END, sid, writer.seq)
                try:
                    # cancelled streams leave the stop flag and a ring
                    # window of unread slots behind: sweep them
                    if store.contains(stop_oid):
                        drain_stale_slots(
                            store,
                            [chan["base"], writer.ack_base],
                            writer.seq - int(chan["ring"]), writer.seq)
                        store.delete(stop_oid)
                except Exception:
                    pass  # store closing: slots die with it
                loop.call_soon_threadsafe(self._drop_stream, sid)

        threading.Thread(target=drain, daemon=True,
                         name=f"serve-stream-chan-{sid}").start()
        return True

    async def stream_next(self, sid: int, max_items: int = 8):
        """(items, done): blocks for the FIRST item only, then takes up to
        max_items - 1 more that are already available — a slow generator
        streams item-by-item (low latency), a fast one ships batches (the
        round-trip amortization). The possibly-unfinished __anext__ is
        parked in _pending for the next call, never cancelled (cancelling
        mid-__anext__ would corrupt the generator)."""
        gen = self._streams.get(sid)
        if gen is None:
            return [], True
        items: list = []
        done = False
        try:
            if hasattr(gen, "__anext__"):
                pending = self._pending.pop(sid, None)
                while len(items) < max_items:
                    if pending is None:
                        pending = asyncio.ensure_future(gen.__anext__())
                    try:
                        if items:
                            # past the 1st item take only near-ready ones:
                            # a tiny positive timeout lets a ready
                            # __anext__ actually run (timeout=0 would just
                            # check done() on the never-scheduled task and
                            # defeat the batching)
                            item = await asyncio.wait_for(
                                asyncio.shield(pending), 0.002)
                        else:
                            item = await pending
                    except asyncio.TimeoutError:
                        self._pending[sid] = pending
                        return items, False
                    except StopAsyncIteration:
                        done = True
                        break
                    pending = None
                    items.append(item)
                if pending is not None:
                    self._pending[sid] = pending
            else:
                # sync generator: one item per call — next() can block
                # arbitrarily in a pinned executor thread, so favor
                # latency; sync deployments wanting throughput should
                # yield pre-batched chunks
                loop = asyncio.get_event_loop()
                def pull():
                    try:
                        return [next(gen)], False
                    except StopIteration:
                        return [], True
                items, done = await loop.run_in_executor(None, pull)
        except BaseException:
            self._drop_stream(sid)
            raise
        if done:
            self._drop_stream(sid)
        return items, done

    def _drop_stream(self, sid: int):
        if self._streams.pop(sid, None) is not None:
            self._ongoing -= 1
        pending = self._pending.pop(sid, None)
        if pending is not None:
            pending.cancel()

    async def stream_cancel(self, sid: int):
        self._drop_stream(sid)

    async def stats(self) -> dict:
        return {"ongoing": self._ongoing, "total": self._total}

    async def reconfigure(self, user_config: Any):
        if hasattr(self._callable, "reconfigure"):
            res = self._callable.reconfigure(user_config)
            if asyncio.iscoroutine(res):
                await res

    async def set_self(self, handle):
        """Inject this replica's OWN actor handle (the controller calls
        this right after creation, passing the handle back in). The
        prefix-directory client publishes it as the owner of every page
        hash this replica registers (llm/serving.py
        set_replica_handle)."""
        if hasattr(self._callable, "set_replica_handle"):
            self._callable.set_replica_handle(handle)

    async def health_check(self) -> bool:
        if hasattr(self._callable, "check_health"):
            self._callable.check_health()
        return True


class _DeploymentState:
    def __init__(self, spec: DeploymentSpec, app: str, version_counter):
        self.spec = spec
        self.app = app
        self.replicas: list = []          # actor handles
        self.target = spec.num_replicas
        if spec.autoscaling_config:
            self.target = spec.autoscaling_config.min_replicas
        # versions are controller-global monotonic so a redeploy can never
        # collide with a cached handle's last-seen version
        self._vc = version_counter
        self.version = next(version_counter)
        self._last_scale_up = 0.0
        self._last_scale_down = 0.0
        # cached TSDB autoscale signals (obs/scraper.py), refreshed at
        # most once per scrape period per deployment; the remote fetch
        # runs OFF the controller's event loop (_sig_fetching guards
        # one in-flight refresh)
        self._sig = None
        self._sig_ts = 0.0
        self._sig_fetching = False
        # long-poll wakeup (reference: _private/long_poll.py:222 — waiters
        # park on the event; bump() swaps in a fresh one)
        self.changed = asyncio.Event()

    def bump(self):
        self.version = next(self._vc)
        old, self.changed = self.changed, asyncio.Event()
        old.set()


class ServeController:
    """Singleton control plane (reference: controller.py:88)."""

    def __init__(self):
        import itertools
        self._apps: dict[str, dict[str, _DeploymentState]] = {}
        self._ingress: dict[str, str] = {}
        # app -> URL route prefix (reference: route_prefix in serve.run)
        self._routes: dict[str, str] = {}
        # proxy fleet (serve/frontdoor): [{"actor", "port", "index"}],
        # controller-managed like replicas — dead proxies are replaced
        # on their port by the reconcile loop
        self._proxies: list[dict] = []
        self._http_port = None
        self._reconcile_task = None
        self._shutdown = False
        self._version_counter = itertools.count(1)
        self._ticks = 0
        # app -> prefill-replica keys already wired to a decode KV ring
        # (MPMD PD pairing over DeploymentSpec.role)
        self._pd_paired: dict[str, set] = {}
        # last published route-table snapshot (minus the version field):
        # republished through frontdoor/routetable.py whenever topology
        # drifts from it
        self._pub_state = None

    # -- deploy ------------------------------------------------------------

    async def deploy_application(self, app_name: str, specs_blob: bytes,
                                 http_port: Optional[int] = None,
                                 num_proxies: Optional[int] = None) -> None:
        import cloudpickle
        specs, ingress, route_prefix = cloudpickle.loads(specs_blob)
        if app_name in self._apps:  # redeploy: tear down the old replicas
            await self.delete_application(app_name)
        states: dict[str, _DeploymentState] = {}
        for spec in specs:
            states[spec.name] = _DeploymentState(spec, app_name,
                                                 self._version_counter)
        self._apps[app_name] = states
        self._ingress[app_name] = ingress
        # "/" (the default) means app-name addressing (/<app>/...); only
        # EXPLICIT prefixes join the longest-match route table
        if route_prefix and route_prefix != "/":
            if not route_prefix.startswith("/"):
                raise ValueError(
                    f"route_prefix must start with '/', got "
                    f"{route_prefix!r}")
            owner = next((a for a, p in self._routes.items()
                          if p == route_prefix and a != app_name), None)
            if owner is not None:
                raise ValueError(
                    f"route_prefix {route_prefix!r} is already used by "
                    f"app {owner!r}")
            self._routes[app_name] = route_prefix
        else:
            self._routes.pop(app_name, None)
        for st in states.values():
            await self._scale_to_target(st)
        await self._pair_pd_roles(app_name)
        if http_port is not None:
            await self._ensure_proxies(http_port, num_proxies)
        self._publish_routes()
        if self._reconcile_task is None:
            self._reconcile_task = asyncio.get_event_loop().create_task(
                self._reconcile_loop())

    def _replica_blob(self, spec: DeploymentSpec) -> bytes:
        import cloudpickle
        from .api import BoundDeployment
        from .handle import DeploymentHandle
        # bound children become live handles (model composition)
        def conv(a):
            if isinstance(a, BoundDeployment):
                import ray_tpu
                ctrl = ray_tpu.get_actor("rtpu:serve:controller")
                return DeploymentHandle(a.spec.name, spec_app(a), ctrl)
            return a

        def spec_app(bound):  # child deployments live in the same app
            for app, states in self._apps.items():
                if bound.spec.name in states:
                    return app
            return "default"

        args = tuple(conv(a) for a in spec.init_args)
        kwargs = {k: conv(v) for k, v in spec.init_kwargs.items()}
        return cloudpickle.dumps((spec, args, kwargs))

    async def _start_replica(self, st: _DeploymentState):
        import ray_tpu
        cls = ray_tpu.remote(ReplicaActor)
        opts = dict(st.spec.ray_actor_options)
        actor = cls.options(
            num_cpus=opts.get("num_cpus", 0.1),
            num_tpus=opts.get("num_tpus", 0),
            resources=opts.get("resources"),
            max_concurrency=max(st.spec.max_ongoing_requests, 1),
        ).remote(self._replica_blob(st.spec))
        if st.spec.user_config is not None:
            # configured BEFORE the replica enters routing (async-aware)
            await actor.reconfigure.remote(st.spec.user_config)
        # hand the replica its own handle (prefix-directory ownership);
        # fire-and-forget: replicas without the hook ignore it
        try:
            actor.set_self.remote(actor)
        except Exception:
            pass  # replica already dying; reconcile replaces it
        st.replicas.append(actor)
        st.bump()

    async def _pair_pd_roles(self, app: str) -> None:
        """MPMD prefill/decode pairing: for an app carrying
        role="prefill" and role="decode" deployment groups, give every
        prefill replica a sealed KV ring into a decode peer (round-robin
        i mod n_decode — llm/pd_disagg.py open_kv_channel /
        connect_kv_channel). Steady-state KV handoff between the pair
        then costs zero control dispatches. Idempotent per prefill
        replica; a replacement replica gets wired on the next reconcile
        tick. Decode replicas may consume several rings (one per paired
        prefill producer)."""
        states = self._apps.get(app, {})
        pre = [r for st in states.values()
               if getattr(st.spec, "role", None) == "prefill"
               for r in st.replicas]
        dec = [r for st in states.values()
               if getattr(st.spec, "role", None) == "decode"
               for r in st.replicas]
        if not pre or not dec:
            return
        paired = self._pd_paired.setdefault(app, set())
        for i, p in enumerate(pre):
            key = getattr(p, "_actor_id", None) or id(p)
            if key in paired:
                continue
            d = dec[i % len(dec)]
            try:
                spec = await d.handle_request.remote(
                    "open_kv_channel", (4, None), {}, None)
                if not spec:
                    continue  # no shared store: actor-call handoff stays
                if await p.handle_request.remote(
                        "connect_kv_channel", (spec,), {}, None):
                    paired.add(key)
            except Exception:
                pass  # replica dying; reconcile replaces then re-pairs

    async def _scale_to_target(self, st: _DeploymentState):
        while len(st.replicas) < st.target:
            await self._start_replica(st)
        while len(st.replicas) > st.target:
            import ray_tpu
            victim = st.replicas.pop()
            st.bump()
            try:
                ray_tpu.kill(victim)
            except Exception:
                pass  # already dead

    # -- routing state -----------------------------------------------------

    async def get_replicas(self, app: str, deployment: str):
        st = self._apps.get(app, {}).get(deployment)
        if st is None:
            raise ValueError(f"no deployment {deployment!r} in app {app!r}")
        return st.version, list(st.replicas)

    async def listen_for_change(self, app: str, deployment: str,
                                known_version: int,
                                timeout_s: float = 30.0):
        """Long-poll: return (version, replicas) as soon as the replica set
        differs from the caller's known_version, else after timeout_s with
        the unchanged state (reference: LongPollHost.listen_for_change,
        _private/long_poll.py:222). Many handles parking here cost only an
        asyncio waiter each — no controller work per poll tick."""
        st = self._apps.get(app, {}).get(deployment)
        if st is None:
            raise ValueError(f"no deployment {deployment!r} in app {app!r}")
        if st.version == known_version:
            try:
                await asyncio.wait_for(st.changed.wait(), timeout_s)
            except asyncio.TimeoutError:
                pass
            # re-resolve: a redeploy may have replaced the state object
            st = self._apps.get(app, {}).get(deployment)
            if st is None:
                raise ValueError(
                    f"deployment {deployment!r} was deleted from {app!r}")
        return st.version, list(st.replicas)

    async def update_user_config(self, app: str, deployment: str,
                                 user_config) -> None:
        """Lightweight update: push reconfigure() to every live replica
        concurrently, then persist for future replicas. Application
        errors SURFACE (and the old config stays for future replicas);
        only dying-replica errors are ignored — the reconcile loop
        replaces those."""
        import dataclasses

        from ..exceptions import ActorDiedError, WorkerCrashedError
        st = self._apps.get(app, {}).get(deployment)
        if st is None:
            raise ValueError(f"no deployment {deployment!r} in app {app!r}")
        refs = [r.reconfigure.remote(user_config) for r in st.replicas]
        app_error = None
        for ref in refs:
            try:
                await asyncio.wait_for(ref, timeout=30)
            except (ActorDiedError, WorkerCrashedError,
                    asyncio.TimeoutError):
                continue  # dying replica: reconcile will replace it
            except Exception as e:  # noqa: BLE001 — user reconfigure bug
                app_error = e
        if app_error is not None:
            raise RuntimeError(
                f"reconfigure({user_config!r}) raised on a replica; "
                f"config NOT persisted") from app_error
        st.spec = dataclasses.replace(st.spec, user_config=user_config)

    async def set_target(self, app: str, deployment: str, n: int) -> None:
        """Manually retarget a deployment's replica count (ops escape
        hatch; autoscaling keeps adjusting around it when configured)."""
        st = self._apps.get(app, {}).get(deployment)
        if st is None:
            raise ValueError(f"no deployment {deployment!r} in app {app!r}")
        st.target = max(0, int(n))
        await self._scale_to_target(st)

    async def get_routes(self) -> dict:
        """{route_prefix: app} for the proxy's longest-prefix matching."""
        return {v: k for k, v in self._routes.items()}

    async def get_proxies(self) -> list:
        """The live proxy fleet with actor handles (ops/chaos tooling)."""
        return [{"actor": p["actor"], "port": p["port"],
                 "index": p["index"]} for p in self._proxies]

    async def get_ingress(self, app: str) -> str:
        if app not in self._ingress:
            raise ValueError(f"no application {app!r}")
        return self._ingress[app]

    async def status(self) -> dict:
        out: dict = {"applications": {},
                     "proxies": [{"index": p["index"], "port": p["port"]}
                                 for p in self._proxies],
                     "http_port": self._http_port}
        for app, states in self._apps.items():
            out["applications"][app] = {
                "ingress": self._ingress.get(app),
                "deployments": {
                    name: {"target_replicas": st.target,
                           "running_replicas": len(st.replicas),
                           "autoscaling": st.spec.autoscaling_config
                           is not None}
                    for name, st in states.items()},
            }
        return out

    async def delete_application(self, app: str) -> None:
        self._routes.pop(app, None)
        import ray_tpu
        states = self._apps.pop(app, None)
        self._ingress.pop(app, None)
        self._publish_routes()
        if not states:
            return
        for st in states.values():
            for r in st.replicas:
                try:
                    ray_tpu.kill(r)
                except Exception:
                    pass  # already dead
            # gauges are last-write-wins: without an explicit zero the
            # deleted deployment's queue_depth/replicas series hold their
            # final value on /metrics forever
            try:
                from . import metrics as sm
                tags = {"app": st.app, "deployment": st.spec.name}
                sm.queue_depth().set(0.0, tags=tags)
                sm.replica_count().set(0.0, tags=tags)
            except Exception:
                pass  # metrics store gone mid-shutdown

    async def shutdown(self) -> None:
        self._shutdown = True
        for app in list(self._apps):
            await self.delete_application(app)
        import ray_tpu
        for rec in self._proxies:
            try:
                ray_tpu.kill(rec["actor"])
            except Exception:
                pass  # already dead
        self._proxies.clear()

    # -- reconcile + autoscaling ------------------------------------------

    async def _reconcile_loop(self):
        import ray_tpu
        while not self._shutdown:
            await asyncio.sleep(0.25)
            self._ticks += 1
            deep = self._ticks % 4 == 0  # user health_check every ~1s
            for states in list(self._apps.values()):
                for st in list(states.values()):
                    alive = []
                    ongoing = 0
                    for r in st.replicas:
                        try:
                            s = await r.stats.remote()
                            if deep:
                                await r.health_check.remote()
                            ongoing += s["ongoing"]
                            alive.append(r)
                        except Exception:
                            # dead or failing health: drop from routing and
                            # kill so _scale_to_target replaces it
                            st.bump()
                            try:
                                ray_tpu.kill(r)
                            except Exception:
                                pass  # already dead
                    st.replicas = alive
                    # membership check right before the write (no await in
                    # between, and the controller is single-event-loop):
                    # delete_application may have zeroed these gauges while
                    # this tick awaited replica stats, and a write from the
                    # pre-delete snapshot would resurrect the series at a
                    # stale value forever
                    if self._apps.get(st.app, {}).get(st.spec.name) is st:
                        try:
                            from . import metrics as sm
                            tags = {"app": st.app,
                                    "deployment": st.spec.name}
                            sm.queue_depth().set(ongoing, tags=tags)
                            sm.replica_count().set(len(st.replicas),
                                                   tags=tags)
                        except Exception:
                            pass  # telemetry is best-effort here
                    cfg = st.spec.autoscaling_config
                    if cfg is not None:
                        self._autoscale(st, cfg, ongoing)
                    await self._scale_to_target(st)
            if deep:
                # replacement replicas of role="prefill" groups need a
                # fresh KV ring to a decode peer; no-op once paired
                for app in list(self._apps):
                    await self._pair_pd_roles(app)
            if deep and self._proxies:
                await self._check_proxies()
            # topology drift (replica counts, proxy replacements) reaches
            # the shared route table here; no-op when nothing changed
            self._publish_routes()

    def _autoscale(self, st: _DeploymentState, cfg: AutoscalingConfig,
                   total_ongoing: int):
        """(reference: autoscaling_policy.py:12
        _calculate_desired_num_replicas) — the ongoing-requests rule,
        composed with the TSDB signals (shed rate, TTFT/e2e burn rate,
        TTFT slope, per-tenant admission backlog) so a deployment scales
        OUT before the first 429 fires. cfg.serve_autoscale_signals=off
        reproduces the legacy queue-depth-only decisions exactly: the
        signal path then contributes nothing to ``desired``."""
        now = time.monotonic()
        desired = math.ceil(total_ongoing / max(cfg.target_ongoing_requests,
                                                1e-9))
        desired = max(cfg.min_replicas, min(cfg.max_replicas, desired))
        sig_reason = None
        sig = self._signals_for(st)
        if sig is not None and sig.get("scale_out"):
            # step out by one replica per decision: the signals say
            # "capacity is short", not by how much — the burn windows
            # re-fire next period if one replica wasn't enough. A
            # firing signal also vetoes any concurrent scale-DOWN
            # (including at max_replicas, where stepped == target and
            # the down branch's desired < target can no longer hold —
            # an overloaded deployment at max must not oscillate)
            legacy = desired
            stepped = min(cfg.max_replicas, st.target + 1)
            desired = max(desired, stepped)
            if desired > st.target and stepped > legacy:
                sig_reason = (sig.get("reasons") or ["signal"])[0]
        direction = None
        if desired > st.target and \
                now - self._last(st, "up") >= cfg.upscale_delay_s:
            st.target = desired
            st._last_scale_up = now
            direction = "up"
        elif desired < st.target and \
                now - self._last(st, "down") >= cfg.downscale_delay_s:
            st.target = desired
            st._last_scale_down = now
            direction = "down"
        if direction is not None:
            try:
                from . import metrics as sm
                sm.autoscale_decisions().inc(1.0, tags={
                    "app": st.app, "deployment": st.spec.name,
                    "direction": direction})
                if direction == "up" and sig_reason is not None:
                    sm.autoscale_signal().inc(1.0, tags={
                        "app": st.app, "deployment": st.spec.name,
                        "reason": sig_reason})
            except Exception:
                pass  # telemetry is best-effort here

    def _signals_for(self, st: _DeploymentState) -> Optional[dict]:
        """The deployment's cached TSDB scale-out signals; None when
        signals are off, the TSDB is disabled, or the head is
        unreachable — every failure mode falls back to the legacy
        ongoing-requests rule. The remote fetch blocks up to the rpc
        timeout when the head is wedged, so it runs in an executor
        thread and THIS call returns the previous cache immediately —
        the reconcile loop (replica/proxy respawn) must never stall
        behind a slow head."""
        from ..core.config import cfg
        if str(cfg.serve_autoscale_signals).lower() in ("off", "0",
                                                        "false"):
            return None
        now = time.monotonic()
        refresh = max(0.25, min(float(cfg.tsdb_scrape_s), 15.0))
        if (not st._sig_fetching
                and (not st._sig_ts or now - st._sig_ts >= refresh)):
            st._sig_fetching = True
            st._sig_ts = now

            def fetch():
                sig = None
                try:
                    from ..core import runtime as rt_mod
                    rt = rt_mod.get_runtime_if_exists()
                    if isinstance(rt, rt_mod.Runtime):
                        sig = rt.obs_signals(st.app, st.spec.name)
                    elif rt is not None:
                        sig = rt._rpc("obs_signals", st.app,
                                      st.spec.name)
                except Exception:
                    sig = None  # TSDB off / head mid-restart: legacy
                st._sig = sig
                st._sig_fetching = False

            try:
                asyncio.get_running_loop().run_in_executor(None, fetch)
            except RuntimeError:
                # no running loop (unit tests drive _autoscale
                # directly): the head-local path is lock-light and
                # sub-ms, safe to run inline
                fetch()
        return st._sig

    @staticmethod
    def _last(st: _DeploymentState, which: str) -> float:
        return st._last_scale_up if which == "up" else st._last_scale_down

    # -- HTTP proxy fleet (serve/frontdoor) -------------------------------

    async def _spawn_proxy(self, port: int, index: int) -> dict:
        import ray_tpu
        from .proxy import ProxyActor
        cls = ray_tpu.remote(ProxyActor)
        actor = cls.options(max_concurrency=512).remote(port, index)
        await actor.start.remote()
        return {"actor": actor, "port": port, "index": index}

    async def _ensure_proxies(self, port: int,
                              num_proxies: Optional[int] = None):
        """Scale the proxy fleet to N actors on ports port..port+N-1
        (cfg.serve_num_proxies when unspecified). Idempotent; a second
        app deploy reuses the running fleet. Excess proxies (a deploy
        shrinking the fleet) drain: killed after the route table stops
        listing them."""
        from ..core.config import cfg
        if num_proxies is None:
            num_proxies = cfg.serve_num_proxies
        n = max(1, int(num_proxies))
        self._http_port = port
        import ray_tpu
        while len(self._proxies) > n:
            victim = self._proxies.pop()
            self._publish_routes()
            try:
                await victim["actor"].stop.remote()
                ray_tpu.kill(victim["actor"])
            except Exception:
                pass  # already dead
        for i in range(len(self._proxies), n):
            self._proxies.append(await self._spawn_proxy(port + i, i))
        self._publish_routes()

    async def _check_proxies(self):
        """Reconcile tick: replace dead proxies on their port (same
        controller-managed contract as replicas)."""
        import ray_tpu
        for rec in list(self._proxies):
            try:
                await rec["actor"].ping.remote()
            except Exception:
                try:
                    ray_tpu.kill(rec["actor"])
                except Exception:
                    pass  # already dead
                try:
                    fresh = await self._spawn_proxy(rec["port"],
                                                    rec["index"])
                except Exception:
                    # port still lingering in TIME_WAIT or node down:
                    # retry next tick rather than losing the slot
                    continue
                self._proxies[self._proxies.index(rec)] = fresh
                self._publish_routes()
        try:
            from . import metrics as sm
            sm.proxy_count().set(float(len(self._proxies)))
        except Exception:
            pass  # telemetry is best-effort here

    # -- shared route table (frontdoor/routetable.py) ---------------------

    def _publish_routes(self):
        """Publish the route-table snapshot to the head's shared
        directory when anything drifted: routes, ingress, per-deployment
        capacity (replicas x max_ongoing — the admission budgets), or
        the proxy fleet. One async frame; proxies TTL-refresh from it
        instead of calling this controller per request."""
        state = {
            "routes": {v: k for k, v in self._routes.items()},
            "ingress": dict(self._ingress),
            "capacity": {
                f"{app}/{name}": [len(st.replicas) or st.target,
                                  st.spec.max_ongoing_requests]
                for app, states in self._apps.items()
                for name, st in states.items()},
            "n_proxies": max(1, len(self._proxies)),
            "proxies": [{"index": p["index"], "port": p["port"]}
                        for p in self._proxies],
        }
        if state == self._pub_state:
            return
        self._pub_state = state
        try:
            from .frontdoor import routetable
            routetable.publish_snapshot(
                {**state, "v": next(self._version_counter)})
        except Exception:
            pass  # no cluster directory (local test): proxies fall back
