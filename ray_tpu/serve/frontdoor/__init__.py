"""Serve front door: the scale-out data plane in front of the replicas.

Three pieces (ROADMAP item 3; reference analog: Ray Serve's proxy tier):

- :mod:`admission` — SLO-aware admission control at every proxy.
  Per-deployment budgets derive from live replica capacity (replicas x
  max_ongoing_requests, split across proxies); past the budget requests
  queue with bounded depth and deadline, then shed as HTTP 429 +
  Retry-After — backpressure to the socket, never a timeout-as-500.
- :mod:`routetable` — the shared route table. The controller publishes
  one snapshot (routes, ingress map, capacity, proxy fleet) into the
  head's shared directory service (core/directory.py); every proxy
  refreshes from it on a short TTL, so ingress scales horizontally
  without per-request controller round-trips.
- :mod:`prefix` — the cluster-wide prefix-cache directory. Paged-engine
  replicas publish their chained page hashes; at admission a replica
  that lacks a prefix locally imports the KV pages from whichever
  replica warmed them, over the object store (extending the PD-disagg
  import_prefill contract). Directory entries are hints: on any failure
  the request prefills cold and the hint is dropped.
"""
from .admission import AdmissionController, ShedError           # noqa: F401
from .prefix import PrefixDirectoryClient                       # noqa: F401
from .routetable import (ROUTES_DIR, fetch_snapshot,            # noqa: F401
                         publish_snapshot)
