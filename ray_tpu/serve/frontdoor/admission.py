"""SLO-aware admission control + load shedding for the serve proxies.

Each proxy runs one :class:`AdmissionController` on its event loop. Per
deployment it holds a budget (its share of the fleet's live capacity:
``replicas x max_ongoing_requests / n_proxies``), a bounded FIFO queue
for arrivals past the budget, and an EWMA of per-request service time.

Decision tree for an arriving request (``acquire``):

1. a slot is free -> admit immediately;
2. the queue is full -> shed (``queue_full``);
3. the *predicted* queue wait — requests ahead divided by the drain
   rate the EWMA implies — already exceeds the deadline
   (cfg.serve_admission_timeout_s) -> shed (``slo``): queueing a
   request that cannot meet its SLO only wastes its socket;
4. otherwise park; a release hands the slot to the queue head. A
   request still parked at the deadline sheds (``deadline``).

Sheds raise :class:`ShedError` carrying a Retry-After estimate (the
predicted time for the backlog to drain, clamped to [1, 60] seconds) —
the proxy turns it into ``429`` + ``Retry-After``, the gRPC proxy into
``RESOURCE_EXHAUSTED``. Backpressure therefore reaches the client
instead of collapsing the replicas, and every admitted request's queue
wait lands in rtpu_serve_admission_queue_wait_seconds.

Everything here is asyncio single-loop state — no locks; the proxy
calls it only from its event loop.
"""
from __future__ import annotations

import asyncio
import math
import time
from collections import deque
from typing import Optional

# EWMA smoothing for per-request service seconds; ~20-request memory
_EWMA_ALPHA = 0.1
# before any completion is observed, assume requests are this slow —
# optimistic enough not to shed a cold deployment on its first burst
_EWMA_SEED_S = 0.05


class ShedError(Exception):
    """Request refused by admission control; carries the retry hint."""

    def __init__(self, reason: str, retry_after_s: int, detail: str = ""):
        super().__init__(detail or f"admission shed ({reason})")
        self.reason = reason
        self.retry_after_s = retry_after_s


class _DeploymentGate:
    def __init__(self, budget: int, queue_depth: int, timeout_s: float):
        self.budget = max(1, int(budget))
        self.queue_depth = max(0, int(queue_depth))
        self.timeout_s = float(timeout_s)
        self.inflight = 0
        self._parked: deque = deque()   # FIFO of (future, enqueue_t)
        self.ewma_s = _EWMA_SEED_S

    def predicted_wait_s(self, ahead: int) -> float:
        """Seconds until `ahead` queued requests drain: the budget
        retires ~budget/ewma requests per second."""
        return ahead * self.ewma_s / self.budget

    def retry_after_s(self) -> int:
        est = self.predicted_wait_s(len(self._parked) + 1)
        return max(1, min(60, int(math.ceil(est))))


class AdmissionController:
    """Per-proxy gatekeeper. ``configure`` is idempotent and cheap — the
    proxy calls it on every route-table refresh so budgets track live
    replica capacity; gates for deployments that disappear are
    dropped."""

    def __init__(self, proxy_label: str = "proxy-0"):
        self._gates: dict[tuple, _DeploymentGate] = {}
        self._proxy = proxy_label

    # -- configuration ---------------------------------------------------

    def configure(self, app: str, deployment: str, capacity: int,
                  n_proxies: int = 1,
                  queue_depth: Optional[int] = None,
                  timeout_s: Optional[float] = None) -> None:
        from ...core.config import cfg
        budget = max(1, int(capacity) // max(1, int(n_proxies)))
        qd = cfg.serve_admission_queue_depth if queue_depth is None \
            else queue_depth
        to = cfg.serve_admission_timeout_s if timeout_s is None \
            else timeout_s
        g = self._gates.get((app, deployment))
        if g is None:
            self._gates[(app, deployment)] = _DeploymentGate(budget, qd, to)
        else:
            g.budget = max(1, int(budget))
            g.queue_depth = max(0, int(qd))
            g.timeout_s = float(to)

    def prune(self, live: set) -> None:
        """Drop gates for (app, deployment) pairs no longer deployed.
        Parked waiters of a pruned gate shed with a small retry hint —
        their app was deleted mid-wait."""
        for key in [k for k in self._gates if k not in live]:
            g = self._gates.pop(key)
            for fut, _t in g._parked:
                if not fut.done():
                    fut.set_exception(ShedError("deadline", 1,
                                                "deployment removed"))
            g._parked.clear()

    def gate_for(self, app: str, deployment: str) -> \
            Optional[_DeploymentGate]:
        return self._gates.get((app, deployment))

    # -- the gate --------------------------------------------------------

    async def acquire(self, app: str, deployment: str):
        """Admit or shed. Returns a zero-arg release callable the caller
        MUST invoke exactly once when the request finishes (any
        outcome); raises ShedError to refuse."""
        g = self._gates.get((app, deployment))
        if g is None:
            # unknown deployment (admission unconfigured — e.g. route
            # snapshot unavailable, or a proxy started standalone in a
            # test): admit untracked. Must accept the release duration
            # argument like a real releaser.
            return lambda *_a: None
        if g.inflight < g.budget:
            g.inflight += 1
            self._count_admit(app, deployment, g, 0.0)
            return self._releaser(app, deployment, g)
        if len(g._parked) >= g.queue_depth:
            self._count_shed(app, deployment, "queue_full", g)
            raise ShedError("queue_full", g.retry_after_s())
        if g.predicted_wait_s(len(g._parked) + 1) > g.timeout_s:
            # SLO-aware refusal: the queue would outlive the deadline
            self._count_shed(app, deployment, "slo", g)
            raise ShedError("slo", g.retry_after_s())
        fut = asyncio.get_event_loop().create_future()
        t0 = time.perf_counter()
        g._parked.append((fut, t0))
        try:
            await asyncio.wait_for(fut, g.timeout_s)
        except asyncio.TimeoutError:
            try:
                g._parked.remove((fut, t0))
            except ValueError:
                pass  # a release popped us concurrently with the timeout
            self._count_shed(app, deployment, "deadline", g)
            raise ShedError("deadline", g.retry_after_s()) from None
        # a releaser handed us its slot (inflight stays counted)
        self._count_admit(app, deployment, g, time.perf_counter() - t0)
        return self._releaser(app, deployment, g)

    def _releaser(self, app: str, deployment: str, g: _DeploymentGate):
        released = False

        def release(duration_s: Optional[float] = None):
            nonlocal released
            if released:
                return
            released = True
            if duration_s is not None:
                g.ewma_s += _EWMA_ALPHA * (duration_s - g.ewma_s)
            # hand the slot to the queue head; the waiter keeps the
            # inflight count we hold, so the budget can never leak
            while g._parked:
                fut, _t = g._parked.popleft()
                if not fut.done():
                    fut.set_result(None)
                    self._set_inflight(app, deployment, g)
                    return
            g.inflight -= 1
            self._set_inflight(app, deployment, g)
        return release

    # -- telemetry (never raises) ----------------------------------------

    def _count_admit(self, app, deployment, g, waited_s: float):
        try:
            from .. import metrics as sm
            tags = {"app": app, "deployment": deployment}
            sm.admission_admitted().inc(1.0, tags=tags)
            sm.admission_queue_wait().observe(waited_s, tags=tags)
            self._set_inflight(app, deployment, g)
        except Exception:
            pass  # telemetry must never fail a request

    def _count_shed(self, app, deployment, reason, g):
        try:
            from .. import metrics as sm
            sm.admission_shed().inc(1.0, tags={
                "app": app, "deployment": deployment, "reason": reason})
        except Exception:
            pass  # telemetry must never fail a request

    def _set_inflight(self, app, deployment, g):
        try:
            from .. import metrics as sm
            sm.admission_inflight().set(float(g.inflight), tags={
                "app": app, "deployment": deployment,
                "proxy": self._proxy})
        except Exception:
            pass  # telemetry must never fail a request

    def stats(self) -> dict:
        return {f"{a}/{d}": {"inflight": g.inflight,
                             "queued": len(g._parked),
                             "budget": g.budget,
                             "ewma_service_s": round(g.ewma_s, 4)}
                for (a, d), g in self._gates.items()}
