"""SLO-aware admission control + per-tenant fairness for the proxies.

Each proxy runs one :class:`AdmissionController` on its event loop. Per
deployment it holds a budget (its share of the fleet's live capacity:
``replicas x max_ongoing_requests / n_proxies``), bounded FIFO queues
for arrivals past the budget, and an EWMA of per-request service time.

Decision tree for an arriving request (``acquire``):

1. a slot is free (globally AND within the request's tenant quota)
   -> admit immediately;
2. the tenant is past its quota and its queue share is full -> shed
   (``tenant_quota``);
3. the global queue is full -> shed (``queue_full``);
4. the *predicted* queue wait — requests ahead divided by the drain
   rate the EWMA implies — already exceeds the deadline
   (cfg.serve_admission_timeout_s) -> shed (``slo``);
5. otherwise park in the tenant's queue; releases hand slots to parked
   waiters. A request still parked at the deadline sheds
   (``deadline``).

Sheds raise :class:`ShedError` carrying a Retry-After estimate (the
predicted time for the backlog to drain, clamped to [1, 60] seconds) —
the proxy turns it into ``429`` + ``Retry-After``, the gRPC proxy into
``RESOURCE_EXHAUSTED``.

**Multi-tenant isolation** (cfg.serve_tenant_*): requests that resolve
a tenant id (``x_tenant_id`` header, ``tenant`` body field, or the
request's LoRA adapter id — :func:`resolve_tenant`) get

- *weighted-fair queueing*: one FIFO per tenant, drained
  deficit-round-robin (per-tenant weights, default 1), so a heavy
  tenant's thousand parked requests cannot starve a light tenant's
  one — the light tenant's p99 stays bounded by its own load;
- *quota*: at most ``serve_tenant_max_share`` of the deployment budget
  in flight (and of the queue depth parked) per tenant; past it the
  HEAVY tenant sheds 429 (reason ``tenant_quota``) while other
  tenants keep admitting.

Untenanted traffic rides the ``""`` bucket: one plain FIFO, no quota —
bit-compatible with the single-tenant front door. Tenant ids are
client-controlled, so per-gate tracking is bounded
(cfg.serve_tenant_max_tracked; overflow shares one ``__other__``
bucket) — gate state and metric cardinality cannot be grown by a
scanner.

Everything here is asyncio single-loop state — no locks; the proxy
calls it only from its event loop.
"""
from __future__ import annotations

import asyncio
import math
import time
from collections import deque
from typing import Optional

# EWMA smoothing for per-request service seconds; ~20-request memory
_EWMA_ALPHA = 0.1
# before any completion is observed, assume requests are this slow —
# optimistic enough not to shed a cold deployment on its first burst
_EWMA_SEED_S = 0.05

# overflow bucket once a gate tracks cfg.serve_tenant_max_tracked ids
_OTHER = "__other__"


def resolve_tenant(headers, payload) -> str:
    """The request's tenant id, resolved at admission: explicit header
    first, then body fields, then the LoRA adapter id (multi-tenant
    serving's natural tenant key — ``lora`` field or the ``:<adapter>``
    suffix of ``model``). "" = untenanted."""
    t = ""
    try:
        if headers is not None:
            t = headers.get("x_tenant_id", "") or ""
        if not t and isinstance(payload, dict):
            t = payload.get("tenant") or payload.get("user") or ""
            if not t:
                t = payload.get("lora") or ""
            if not t:
                model = payload.get("model", "")
                if isinstance(model, str) and ":" in model:
                    t = model.split(":", 1)[1]
        return str(t)[:128]
    except Exception:
        return ""  # tenant resolution must never fail a request


class ShedError(Exception):
    """Request refused by admission control; carries the retry hint."""

    def __init__(self, reason: str, retry_after_s: int, detail: str = ""):
        super().__init__(detail or f"admission shed ({reason})")
        self.reason = reason
        self.retry_after_s = retry_after_s


class _DeploymentGate:
    def __init__(self, budget: int, queue_depth: int, timeout_s: float):
        self.budget = max(1, int(budget))
        self.queue_depth = max(0, int(queue_depth))
        self.timeout_s = float(timeout_s)
        self.inflight = 0
        self.ewma_s = _EWMA_SEED_S
        # per-tenant state; "" is the untenanted bucket (no quota).
        # _queues doubles as the DRR rotation order.
        self._queues: dict[str, deque] = {}   # tenant -> (fut, t0) FIFO
        self._inflight_t: dict[str, int] = {}
        self._credits: dict[str, float] = {}
        self.weights: dict[str, float] = {}
        self._share = 1.0
        self._max_tracked = 64

    # -- tenant bookkeeping ----------------------------------------------

    def bucket(self, tenant: str) -> str:
        """Clamp a client-controlled tenant id to the tracked set."""
        if not tenant:
            return ""
        known = set(self._queues) | set(self._inflight_t)
        if tenant in known or len(known) < self._max_tracked:
            return tenant
        return _OTHER

    def _quota(self, tenant: str) -> Optional[int]:
        """Inflight cap for a tenant (None = unquota'd: untenanted
        traffic, or share >= 1)."""
        if not tenant or self._share >= 1.0:
            return None
        return max(1, int(self.budget * self._share))

    def _queue_quota(self, tenant: str) -> int:
        if not tenant or self._share >= 1.0:
            return self.queue_depth
        return max(1, int(self.queue_depth * self._share))

    def _under_quota(self, tenant: str) -> bool:
        q = self._quota(tenant)
        return q is None or self._inflight_t.get(tenant, 0) < q

    def parked_total(self) -> int:
        return sum(len(q) for q in self._queues.values())

    def parked_of(self, tenant: str) -> int:
        q = self._queues.get(tenant)
        return len(q) if q else 0

    def park(self, tenant: str, fut, t0: float) -> None:
        q = self._queues.get(tenant)
        if q is None:
            q = self._queues[tenant] = deque()
            self._credits.setdefault(tenant, self.weights.get(tenant, 1.0))
        q.append((fut, t0))

    def unpark(self, tenant: str, fut, t0: float) -> None:
        q = self._queues.get(tenant)
        if q is not None:
            try:
                q.remove((fut, t0))
            except ValueError:
                pass  # a release popped us concurrently
        self.prune_idle(tenant)

    def prune_idle(self, tenant: str) -> None:
        """Drop a tenant's gate state once it is fully idle (no slots,
        nothing parked). Without this, the bounded tracked set would
        fill PERMANENTLY — one scan burst of unique ids and every
        later real tenant would share the __other__ bucket forever.
        Configured weights survive (they are operator state, not
        traffic state)."""
        if not tenant or tenant not in (
                set(self._queues) | set(self._inflight_t)):
            return
        if self._inflight_t.get(tenant, 0) == 0 and \
                not self._queues.get(tenant):
            self._inflight_t.pop(tenant, None)
            self._queues.pop(tenant, None)
            self._credits.pop(tenant, None)

    def pop_waiter(self) -> Optional[tuple]:
        """Next waiter to hand a freed slot to: deficit-round-robin over
        tenant queues, skipping tenants at quota (their own releases
        re-arm them). -> (tenant, fut, t0) or None."""
        for _replenish in (False, True):
            if _replenish:
                live = [t for t, q in self._queues.items()
                        if q and self._under_quota(t)]
                if not live:
                    return None
                for t in live:
                    self._credits[t] = max(self.weights.get(t, 1.0), 1e-9)
            for t in list(self._queues):
                q = self._queues[t]
                if not q or not self._under_quota(t):
                    continue
                if self._credits.get(t, 0.0) <= 0:
                    continue
                while q:
                    fut, t0 = q.popleft()
                    if not fut.done():
                        self._credits[t] -= 1.0
                        # rotate: this tenant goes to the back of the
                        # round-robin order
                        self._queues[t] = self._queues.pop(t)
                        return t, fut, t0
        return None

    # -- prediction -------------------------------------------------------

    def predicted_wait_s(self, ahead: int) -> float:
        """Seconds until `ahead` queued requests drain: the budget
        retires ~budget/ewma requests per second."""
        return ahead * self.ewma_s / self.budget

    def retry_after_s(self) -> int:
        est = self.predicted_wait_s(self.parked_total() + 1)
        return max(1, min(60, int(math.ceil(est))))


class AdmissionController:
    """Per-proxy gatekeeper. ``configure`` is idempotent and cheap — the
    proxy calls it on every route-table refresh so budgets track live
    replica capacity; gates for deployments that disappear are
    dropped."""

    def __init__(self, proxy_label: str = "proxy-0"):
        self._gates: dict[tuple, _DeploymentGate] = {}
        self._proxy = proxy_label

    # -- configuration ---------------------------------------------------

    def configure(self, app: str, deployment: str, capacity: int,
                  n_proxies: int = 1,
                  queue_depth: Optional[int] = None,
                  timeout_s: Optional[float] = None,
                  tenant_max_share: Optional[float] = None,
                  tenant_weights: Optional[dict] = None) -> None:
        from ...core.config import cfg
        budget = max(1, int(capacity) // max(1, int(n_proxies)))
        qd = cfg.serve_admission_queue_depth if queue_depth is None \
            else queue_depth
        to = cfg.serve_admission_timeout_s if timeout_s is None \
            else timeout_s
        share = cfg.serve_tenant_max_share if tenant_max_share is None \
            else tenant_max_share
        g = self._gates.get((app, deployment))
        if g is None:
            g = self._gates[(app, deployment)] = _DeploymentGate(
                budget, qd, to)
        else:
            g.budget = max(1, int(budget))
            g.queue_depth = max(0, int(qd))
            g.timeout_s = float(to)
        g._share = float(share)
        g._max_tracked = max(1, int(cfg.serve_tenant_max_tracked))
        if tenant_weights:
            g.weights.update({str(k): float(v)
                              for k, v in tenant_weights.items()})

    def prune(self, live: set) -> None:
        """Drop gates for (app, deployment) pairs no longer deployed.
        Parked waiters of a pruned gate shed with a small retry hint —
        their app was deleted mid-wait."""
        for key in [k for k in self._gates if k not in live]:
            g = self._gates.pop(key)
            for tenant, q in g._queues.items():
                for fut, _t in q:
                    if not fut.done():
                        fut.set_exception(ShedError("deadline", 1,
                                                    "deployment removed"))
                q.clear()
                # last-write-wins gauge: a removed deployment must not
                # pin a stale queue depth on the TSDB forever
                self._set_queued(key[0], key[1], g, tenant)

    def gate_for(self, app: str, deployment: str) -> \
            Optional[_DeploymentGate]:
        return self._gates.get((app, deployment))

    # -- the gate --------------------------------------------------------

    async def acquire(self, app: str, deployment: str, tenant: str = ""):
        """Admit or shed. Returns a zero-arg release callable the caller
        MUST invoke exactly once when the request finishes (any
        outcome); raises ShedError to refuse."""
        g = self._gates.get((app, deployment))
        if g is None:
            # unknown deployment (admission unconfigured — e.g. route
            # snapshot unavailable, or a proxy started standalone in a
            # test): admit untracked. Must accept the release duration
            # argument like a real releaser.
            return lambda *_a: None
        from ...core.config import cfg
        if not cfg.serve_tenant_fair:
            tenant = ""   # one FIFO, no quota: the single-tenant gate
        t = g.bucket(tenant)
        if g.inflight < g.budget and g._under_quota(t):
            g.inflight += 1
            g._inflight_t[t] = g._inflight_t.get(t, 0) + 1
            self._count_admit(app, deployment, g, t, 0.0)
            return self._releaser(app, deployment, g, t)
        if t and g._share < 1.0 and \
                g.parked_of(t) >= g._queue_quota(t):
            # the HEAVY tenant sheds once its queue share fills —
            # regardless of its inflight count, so a tenant holding
            # zero slots still cannot fill the global queue and starve
            # everyone else into queue_full sheds
            self._count_shed(app, deployment, "tenant_quota", g, t)
            raise ShedError("tenant_quota", g.retry_after_s())
        if g.parked_total() >= g.queue_depth:
            self._count_shed(app, deployment, "queue_full", g, t)
            raise ShedError("queue_full", g.retry_after_s())
        if g.predicted_wait_s(g.parked_total() + 1) > g.timeout_s:
            # SLO-aware refusal: the queue would outlive the deadline
            self._count_shed(app, deployment, "slo", g, t)
            raise ShedError("slo", g.retry_after_s())
        fut = asyncio.get_event_loop().create_future()
        t0 = time.perf_counter()
        g.park(t, fut, t0)
        self._set_queued(app, deployment, g, t)
        try:
            await asyncio.wait_for(fut, g.timeout_s)
        except asyncio.TimeoutError:
            if fut.done() and not fut.cancelled():
                # same-tick race (Python >= 3.12 wait_for discards a
                # completed result when the timer fires first): a
                # releaser already transferred its slot to us — pass it
                # onward or g.inflight leaks one budget slot forever
                self._releaser(app, deployment, g, t)(None)
            else:
                g.unpark(t, fut, t0)
            self._set_queued(app, deployment, g, t)
            self._count_shed(app, deployment, "deadline", g, t)
            raise ShedError("deadline", g.retry_after_s()) from None
        except asyncio.CancelledError:
            # client disconnected while parked: withdraw from the queue
            # and re-record the gauge — rtpu_serve_tenant_queued feeds
            # the tenant_queue autoscale signal, so a waiter that left
            # without unparking would pin a stale backlog that scales
            # the deployment out forever. If a releaser handed us its
            # slot in the same tick (fut completed before the cancel
            # landed), the pop-time bookkeeping already transferred the
            # inflight count to us: release it onward.
            if fut.done() and not fut.cancelled():
                self._releaser(app, deployment, g, t)(None)
            else:
                g.unpark(t, fut, t0)
            self._set_queued(app, deployment, g, t)
            raise
        # a releaser handed us its slot (inflight + our tenant count
        # are already transferred/incremented by pop-time bookkeeping)
        self._count_admit(app, deployment, g, t,
                          time.perf_counter() - t0)
        return self._releaser(app, deployment, g, t)

    def _releaser(self, app: str, deployment: str, g: _DeploymentGate,
                  tenant: str):
        released = False

        def release(duration_s: Optional[float] = None):
            nonlocal released
            if released:
                return
            released = True
            if duration_s is not None:
                g.ewma_s += _EWMA_ALPHA * (duration_s - g.ewma_s)
            # free OUR tenant's slot first, then hand the global slot to
            # the fairest eligible waiter; the waiter keeps the inflight
            # count we hold, so the budget can never leak
            g._inflight_t[tenant] = max(
                g._inflight_t.get(tenant, 1) - 1, 0)
            got = g.pop_waiter()
            if got is not None:
                w_t, fut, _t0 = got
                g._inflight_t[w_t] = g._inflight_t.get(w_t, 0) + 1
                fut.set_result(None)
                self._set_inflight(app, deployment, g, w_t)
                self._set_queued(app, deployment, g, w_t)
            else:
                g.inflight -= 1
            self._set_inflight(app, deployment, g, tenant)
            g.prune_idle(tenant)
        return release

    # -- telemetry (never raises) ----------------------------------------

    def _count_admit(self, app, deployment, g, tenant, waited_s: float):
        try:
            from .. import metrics as sm
            tags = {"app": app, "deployment": deployment}
            sm.admission_admitted().inc(1.0, tags=tags)
            sm.admission_queue_wait().observe(waited_s, tags=tags)
            if tenant:
                sm.tenant_requests().inc(1.0, tags={
                    **tags, "tenant": tenant, "outcome": "admitted"})
            self._set_inflight(app, deployment, g, tenant)
        except Exception:
            pass  # telemetry must never fail a request

    def _count_shed(self, app, deployment, reason, g, tenant=""):
        try:
            from .. import metrics as sm
            sm.admission_shed().inc(1.0, tags={
                "app": app, "deployment": deployment, "reason": reason})
            if tenant:
                sm.tenant_requests().inc(1.0, tags={
                    "app": app, "deployment": deployment,
                    "tenant": tenant, "outcome": "shed"})
        except Exception:
            pass  # telemetry must never fail a request

    def _set_queued(self, app, deployment, g, tenant=""):
        """Per-tenant queue-depth gauge (the "" bucket doubles as the
        deployment's plain admission backlog). The TSDB turns these
        last-write samples into the per-tenant queue-depth SERIES the
        adapter-aware autoscaling signal reads; the proc label keys the
        head's death sweep (a killed proxy's backlog zeroes instead of
        pinning the scale-out signal on forever)."""
        try:
            from ...llm.telemetry import _proc
            from .. import metrics as sm
            sm.tenant_queued().set(float(g.parked_of(tenant)), tags={
                "app": app, "deployment": deployment,
                "tenant": tenant, "proxy": self._proxy,
                "proc": _proc()})
        except Exception:
            pass  # telemetry must never fail a request

    def _set_inflight(self, app, deployment, g, tenant=""):
        try:
            from .. import metrics as sm
            sm.admission_inflight().set(float(g.inflight), tags={
                "app": app, "deployment": deployment,
                "proxy": self._proxy})
            if tenant:
                sm.tenant_inflight().set(
                    float(g._inflight_t.get(tenant, 0)), tags={
                        "app": app, "deployment": deployment,
                        "tenant": tenant, "proxy": self._proxy})
        except Exception:
            pass  # telemetry must never fail a request

    def stats(self) -> dict:
        out = {}
        for (a, d), g in self._gates.items():
            out[f"{a}/{d}"] = {
                "inflight": g.inflight,
                "queued": g.parked_total(),
                "budget": g.budget,
                "ewma_service_s": round(g.ewma_s, 4),
                "tenants": {t: {"inflight": g._inflight_t.get(t, 0),
                                "queued": g.parked_of(t)}
                            for t in (set(g._inflight_t)
                                      | set(g._queues)) if t},
            }
        return out
