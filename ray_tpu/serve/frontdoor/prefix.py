"""Cluster-wide prefix-cache directory client (replica side).

PR 2 gave every paged engine a per-replica prefix cache: full prompt
pages content-addressed by chained hashes, admission-matched so shared
system prompts prefill once per replica. This module makes those caches
ONE cluster cache:

- **publish**: the replica's engine loop drains newly registered /
  evicted page hashes (PagedInferenceEngine.drain_directory_delta) and
  merges them into the ``serve:prefix:<model>`` shared directory,
  valued with this replica's own actor handle;
- **import**: before submitting a prompt, a replica computes the
  prompt's chain hashes, checks local coverage, and asks the directory
  about the rest. If another replica warmed a longer run, it calls that
  replica's ``export_prefix`` (pages gathered to host arrays — the
  payload rides the object store like any large actor-call result) and
  seeds its own cache via ``import_prefix``; admission then hits
  locally as if the pages had been computed here. Greedy decoding over
  imported pages is bit-identical to a cold prefill — the pages ARE
  the cold prefill's pages, moved;
- **heat**: each publish cadence also files ONE bounded summary entry
  under the string key ``"heat:<host:pid>"`` in the same directory —
  pool occupancy, hit rate, and the engine's top-K hot chains from the
  cache heat plane (llm/chainstats.py). String keys cannot collide
  with the 16-byte page-hash keys and importers only query by hash, so
  the summaries are invisible to the import path; they ride the same
  dir_update frames (no protocol change), are owner-stamped so a dead
  replica's summary sweeps with its page entries, and feed the head's
  ``cache_report()`` / ``cli cache`` cluster heat map;
- **spill** (the tiered KV-cache, llm/tiering.py): when the engine
  runs with ``kv_spill``, the publish cadence also materializes newly
  demoted pages into the host object store (SpillTier.materialize)
  and registers them as ``"spill:<hash hex>"`` string entries valued
  ``{"m": model_id, "oid": ref_binary}``. The import path queries
  both key shapes: a LIVE peer covering at least as long a run wins
  (export_prefix is one hop, no store fetch), otherwise the importer
  fetches the spill segments straight from the store — the owner
  replica need not even be alive, only its refs (held by its tier)
  must be. So a prefix NO replica holds in device memory any more is
  still one directory query + store fetch away from a warm admit.

Spill entries are hints like everything else here: a fetched payload
is validated against the requested chain before any scatter, and a
mismatch drops the stale keys, counts ``spill_drops``, and prefills
cold — latency, never correctness.

Failure model (the consistency rule the README documents): every
directory entry is a HINT. Owner dead, pages evicted, head gone — the
importer drops the stale keys (best effort) and the request prefills
cold. Nothing on this path can corrupt an answer; it can only miss a
shortcut. Sheds and deaths mid-import surface as a cold prefill, never
an error.
"""
from __future__ import annotations

import time
from typing import Any, Optional


class PrefixDirectoryClient:
    """One per LLMServer replica, on the replica's PRIMARY paged engine.

    LoRA-merged side engines stay out (different KV for the same
    tokens, unsalted chains would collide). The batched multi-LoRA
    path shares the primary engine safely: its requests hash with a
    per-(adapter_id, version) salt (llm/multilora/manager.prefix_salt),
    so directory keys are tenant-scoped by construction — a hit can
    only come from the same adapter at the same version."""

    def __init__(self, model_id: str):
        self.dir_name = f"serve:prefix:{model_id}"
        self.model_id = model_id
        self._self_handle: Any = None
        self._self_id: Optional[bytes] = None
        self._last_publish = 0.0

    def set_replica_handle(self, handle) -> None:
        """The replica's own actor handle (injected by the controller
        right after creation) — published as every entry's value so
        importers can call export_prefix on the owner."""
        self._self_handle = handle
        self._self_id = getattr(handle, "_actor_id", None)

    # -- publish ---------------------------------------------------------

    def maybe_publish(self, engine) -> int:
        """Called from the replica's engine loop (the stepping thread —
        drain_directory_delta's contract): ship accumulated page-hash
        deltas to the head, rate-limited by cfg.serve_prefix_publish_s.
        Returns hashes published."""
        if self._self_handle is None:
            return 0    # handle not injected yet: nothing to own entries
        from ...core.config import cfg
        now = time.monotonic()
        if now - self._last_publish < cfg.serve_prefix_publish_s:
            return 0
        self._last_publish = now
        new, dropped = engine.drain_directory_delta()
        put: dict = {h: self._self_handle for h in new}
        dropped = list(dropped)
        heat = self._heat_summary(engine)
        if heat is not None:
            # refreshed every cadence even with no page deltas: last-hit
            # ages and pool occupancy move while the key set stands still
            put[heat["key"]] = heat["value"]
        spill_put, spill_drop = self._spill_delta(engine)
        put.update(spill_put)
        dropped.extend(spill_drop)
        if not put and not dropped:
            return 0
        from ...core import directory as cdir
        ok = cdir.update(self.dir_name, put=put, drop=dropped)
        if ok and new:
            try:
                from .. import metrics as sm
                sm.prefix_directory_publishes().inc(
                    float(len(new)), tags={"model": self.model_id})
            except Exception:
                pass  # telemetry must never fail the engine loop
        return len(new) if ok else 0

    def _spill_delta(self, engine) -> tuple:
        """Spill-tier directory delta for this cadence: materialize
        still-staged demoted pages into the object store and return
        ({put}, [drop]) of ``spill:<hex>`` entries. Runs on the
        stepping thread (the tier's serialization contract). Best
        effort end to end — a store/put failure leaves pages staged
        and locally promotable; they re-register on a later cadence
        via materialize's already-stored reporting."""
        tier = getattr(engine, "spill", None)
        if tier is None:
            return {}, []
        try:
            new, gone = tier.drain_publish_delta()
            drop = ["spill:" + h.hex() for h in gone]
            if not new:
                return {}, drop
            import ray_tpu
            oids = tier.materialize(new, engine.cfg.page_size,
                                    ray_tpu.put)
            missed = [h for h in new if h not in oids]
            if missed:
                tier.requeue_publish(missed)   # retry next cadence
            put = {"spill:" + h.hex(): {"m": self.model_id, "oid": oid}
                   for h, oid in oids.items()}
            return put, drop
        except Exception:
            return {}, []   # spill publish must never fail the loop

    def _heat_summary(self, engine) -> Optional[dict]:
        """One bounded dict describing this replica's cache heat —
        {"key": "heat:<proc>", "value": {...}} — or None when the
        engine's heat plane is off. Size is capped by construction:
        top-K chain rows + a handful of pool scalars."""
        try:
            report = engine.chain_stats_report()
            if not report:
                return None
            from ...llm.telemetry import _proc
            acct = engine.prefix_accounting()
            pool = engine.pool_stats()
            page_bytes = report["table"]["page_bytes"]
            cached = acct["cached_pages"]
            return {"key": f"heat:{_proc()}", "value": {
                "model": self.model_id,
                "proc": _proc(),
                "ts": time.time(),
                "hit_rate": acct["hit_rate"],
                "pool": {
                    "free_pages": pool["free_pages"],
                    "cached_pages": cached,
                    "total_pages": pool["total_pages"],
                    "page_bytes": page_bytes,
                    # what tiering could spill today: refcount-0 pages
                    # held only for possible reuse
                    "reclaimable_bytes": cached * page_bytes,
                    # the spill tier's host-side residence (0/0 with
                    # kv_spill off)
                    "spilled_pages": acct.get("spill_resident_pages", 0),
                    "spilled_bytes": acct.get("spill_resident_bytes", 0),
                },
                "chains": report["chains"],
            }}
        except Exception:
            return None  # heat is telemetry; never fail the engine loop

    # -- import ----------------------------------------------------------

    def maybe_import(self, engine, steplock, prompt,
                     salt: bytes = b"") -> int:
        """Admission-time cross-replica import. Returns pages imported
        (0 on local-hit, no-entry, or any failure — all of which just
        mean a cold prefill). Called on a request thread; `steplock`
        serializes the cache scatter against the engine loop (the same
        contract PD-disagg's import_prefill rides). ``salt`` must match
        the submitting request's prefix_salt (tenant-scoped chains)."""
        try:
            hashes = engine.hash_prompt(prompt, salt=salt)
        except Exception:
            return 0
        if not hashes:
            return 0
        local = engine.cached_prefix_len(hashes)
        if local >= len(hashes):
            return 0    # fully covered locally: not a directory event
        from ...core import directory as cdir
        from ...core.config import cfg
        # one query, both key shapes: live replicas own the 16-byte
        # page-hash entries, the spill tier owns "spill:<hex>" strings
        tail = hashes[local:]
        got = cdir.query(self.dir_name,
                         keys=tail + ["spill:" + h.hex() for h in tail],
                         timeout=2.0)
        entries = (got or {}).get("entries") or {}
        # longest hash the cluster claims to cover, owned by a peer
        best_i, owner = -1, None
        for i in range(len(hashes) - 1, local - 1, -1):
            cand = entries.get(hashes[i])
            if cand is None:
                continue
            if self._self_id is not None and \
                    getattr(cand, "_actor_id", None) == self._self_id:
                continue    # our own publication
            best_i, owner = i, cand
            break
        # longest consecutive run the spill tier covers from `local`
        spill_i = local - 1
        while spill_i + 1 < len(hashes) and isinstance(
                entries.get("spill:" + hashes[spill_i + 1].hex()), dict):
            spill_i += 1
        if owner is None and spill_i < local:
            self._count("misses")
            return 0
        if owner is None or spill_i > best_i:
            # no live peer, or the store covers a strictly longer run
            # (ties go to the live peer: export_prefix is one hop):
            # promote straight from the object store — works even when
            # NO replica still holds these pages in device memory, and
            # the importer needs no tier of its own (import_prefix is
            # the ordinary cross-replica scatter)
            return self._import_spilled(engine, steplock, hashes,
                                        local, spill_i, entries)
        want = hashes[:best_i + 1]
        try:
            import ray_tpu
            payload = ray_tpu.get(
                owner.handle_request.remote(
                    "export_prefix", (want,), {}, None),
                timeout=cfg.serve_prefix_import_timeout_s)
        except Exception:
            # owner dead/slow: drop the stale hints so the next request
            # doesn't retry a dead replica, then prefill cold
            cdir.update(self.dir_name,
                        drop=[h for h in want if h in entries])
            self._count("stale")
            return 0
        if not payload:
            cdir.update(self.dir_name,
                        drop=[h for h in want if h in entries])
            self._count("stale")
            return 0
        try:
            with steplock:
                n = engine.import_prefix(payload)
        except Exception:
            # a matching hint with an incompatible payload (same
            # model_id, different engine geometry) must cost a cold
            # prefill, never the request — per the module failure model
            cdir.update(self.dir_name,
                        drop=[h for h in want if h in entries])
            self._count("stale")
            return 0
        if n > 0:
            self._count("hits")
            try:
                from .. import metrics as sm
                sm.prefix_directory_imported_pages().inc(
                    float(n), tags={"model": self.model_id})
            except Exception:
                pass  # telemetry must never fail a request
        else:
            self._count("misses")
        return n

    def _import_spilled(self, engine, steplock, hashes, local, spill_i,
                        entries) -> int:
        """Promote a consecutive spilled run straight from the host
        object store: fetch each distinct segment payload once, pull
        the run's rows in chain order, and seed the engine through the
        ordinary import_prefix scatter. Validate-on-promote per the
        module failure model — any stale/corrupt segment truncates the
        run there, drops the bad ``spill:`` keys, and counts
        ``spill_drops``; whatever validated before the break still
        imports. Returns pages imported (0 = cold prefill)."""
        from ...core import directory as cdir
        from ...core.config import cfg
        from ...core.ids import ObjectID
        from ...core.ref import ObjectRef
        from ...llm.tiering import _payload_ok
        import numpy as np
        import ray_tpu
        run = hashes[local:spill_i + 1]
        page_size = engine.cfg.page_size
        seg_cache: dict = {}    # oid bytes -> payload | None (bad)
        rows: list = []         # (hash, [k per layer], [v per layer])
        stale: list = []        # spill:<hex> keys to drop
        for h in run:
            key = "spill:" + h.hex()
            e = entries.get(key)
            oid = e.get("oid") if isinstance(e, dict) else None
            if not isinstance(oid, (bytes, bytearray)) or \
                    e.get("m") != self.model_id:
                stale.append(key)
                break
            oid = bytes(oid)
            if oid not in seg_cache:
                try:
                    payload = ray_tpu.get(
                        ObjectRef(ObjectID(oid)),
                        timeout=cfg.serve_prefix_import_timeout_s)
                except Exception:
                    payload = None
                if not _payload_ok(payload, page_size):
                    payload = None
                seg_cache[oid] = payload
            payload = seg_cache[oid]
            if payload is None:
                # the whole segment is gone/garbage: every run key that
                # points at this oid is equally stale
                stale.append(key)
                stale.extend(
                    "spill:" + hh.hex() for hh in run
                    if isinstance(entries.get("spill:" + hh.hex()), dict)
                    and entries["spill:" + hh.hex()].get("oid") == oid)
                break
            try:
                i = payload["page_hashes"].index(h)
                rows.append((h,
                             [lay["k"][i] for lay in payload["pages"]],
                             [lay["v"][i] for lay in payload["pages"]]))
            except Exception:
                stale.append(key)   # segment no longer carries the hash
                break
        n = 0
        if rows:
            try:
                n_layers = len(rows[0][1])
                combined = {
                    "page_size": page_size,
                    "page_hashes": [r[0] for r in rows],
                    "pages": [
                        {"k": np.stack([r[1][li] for r in rows]),
                         "v": np.stack([r[2][li] for r in rows])}
                        for li in range(n_layers)],
                }
                with steplock:
                    n = engine.import_prefix(combined)
            except Exception:
                # ragged geometry across segments, or an engine with
                # incompatible pools: cost a cold prefill, never the
                # request
                stale.extend("spill:" + r[0].hex() for r in rows)
                n = 0
        if stale:
            stale = [k for k in dict.fromkeys(stale) if k in entries]
            cdir.update(self.dir_name, drop=stale)
            engine.note_spill_drops(len(stale))
            self._count("stale")
        if n > 0:
            engine.note_spill_promotion(hashes[0], n)
            self._count("hits")
            try:
                from .. import metrics as sm
                sm.prefix_directory_imported_pages().inc(
                    float(n), tags={"model": self.model_id})
            except Exception:
                pass  # telemetry must never fail a request
        elif not stale:
            self._count("misses")
        return n

    def _count(self, which: str):
        try:
            from .. import metrics as sm
            fn = {"hits": sm.prefix_directory_hits,
                  "misses": sm.prefix_directory_misses,
                  "stale": sm.prefix_directory_stale}[which]
            fn().inc(1.0, tags={"model": self.model_id})
        except Exception:
            pass  # telemetry must never fail a request
