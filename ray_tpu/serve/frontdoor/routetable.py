"""The proxies' shared route table, published through the head's shared
directory service (core/directory.py, protocol v7).

The controller is the single writer: on every topology change (deploy,
delete, replica scale, proxy death/replacement) it publishes ONE
snapshot entry into the ``serve:routes`` directory::

    {"v": int,                      # controller-side version counter
     "routes": {route_prefix: app},      # longest-match table
     "ingress": {app: ingress_deployment},
     "capacity": {"app/deployment": [replicas, max_ongoing_requests]},
     "n_proxies": int,
     "proxies": [{"index": i, "port": p}]}

Every proxy refreshes its copy on a short TTL with one ``dir_query``
frame — no per-request controller round-trips, and N proxies cost the
controller nothing in steady state. When the directory is unreachable
(local clusters torn mid-test, head restarting) proxies fall back to
direct controller calls, so the snapshot is an optimization AND the
scale-out mechanism, never a single point of failure.

Like every shared-directory payload, the snapshot is a hint: a proxy
may briefly route on a stale table after a scale event. That window is
bounded by the TTL and is benign — handles re-resolve replicas
themselves, and admission budgets only lag capacity by one refresh.
"""
from __future__ import annotations

from typing import Optional

ROUTES_DIR = "serve:routes"
_SNAP_KEY = "snapshot"


def publish_snapshot(snap: dict) -> bool:
    """Controller-side: merge the current snapshot into the directory.
    Fire-and-forget (one async frame); False when no cluster runtime."""
    from ...core import directory as cdir
    return cdir.update(ROUTES_DIR, put={_SNAP_KEY: snap})


def fetch_snapshot(timeout: float = 2.0) -> Optional[dict]:
    """Proxy-side: the latest published snapshot, or None when the
    directory is unreachable/empty (callers fall back to controller
    RPCs)."""
    from ...core import directory as cdir
    got = cdir.query(ROUTES_DIR, keys=[_SNAP_KEY], timeout=timeout)
    if not got:
        return None
    return got["entries"].get(_SNAP_KEY)


def capacity_of(snap: dict, app: str, deployment: str) -> int:
    cap = snap.get("capacity", {}).get(f"{app}/{deployment}")
    if not cap:
        return 0
    replicas, max_ongoing = cap
    return max(int(replicas), 1) * max(int(max_ongoing), 1)
