"""gRPC ingress for Serve.

Reference parity: the Serve gRPC proxy (serve/_private/proxy.py gRPCProxy +
user-supplied proto servicers). This image ships the grpc RUNTIME but not
protoc codegen, so the ingress is a *generic* service registered with
``GenericRpcHandler`` — no generated stubs on either side:

  method  /raytpu.Serve/Call         unary-unary
  method  /raytpu.Serve/CallStream   unary-stream
  request/response payloads: JSON bytes
  request envelope: {"app": str, "method": str, "payload": any,
                     "multiplexed_model_id": str}

Client (pure grpc, no stubs):

    ch = grpc.insecure_channel(f"127.0.0.1:{port}")
    call = ch.unary_unary("/raytpu.Serve/Call")
    out = json.loads(call(json.dumps({"app": "llm",
                                      "method": "v1_models"}).encode()))
"""
from __future__ import annotations

import json
import threading
from collections import OrderedDict
from concurrent import futures
from typing import Optional


class GrpcProxyActor:
    """Serve deployment-routing gRPC server (one per cluster, started by
    serve.start_grpc_proxy)."""

    def __init__(self, port: int = 0):
        self._requested_port = port
        self._server = None
        self.port: Optional[int] = None
        self._handles: "OrderedDict" = OrderedDict()
        self._handles_max = 256
        # the 16-thread gRPC executor mutates the cache concurrently
        # (unlike the HTTP proxy, which lives on one event-loop thread)
        self._handles_lock = threading.Lock()
        # synchronous admission gate (the HTTP fleet's asyncio
        # controller doesn't fit a thread-pool server): per-app
        # in-flight counts against the route-table capacity; past
        # budget + queue depth the request sheds RESOURCE_EXHAUSTED —
        # the gRPC spelling of the HTTP 429 contract
        self._inflight: dict[str, int] = {}  # guarded by: self._adm_lock
        self._adm_lock = threading.Lock()
        self._snap: Optional[dict] = None
        self._snap_ts = 0.0

    # -- admission (frontdoor, sync flavor) --------------------------------

    def _budget_for(self, app: str) -> Optional[int]:
        """App's admission bound from the shared route-table snapshot:
        the fleet capacity itself (replicas x max_ongoing_requests —
        replica-side queueing is already inside max_ongoing, and gRPC
        clients carry deadlines/retries, so unlike the HTTP proxies
        there is no extra proxy-side queue allowance). None =
        unconfigured (admit untracked)."""
        from ..core.config import cfg
        if not cfg.serve_admission_control:
            return None
        import time as _time

        from .frontdoor import routetable
        if _time.monotonic() - self._snap_ts > 1.0:
            try:
                self._snap = routetable.fetch_snapshot()
            except Exception:
                self._snap = None  # directory unreachable: admit open
            self._snap_ts = _time.monotonic()
        snap = self._snap
        if not snap:
            return None
        ing = snap.get("ingress", {}).get(app)
        if ing is None:
            return None
        cap = routetable.capacity_of(snap, app, ing)
        if cap <= 0:
            return None
        return cap

    def _admit(self, app: str, context, tenant: str = "") -> bool:
        """True = admitted (caller must _release); aborts the rpc with
        RESOURCE_EXHAUSTED when the app — or the request's TENANT share
        of it (cfg.serve_tenant_max_share, same quota rule as the HTTP
        gate) — is past budget."""
        import grpc

        from ..core.config import cfg
        bound = self._budget_for(app)
        if not cfg.serve_tenant_fair:
            tenant = ""
        reason = "queue_full"
        with self._adm_lock:
            cur = self._inflight.get(app, 0)
            t_bound = None
            if bound is not None and tenant and \
                    cfg.serve_tenant_max_share < 1.0:
                t_bound = max(1, int(bound * cfg.serve_tenant_max_share))
            t_cur = self._inflight.get((app, tenant), 0) if tenant else 0
            if bound is not None and cur >= bound:
                shed = True
            elif t_bound is not None and t_cur >= t_bound:
                shed, reason = True, "tenant_quota"
            else:
                self._inflight[app] = cur + 1
                if tenant:
                    self._inflight[(app, tenant)] = t_cur + 1
                shed = False
        if shed:
            try:
                from . import metrics as sm
                sm.admission_shed().inc(1.0, tags={
                    "app": app, "deployment": "", "reason": reason})
                if tenant:
                    sm.tenant_requests().inc(1.0, tags={
                        "app": app, "deployment": "", "tenant": tenant,
                        "outcome": "shed"})
            except Exception:
                pass  # telemetry must never fail a request
            context.abort(grpc.StatusCode.RESOURCE_EXHAUSTED,
                          "overloaded; retry_after_s=1")
        return True

    def _release(self, app: str, tenant: str = ""):
        with self._adm_lock:
            self._inflight[app] = max(0, self._inflight.get(app, 1) - 1)
            if tenant and (app, tenant) in self._inflight:
                self._inflight[(app, tenant)] = max(
                    0, self._inflight[(app, tenant)] - 1)

    def start(self) -> int:
        import grpc

        if self._server is not None:   # idempotent: start-or-return
            return self.port

        outer = self

        class Handler(grpc.GenericRpcHandler):
            def service(self, handler_call_details):
                method = handler_call_details.method
                if method == "/raytpu.Serve/Call":
                    return grpc.unary_unary_rpc_method_handler(
                        outer._call)
                if method == "/raytpu.Serve/CallStream":
                    return grpc.unary_stream_rpc_method_handler(
                        outer._call_stream)
                return None

        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=16))
        self._server.add_generic_rpc_handlers((Handler(),))
        self.port = self._server.add_insecure_port(
            f"127.0.0.1:{self._requested_port}")
        self._server.start()
        return self.port

    # -- routing (mirrors the HTTP proxy's handle cache) ----------------- #

    def _handle_for(self, app: str, method: str, stream: bool,
                    model_id: str):
        import ray_tpu

        from .api import CONTROLLER_NAME
        from .handle import DeploymentHandle
        # re-resolve the ingress EVERY request and key on it: a redeployed
        # app must not route to the old ingress (matches the HTTP proxy)
        ctrl = ray_tpu.get_actor(CONTROLLER_NAME)
        ingress = ray_tpu.get(ctrl.get_ingress.remote(app))
        key = (app, ingress, method, stream, model_id)
        with self._handles_lock:
            h = self._handles.get(key)
            if h is None:
                h = DeploymentHandle(ingress, app, ctrl, method,
                                     stream=stream,
                                     multiplexed_model_id=model_id)
                self._handles[key] = h
                while len(self._handles) > self._handles_max:
                    self._handles.popitem(last=False)
            else:
                self._handles.move_to_end(key)
        return h

    @staticmethod
    def _parse(request_bytes: bytes):
        req = json.loads(request_bytes or b"{}")
        app = req.get("app", "default")
        method = req.get("method", "__call__")
        if method != "__call__" and (
                method.startswith("_") or not method.isidentifier()):
            raise ValueError(f"no route {method!r}")
        return (app, method, req.get("payload"),
                req.get("multiplexed_model_id", ""))

    @staticmethod
    def _typed_abort(context, e) -> None:
        """Typed statuses for the failure modes a healthy front door
        still sees (same contract as the HTTP proxy's 503/504): replica
        death -> UNAVAILABLE (retryable), upstream timeout ->
        DEADLINE_EXCEEDED; anything else is a real INTERNAL."""
        import grpc

        from ..exceptions import (ActorDiedError, GetTimeoutError,
                                  WorkerCrashedError)
        if isinstance(e, (ActorDiedError, WorkerCrashedError)) or (
                isinstance(e, RuntimeError) and "no replicas" in str(e)):
            context.abort(grpc.StatusCode.UNAVAILABLE,
                          f"replica_unavailable: {type(e).__name__}")
        if isinstance(e, GetTimeoutError):
            context.abort(grpc.StatusCode.DEADLINE_EXCEEDED,
                          "upstream_timeout")
        context.abort(grpc.StatusCode.INTERNAL, repr(e))

    def _call(self, request_bytes: bytes, context) -> bytes:
        try:
            app, method, payload, model_id = self._parse(request_bytes)
        except Exception as e:  # noqa: BLE001 — bad envelope
            import grpc
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, repr(e))
        from .frontdoor.admission import resolve_tenant
        tenant = resolve_tenant(None, payload)
        self._admit(app, context, tenant)
        try:
            h = self._handle_for(app, method, False, model_id)
            resp = (h.remote(payload) if payload is not None
                    else h.remote())
            out = resp.result(timeout_s=300)
            return json.dumps(out, default=str).encode()
        except Exception as e:  # noqa: BLE001 — map to grpc status
            self._typed_abort(context, e)
        finally:
            self._release(app, tenant)

    def _call_stream(self, request_bytes: bytes, context):
        try:
            app, method, payload, model_id = self._parse(request_bytes)
        except Exception as e:  # noqa: BLE001 — bad envelope
            import grpc
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, repr(e))
        from .frontdoor.admission import resolve_tenant
        tenant = resolve_tenant(None, payload)
        self._admit(app, context, tenant)
        try:
            h = self._handle_for(app, method, True, model_id)
            gen = (h.remote(payload) if payload is not None
                   else h.remote())
            try:
                for chunk in gen:
                    yield json.dumps(chunk, default=str).encode()
            finally:
                gen.cancel()
        except Exception as e:  # noqa: BLE001
            self._typed_abort(context, e)
        finally:
            self._release(app, tenant)

    def stop(self):
        if self._server is not None:
            self._server.stop(grace=1.0)


def start_grpc_proxy(port: int = 0):
    """Start (or return) the cluster's gRPC proxy actor; returns
    (handle, bound_port)."""
    import ray_tpu
    name = "rtpu:serve:grpc-proxy"
    try:
        actor = ray_tpu.get_actor(name)
        return actor, ray_tpu.get(actor.start.remote())
    except ValueError:
        pass
    cls = ray_tpu.remote(GrpcProxyActor)
    actor = cls.options(name=name, max_concurrency=32).remote(port)
    bound = ray_tpu.get(actor.start.remote())
    return actor, bound
