"""DeploymentHandle: the client-side router.

Reference parity: serve/handle.py:639 (DeploymentHandle.remote :715 ->
DeploymentResponse), _private/router.py:365 (AsyncioRouter.assign_request
:676) and request_router/pow_2_router.py:27 (power-of-two-choices).

Routing here tracks in-flight counts per handle (each handle routes its own
traffic) and picks the lighter of two random replicas; the replica set is
cached and refreshed from the controller when its version changes or a
replica dies mid-call (retried once on a fresh set).

Prefix affinity: LLM-style requests (a dict carrying ``prompt``, or an
explicit ``session_id``) rendezvous-hash onto a stable replica so repeated
prefixes — system prompts, multi-turn sessions — land where the paged
engine's prefix cache already holds their KV pages (paged_engine.py
enable_prefix_caching). The affinity choice yields to least-loaded when
the preferred replica is clearly busier than the lightest one, so a hot
prefix cannot hotspot a replica into queueing.
"""
from __future__ import annotations

import random
import time
from collections import deque
from typing import Any, Optional

from ..core.config import cfg as _cfg
from ..core import flight as _fl

# affinity yields to load: the preferred replica is skipped when it has
# this many more in-flight requests (on this handle) than the lightest
# replica — a cache hit saves prefill, not a queueing delay
_AFFINITY_SLACK = 4


class DeploymentResponse:
    """Future for one request (reference: handle.py DeploymentResponse).
    `.result()` blocks; `await` works inside async actors; passing a
    response to another .remote() passes the underlying ObjectRef so the
    payload never bounces through the caller.

    `.result()` retries once on a fresh replica set when the chosen replica
    died (scale-down or crash race against the handle's cached set)."""

    def __init__(self, ref, on_done, retry=None):
        self._ref = ref
        self._done = False
        self._on_done = on_done
        self._retry = retry

    def result(self, timeout_s: Optional[float] = None) -> Any:
        import ray_tpu
        from ..exceptions import ActorDiedError, WorkerCrashedError
        try:
            try:
                return ray_tpu.get(self._ref, timeout=timeout_s)
            except (ActorDiedError, WorkerCrashedError) as e:
                if self._retry is None:
                    raise
                # break the exception->traceback->frame cycle NOW: the
                # traceback's get() frames pin the dead replica's error
                # ref until a gc pass happens to run, which would hold
                # the store above baseline long after a chaos kill is
                # retried successfully
                e.__traceback__ = None
                self._ref = self._retry()
                return ray_tpu.get(self._ref, timeout=timeout_s)
        finally:
            self._settle()

    def _settle(self):
        if not self._done:
            self._done = True
            self._on_done()

    def _to_object_ref(self):
        return self._ref

    def __await__(self):
        def gen():
            try:
                out = yield from self._ref.__await__()
                return out
            finally:
                self._settle()
        return gen()


class ChannelResponseGenerator:
    """Iterator over a streaming response served by the STATIC DECODE
    PLAN: the replica drains its generator into a sealed ring channel
    (dag/channel.py) and this end reads items straight out of shm —
    zero control-plane dispatches per item in steady state (the only
    actor calls are the setup and, when the stream goes quiet for a long
    time, a liveness probe so a dead replica raises instead of hanging).
    Falls out of DeploymentHandle.remote() when the replica shares the
    caller's object store and cfg.serve_static_decode_plan is on."""

    # probe the replica after this many idle 0.5s wait-slices in a row
    # (a healthy but slow decode costs at most one probe dispatch per
    # ~30s of silence — still amortized-zero)
    _PROBE_IDLE_SLICES = 60

    def __init__(self, replica, chan: dict, on_done, tags: dict):
        from ..core import runtime as rt_mod
        from ..core.ids import ObjectID
        from ..dag.channel import RingReader
        rt = rt_mod.get_runtime_if_exists()
        self._replica = replica
        self._reader = RingReader(rt.store, chan["base"],
                                  ObjectID(chan["stop"]),
                                  int(chan["ring"]))
        self._on_done = on_done
        self._tags = {**tags, "transport": "chan"}
        self._done = False
        self._idle = 0

    def __iter__(self):
        return self

    def _probe(self):
        self._idle += 1
        if self._idle % self._PROBE_IDLE_SLICES:
            return
        import ray_tpu
        try:
            from . import metrics as sm
            sm.stream_dispatches().inc(1.0, tags=self._tags)
        except Exception:
            pass  # telemetry must never fail a stream
        ray_tpu.get(self._replica.stats.remote(), timeout=30)  # liveness

    def __next__(self):
        from ..dag.channel import ChannelClosed
        if self._done:
            raise StopIteration
        try:
            kind, payload = self._reader.read(on_idle=self._probe)
        except ChannelClosed:
            self._reader.retire()
            self._settle()
            raise StopIteration from None
        self._idle = 0
        if kind == "i":
            try:
                from . import metrics as sm
                sm.stream_items().inc(1.0, tags=self._tags)
            except Exception:
                pass  # telemetry must never fail a stream
            return payload
        self._reader.retire()  # sweep the trailing ack ring (leak-free)
        self._settle()
        if kind == "x":
            raise payload
        raise StopIteration

    def _settle(self):
        if not self._done:
            self._done = True
            if self._on_done:
                self._on_done()
                self._on_done = None

    def cancel(self):
        if self._done:
            return
        # sealing the stop flag is the whole cancellation: the replica's
        # drain thread observes it (its next write/closed() check) and
        # sweeps the channel — no actor call, zero dispatches
        self._reader.close()
        self._settle()


class DeploymentResponseGenerator:
    """Iterator over a streaming deployment response (reference:
    handle.py DeploymentResponseGenerator). Pulls batched chunks from the
    replica-retained generator via stream_next — the fallback transport
    when the static decode plan can't engage (no shared store, or
    cfg.serve_static_decode_plan off)."""

    def __init__(self, replica, sid: int, on_done, tags=None):
        self._replica = replica
        self._sid = sid
        self._on_done = on_done
        self._tags = {**(tags or {}), "transport": "poll"}
        self._buf: deque = deque()
        self._done = False

    def __iter__(self):
        return self

    def __next__(self):
        import ray_tpu
        while not self._buf:
            if self._done:
                raise StopIteration
            items, done = ray_tpu.get(
                self._replica.stream_next.remote(self._sid))
            try:
                from . import metrics as sm
                sm.stream_dispatches().inc(1.0, tags=self._tags)
                if items:
                    sm.stream_items().inc(float(len(items)),
                                          tags=self._tags)
            except Exception:
                pass  # telemetry must never fail a stream
            self._buf.extend(items)
            if done:
                self._done = True
                if self._on_done:
                    self._on_done()
                    self._on_done = None
        return self._buf.popleft()

    def cancel(self):
        import ray_tpu
        if not self._done:
            self._done = True
            try:
                ray_tpu.get(self._replica.stream_cancel.remote(self._sid))
            except Exception:
                pass  # replica died; stream is gone either way
            if self._on_done:
                self._on_done()
                self._on_done = None


def _listen_loop_weak(handle_ref):
    """Body of a handle's long-poll listener thread. Takes a weakref so an
    abandoned handle (and this thread) can die; between polls only ids are
    kept live."""
    import ray_tpu
    failures = 0
    while True:
        h = handle_ref()
        if h is None:
            return
        ctrl, app, dep, known = (h._ctrl, h.app_name, h.deployment_name,
                                 h._version)
        del h  # don't pin the handle across the (long) poll
        try:
            version, replicas = ray_tpu.get(
                ctrl.listen_for_change.remote(app, dep, known),
                timeout=45.0)
            failures = 0
        except Exception:
            # controller busy/restarting or deployment deleted; back off
            # and give up after repeated failures (the TTL path in
            # _refresh still keeps the handle usable)
            failures += 1
            h = handle_ref()
            if failures >= 5 or h is None:
                if h is not None:
                    h._listener_started = False
                return
            del h
            time.sleep(min(2.0 ** failures, 10.0))
            continue
        h = handle_ref()
        if h is None:
            return
        if version != h._version:
            # atomic installs: readers snapshot these attributes
            h._inflight = {i: 0 for i in range(len(replicas))}
            h._replicas = replicas
            h._version = version
        h._last_refresh = time.monotonic()
        del h


class DeploymentHandle:
    def __init__(self, deployment: str, app: str, controller,
                 method: str = "__call__", stream: bool = False,
                 multiplexed_model_id: str = "",
                 replica_index: Optional[int] = None):
        self.deployment_name = deployment
        self.app_name = app
        self._ctrl = controller
        self._method = method
        self._stream = stream
        self._model_id = multiplexed_model_id
        self._replica_index = replica_index
        self._replicas: list = []
        self._version = -1
        self._inflight: dict[int, int] = {}
        self._last_refresh = 0.0
        self._listener_started = False

    # handles pickle into replicas/tasks; router state is rebuilt lazily
    def __reduce__(self):
        return (DeploymentHandle,
                (self.deployment_name, self.app_name, self._ctrl,
                 self._method, self._stream, self._model_id,
                 self._replica_index))

    def options(self, method_name: Optional[str] = None,
                stream: Optional[bool] = None,
                multiplexed_model_id: Optional[str] = None,
                replica_index: Optional[int] = None,
                **_ignored) -> "DeploymentHandle":
        return DeploymentHandle(
            self.deployment_name, self.app_name, self._ctrl,
            method_name or self._method,
            self._stream if stream is None else stream,
            self._model_id if multiplexed_model_id is None
            else multiplexed_model_id,
            self._replica_index if replica_index is None
            else replica_index)

    def __getattr__(self, name: str) -> "DeploymentHandle":
        if name.startswith("_"):
            raise AttributeError(name)
        return DeploymentHandle(self.deployment_name, self.app_name,
                                self._ctrl, name, self._stream,
                                self._model_id, self._replica_index)

    # -- routing ----------------------------------------------------------

    def num_replicas(self) -> int:
        """Live replica count (fresh poll) — lets index-pinned callers
        (see ``options(replica_index=...)``) size their routing modulus
        to the deployment's actual width."""
        self._refresh(force=True)
        return len(self._replicas)

    def _ensure_listener(self):
        """Long-poll push of replica-set changes (reference:
        _private/long_poll.py LongPollClient): one daemon thread parks in
        the controller's listen_for_change, so scale-ups/downs reach this
        handle promptly instead of on the next TTL poll, and steady-state
        traffic costs the controller one parked waiter, not one
        get_replicas per poll interval. The thread holds only a WEAKREF to
        this handle and exits when the handle is collected — short-lived
        handles (e.g. per-request ones) must not each pin a thread."""
        if self._listener_started:
            return
        self._listener_started = True
        import threading
        import weakref
        threading.Thread(target=_listen_loop_weak,
                         args=(weakref.ref(self),), daemon=True,
                         name=f"serve-lp-{self.deployment_name}").start()

    def _refresh(self, force: bool = False):
        import ray_tpu
        now = time.monotonic()
        if not force and self._replicas and (
                now - self._last_refresh < _cfg.serve_replica_poll_s):
            return
        version, replicas = ray_tpu.get(self._ctrl.get_replicas.remote(
            self.app_name, self.deployment_name))
        if version != self._version:
            self._version = version
            self._replicas = replicas
            self._inflight = {i: 0 for i in range(len(replicas))}
        self._last_refresh = now

    @staticmethod
    def _affinity_key(args: tuple, kwargs: dict) -> Optional[str]:
        """Prefix-affinity routing key for LLM-style calls: an explicit
        ``session_id`` (kwarg or request field) wins; otherwise the head
        of the request dict's prompt — the first N tokens/chars, which is
        exactly the region the paged engine's prefix cache can reuse.
        Non-LLM calls (no dict request, no session) return None and keep
        pure least-loaded routing."""
        req = args[0] if args and isinstance(args[0], dict) else None
        sid = kwargs.get("session_id") or (
            req.get("session_id") if req else None)
        if sid:
            return f"sid:{sid}"
        if req is None:
            return None
        prompt = req.get("prompt")
        if isinstance(prompt, str) and prompt:
            return "tok:" + prompt[:256]
        if isinstance(prompt, (list, tuple)) and prompt:
            return "tok:" + ",".join(map(str, prompt[:64]))
        return None

    def _pick(self, replicas: list, affinity: Optional[str] = None) -> int:
        """Power-of-two-choices over local in-flight counts
        (reference: pow_2_router.py:27). With a multiplexed model id,
        rendezvous hashing over stable replica (actor) ids instead: same
        model → same replica while it lives, so its weights stay
        cache-hot (multiplex.py routing note). An affinity key (shared
        prompt prefix / session) rendezvous-hashes the same way — same
        prefix → same replica → warm prefix cache — but yields to the
        least-loaded replica when the preferred one is clearly busier.
        Operates on the caller's SNAPSHOT of the replica list — the
        listener thread may swap self._replicas concurrently."""
        n = len(replicas)
        if n == 1:
            return 0
        import hashlib

        def rendezvous(key):
            def score(i):
                rid = replicas[i]._actor_id.hex()
                return hashlib.md5(f"{key}:{rid}".encode()).digest()
            return max(range(n), key=score)

        if self._model_id:
            return rendezvous(self._model_id)
        if affinity is not None:
            pref = rendezvous(affinity)
            loads = [self._inflight.get(i, 0) for i in range(n)]
            if loads[pref] <= min(loads) + _AFFINITY_SLACK:
                return pref
            return loads.index(min(loads))
        i, j = random.sample(range(n), 2)
        return i if self._inflight.get(i, 0) <= self._inflight.get(j, 0) \
            else j

    @staticmethod
    def _make_chan_spec():
        """Channel spec for the static decode plan, or None when it
        can't engage from this process (flag off, no shm store — local
        mode — or this caller sits on an own-store node and can't share
        a store with a head-store replica)."""
        if not _cfg.serve_static_decode_plan:
            return None
        from ..core import runtime as rt_mod
        rt = rt_mod.get_runtime_if_exists()
        if getattr(rt, "store", None) is None or \
                getattr(rt, "own_store", False):
            return None
        import os
        return {"base": os.urandom(16), "stop": os.urandom(16),
                "ring": max(2, _cfg.serve_stream_ring)}

    def remote(self, *args, **kwargs) -> DeploymentResponse:
        import ray_tpu
        t0 = time.perf_counter()
        self._refresh()
        self._ensure_listener()
        deadline = time.monotonic() + 30.0
        while not self._replicas:
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"no replicas for {self.deployment_name!r}")
            time.sleep(0.05)
            self._refresh(force=True)
        args = tuple(a._to_object_ref() if isinstance(a, DeploymentResponse)
                     else a for a in args)
        kwargs = {k: (v._to_object_ref()
                      if isinstance(v, DeploymentResponse) else v)
                  for k, v in kwargs.items()}
        replicas = self._replicas  # snapshot: listener may swap the list
        if self._replica_index is not None:
            # pinned routing (PD channel pairing): the caller addresses a
            # specific replica by stable index, modulo the live count so a
            # scale-down degrades to wraparound instead of erroring
            idx = self._replica_index % len(replicas)
        else:
            idx = self._pick(replicas, self._affinity_key(args, kwargs))
        replica = replicas[idx]
        self._inflight[idx] = self._inflight.get(idx, 0) + 1
        _fl.evt(_fl.SRV_DISPATCH, idx, int(self._stream))

        def done(i=idx):
            self._inflight[i] = max(0, self._inflight.get(i, 1) - 1)

        request_id = ""
        try:
            from . import metrics as sm
            from .context import get_request_context
            request_id = get_request_context().request_id
            tags = {"app": self.app_name,
                    "deployment": self.deployment_name}
            sm.handle_requests().inc(1.0, tags=tags)
            sm.router_wait().observe(time.perf_counter() - t0, tags=tags)
        except Exception:
            pass  # telemetry must never fail a request

        context = {"app_name": self.app_name,
                   "deployment": self.deployment_name,
                   "multiplexed_model_id": self._model_id,
                   "request_id": request_id}

        if self._stream:
            import ray_tpu
            tags = {"app": self.app_name, "deployment": self.deployment_name}
            chan = self._make_chan_spec()
            resp = ray_tpu.get(replica.handle_request_streaming.remote(
                self._method, args, kwargs, context, chan))
            try:
                from . import metrics as sm
                sm.stream_dispatches().inc(1.0, tags={
                    **tags, "transport": "chan" if isinstance(resp, dict)
                    else "poll"})
            except Exception:
                pass  # telemetry must never fail a request
            if isinstance(resp, dict) and resp.get("chan") is not None:
                # static decode plan engaged: items arrive over the ring
                # channel, no per-chunk actor calls
                _fl.evt(_fl.SRV_STREAM_START, int(resp["chan"]), 1)
                return ChannelResponseGenerator(replica, chan, done, tags)
            _fl.evt(_fl.SRV_STREAM_START, int(resp), 0)
            return DeploymentResponseGenerator(replica, resp, done, tags)

        def retry():
            self._refresh(force=True)
            rs = self._replicas
            if not rs:
                raise RuntimeError(
                    f"no replicas for {self.deployment_name!r}")
            r = rs[self._pick(rs)]
            return r.handle_request.remote(self._method, args, kwargs,
                                           context)

        ref = replica.handle_request.remote(self._method, args, kwargs,
                                            context)
        return DeploymentResponse(ref, done, retry)
