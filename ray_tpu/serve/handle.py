"""DeploymentHandle: the client-side router.

Reference parity: serve/handle.py:639 (DeploymentHandle.remote :715 ->
DeploymentResponse), _private/router.py:365 (AsyncioRouter.assign_request
:676) and request_router/pow_2_router.py:27 (power-of-two-choices).

Routing here tracks in-flight counts per handle (each handle routes its own
traffic) and picks the lighter of two random replicas; the replica set is
cached and refreshed from the controller when its version changes or a
replica dies mid-call (retried once on a fresh set).
"""
from __future__ import annotations

import random
import time
from typing import Any, Optional

from ..core.config import cfg as _cfg


class DeploymentResponse:
    """Future for one request (reference: handle.py DeploymentResponse).
    `.result()` blocks; `await` works inside async actors; passing a
    response to another .remote() passes the underlying ObjectRef so the
    payload never bounces through the caller.

    `.result()` retries once on a fresh replica set when the chosen replica
    died (scale-down or crash race against the handle's cached set)."""

    def __init__(self, ref, on_done, retry=None):
        self._ref = ref
        self._done = False
        self._on_done = on_done
        self._retry = retry

    def result(self, timeout_s: Optional[float] = None) -> Any:
        import ray_tpu
        from ..exceptions import ActorDiedError, WorkerCrashedError
        try:
            try:
                return ray_tpu.get(self._ref, timeout=timeout_s)
            except (ActorDiedError, WorkerCrashedError):
                if self._retry is None:
                    raise
                self._ref = self._retry()
                return ray_tpu.get(self._ref, timeout=timeout_s)
        finally:
            self._settle()

    def _settle(self):
        if not self._done:
            self._done = True
            self._on_done()

    def _to_object_ref(self):
        return self._ref

    def __await__(self):
        def gen():
            try:
                out = yield from self._ref.__await__()
                return out
            finally:
                self._settle()
        return gen()


class DeploymentHandle:
    def __init__(self, deployment: str, app: str, controller,
                 method: str = "__call__"):
        self.deployment_name = deployment
        self.app_name = app
        self._ctrl = controller
        self._method = method
        self._replicas: list = []
        self._version = -1
        self._inflight: dict[int, int] = {}
        self._last_refresh = 0.0

    # handles pickle into replicas/tasks; router state is rebuilt lazily
    def __reduce__(self):
        return (DeploymentHandle,
                (self.deployment_name, self.app_name, self._ctrl,
                 self._method))

    def options(self, method_name: Optional[str] = None,
                **_ignored) -> "DeploymentHandle":
        return DeploymentHandle(self.deployment_name, self.app_name,
                                self._ctrl, method_name or self._method)

    def __getattr__(self, name: str) -> "DeploymentHandle":
        if name.startswith("_"):
            raise AttributeError(name)
        return DeploymentHandle(self.deployment_name, self.app_name,
                                self._ctrl, name)

    # -- routing ----------------------------------------------------------

    def _refresh(self, force: bool = False):
        import ray_tpu
        now = time.monotonic()
        if not force and self._replicas and (
                now - self._last_refresh < _cfg.serve_replica_poll_s):
            return
        version, replicas = ray_tpu.get(self._ctrl.get_replicas.remote(
            self.app_name, self.deployment_name))
        if version != self._version:
            self._version = version
            self._replicas = replicas
            self._inflight = {i: 0 for i in range(len(replicas))}
        self._last_refresh = now

    def _pick(self) -> int:
        """Power-of-two-choices over local in-flight counts
        (reference: pow_2_router.py:27)."""
        n = len(self._replicas)
        if n == 1:
            return 0
        i, j = random.sample(range(n), 2)
        return i if self._inflight.get(i, 0) <= self._inflight.get(j, 0) \
            else j

    def remote(self, *args, **kwargs) -> DeploymentResponse:
        import ray_tpu
        self._refresh()
        deadline = time.monotonic() + 30.0
        while not self._replicas:
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"no replicas for {self.deployment_name!r}")
            time.sleep(0.05)
            self._refresh(force=True)
        args = tuple(a._to_object_ref() if isinstance(a, DeploymentResponse)
                     else a for a in args)
        kwargs = {k: (v._to_object_ref()
                      if isinstance(v, DeploymentResponse) else v)
                  for k, v in kwargs.items()}
        idx = self._pick()
        replica = self._replicas[idx]
        self._inflight[idx] = self._inflight.get(idx, 0) + 1

        def done(i=idx):
            self._inflight[i] = max(0, self._inflight.get(i, 1) - 1)

        def retry():
            self._refresh(force=True)
            if not self._replicas:
                raise RuntimeError(
                    f"no replicas for {self.deployment_name!r}")
            r = self._replicas[self._pick()]
            return r.handle_request.remote(self._method, args, kwargs)

        ref = replica.handle_request.remote(self._method, args, kwargs)
        return DeploymentResponse(ref, done, retry)
