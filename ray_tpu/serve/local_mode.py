"""Serve local testing mode: run an application fully in-process.

Reference parity: serve/_private/local_testing_mode.py (the
``serve.run(app, _local_testing_mode=True)`` path) — deployments are
instantiated as plain objects in the driver process, handles dispatch to
them over a thread pool, and no cluster, controller, proxy, or replica
actors exist. The point is unit-testing application logic (composition,
async methods, streaming, reconfigure) at interactive speed; production
behavior — autoscaling, routing, restarts — is exactly what it does NOT
exercise.

Handles mirror the cluster ``DeploymentHandle`` surface: ``.remote()``
returns a response with ``.result(timeout_s)`` / ``await``; attribute
access selects a method; ``.options(stream=True)`` yields a generator
response; composition works because bound children are injected as local
handles at build time, same as the controller does with real handles.
"""
from __future__ import annotations

import asyncio
import concurrent.futures
import threading
from typing import Any, Optional

_REGISTRY: dict[str, "LocalDeploymentHandle"] = {}
_POOL: Optional[concurrent.futures.ThreadPoolExecutor] = None
_LOOP: Optional[asyncio.AbstractEventLoop] = None
_LOOP_THREAD: Optional[threading.Thread] = None
_LOCK = threading.Lock()


def _pool() -> concurrent.futures.ThreadPoolExecutor:
    global _POOL
    with _LOCK:
        if _POOL is None:
            _POOL = concurrent.futures.ThreadPoolExecutor(
                max_workers=32, thread_name_prefix="serve-local")
        return _POOL


def _loop() -> asyncio.AbstractEventLoop:
    """One shared background event loop runs every async deployment
    method (the local-mode analog of the replica's asyncio loop)."""
    global _LOOP, _LOOP_THREAD
    with _LOCK:
        if _LOOP is None:
            loop = asyncio.new_event_loop()
            t = threading.Thread(target=loop.run_forever, daemon=True,
                                 name="serve-local-loop")
            t.start()
            _LOOP, _LOOP_THREAD = loop, t
        return _LOOP


def _guard_loop_thread(what: str) -> None:
    """Blocking on a response from the shared loop thread would deadlock
    every async deployment — refuse loudly instead."""
    if _LOOP_THREAD is not None and \
            threading.current_thread() is _LOOP_THREAD:
        raise RuntimeError(
            f"{what} would block the serve-local event loop from inside "
            f"an async deployment method; await the response instead")


class LocalDeploymentResponse:
    """result()/await surface of DeploymentResponse over a plain
    concurrent future."""

    def __init__(self, fut: concurrent.futures.Future):
        self._fut = fut

    def result(self, timeout_s: Optional[float] = None) -> Any:
        if not self._fut.done():
            _guard_loop_thread("result()")
        return self._fut.result(timeout=timeout_s)

    def _to_object_ref(self):  # composition: nested handle args resolve
        return self.result()

    def __await__(self):
        return asyncio.wrap_future(self._fut).__await__()


def _drive_async_gen(agen):
    """Sync iterator over an async-generator method, items pulled through
    the shared loop (the local analog of the replica's streaming
    responses over async generators)."""
    while True:
        _guard_loop_thread("iterating a streaming response")
        try:
            yield asyncio.run_coroutine_threadsafe(
                agen.__anext__(), _loop()).result()
        except StopAsyncIteration:
            return


class LocalResponseGenerator:
    """Streaming response: iterates the method's generator directly."""

    def __init__(self, gen):
        self._gen = gen

    def __iter__(self):
        return self._gen

    def __next__(self):
        return next(self._gen)

    def cancel(self):
        self._gen.close()


class LocalDeploymentHandle:
    """In-process stand-in for DeploymentHandle (same call surface)."""

    def __init__(self, instance: Any, name: str, method: str = "__call__",
                 stream: bool = False):
        self._instance = instance
        self.deployment_name = name
        self._method = method
        self._stream = stream

    def options(self, method_name: Optional[str] = None,
                stream: Optional[bool] = None,
                **_ignored) -> "LocalDeploymentHandle":
        return LocalDeploymentHandle(
            self._instance, self.deployment_name,
            method_name or self._method,
            self._stream if stream is None else stream)

    def __getattr__(self, name: str) -> "LocalDeploymentHandle":
        if name.startswith("_"):
            raise AttributeError(name)
        return LocalDeploymentHandle(self._instance, self.deployment_name,
                                     name, self._stream)

    def _target(self):
        import inspect
        # function deployments: the function IS the replica — return it
        # directly so iscoroutinefunction still sees an async def (its
        # bound __call__ wrapper would hide that)
        if self._method == "__call__" and (
                inspect.isfunction(self._instance)
                or inspect.iscoroutinefunction(self._instance)):
            return self._instance
        fn = getattr(self._instance, self._method, None)
        if fn is None:
            raise AttributeError(
                f"{self.deployment_name!r} has no method {self._method!r}")
        return fn

    def remote(self, *args, **kwargs):
        import inspect
        fn = self._target()

        def resolve():
            # nested responses resolve to their values before dispatch,
            # the local analog of passing the underlying ObjectRef
            a = tuple(x.result() if isinstance(x, LocalDeploymentResponse)
                      else x for x in args)
            kw = {k: (v.result()
                      if isinstance(v, LocalDeploymentResponse) else v)
                  for k, v in kwargs.items()}
            return a, kw

        if self._stream:
            a, kw = resolve()  # result() guards the loop thread itself
            out = fn(*a, **kw)
            if inspect.isasyncgen(out):
                return LocalResponseGenerator(_drive_async_gen(out))
            return LocalResponseGenerator(iter(out))

        # resolve + invoke entirely on the pool: calling .remote() from
        # inside an async deployment (on the loop thread) must never
        # block the loop waiting on another deployment's coroutine
        def invoke():
            a, kw = resolve()
            if inspect.isasyncgenfunction(fn):
                raise TypeError(
                    "async-generator methods require "
                    ".options(stream=True)")
            if asyncio.iscoroutinefunction(fn):
                return asyncio.run_coroutine_threadsafe(
                    fn(*a, **kw), _loop()).result()
            return fn(*a, **kw)

        return LocalDeploymentResponse(_pool().submit(invoke))


def build_local_app(app, name: str = "default") -> LocalDeploymentHandle:
    """Instantiate every deployment of a bound application in-process and
    return the ingress handle (reference: local_testing_mode's
    make_local_deployment_handle over the built app graph)."""
    from .api import BoundDeployment

    instances: dict[str, Any] = {}

    def build(node: BoundDeployment):
        spec = node.spec
        if spec.name in instances:
            return instances[spec.name]
        args = tuple(LocalDeploymentHandle(build(a), a.spec.name)
                     if isinstance(a, BoundDeployment) else a
                     for a in spec.init_args)
        kwargs = {k: (LocalDeploymentHandle(build(v), v.spec.name)
                      if isinstance(v, BoundDeployment) else v)
                  for k, v in spec.init_kwargs.items()}
        fc = spec.func_or_class
        if isinstance(fc, type):
            inst = fc(*args, **kwargs)
            if spec.user_config is not None and hasattr(inst,
                                                        "reconfigure"):
                inst.reconfigure(spec.user_config)
        else:
            inst = fc  # function deployment: the function is the replica
        instances[spec.name] = inst
        return inst

    ingress = build(app.ingress)
    handle = LocalDeploymentHandle(ingress, app.ingress.spec.name)
    _REGISTRY[name] = handle
    return handle


def get_local_app(name: str = "default") -> Optional[LocalDeploymentHandle]:
    return _REGISTRY.get(name)


def delete_local_app(name: str = "default") -> None:
    _REGISTRY.pop(name, None)
