"""Serve telemetry: request-path metrics + the metrics_summary() helper.

Reference parity: serve/_private's per-deployment counters and latency
histograms feeding the metrics agent (_private/metrics_agent.py) and the
autoscaler. Everything here records through util/metrics.py, so series
from the proxy, handles, replicas and controller — each its own process —
merge on the head and render on `/metrics` with zero new transport.

Metric names and label sets:
  rtpu_serve_proxy_requests_total{route,method,status}   counter
  rtpu_serve_request_latency_seconds{app,route}          histogram (e2e,
      observed at the proxy: parse -> route -> replica -> respond)
  rtpu_serve_request_errors_total{app,route,code}        counter
  rtpu_serve_handle_requests_total{app,deployment}       counter
  rtpu_serve_router_wait_seconds{app,deployment}         histogram (handle
      call -> request handed to a replica: replica-set refresh + cold start)
  rtpu_serve_replica_latency_seconds{app,deployment}     histogram
  rtpu_serve_replica_requests_total{app,deployment,outcome} counter
  rtpu_serve_queue_depth{app,deployment}                 gauge (ongoing
      requests summed over replicas; the autoscaler's input signal)
  rtpu_serve_replicas{app,deployment}                    gauge
  rtpu_serve_autoscale_decisions_total{app,deployment,direction} counter
  rtpu_serve_batch_size{fn}                              histogram
  rtpu_serve_batch_wait_seconds{fn}                      histogram
  rtpu_serve_stream_dispatches_total{app,deployment,transport} counter
      (control-plane dispatches serving streams — the static decode
      plan's "dispatches per token -> ~0" headline reads from this)
  rtpu_serve_stream_items_total{app,deployment,transport} counter
  rtpu_serve_admission_admitted_total{app,deployment}     counter
  rtpu_serve_admission_shed_total{app,deployment,reason}  counter (shed
      429s by reason: queue_full | slo | deadline)
  rtpu_serve_admission_queue_wait_seconds{app,deployment} histogram
  rtpu_serve_admission_inflight{app,deployment,proxy}     gauge
  rtpu_serve_tenant_requests_total{app,deployment,tenant,outcome} counter
      (per-tenant admission outcomes: admitted | shed; tenant ids are
      clamped to a bounded tracked set per gate — see
      cfg.serve_tenant_max_tracked — so cardinality stays bounded)
  rtpu_serve_tenant_inflight{app,deployment,tenant,proxy} gauge
  rtpu_serve_tenant_queued{app,deployment,tenant,proxy,proc} gauge
      (requests parked in a tenant's admission queue — the per-tenant
      queue-depth series the adapter-aware autoscaler signal reads from
      the TSDB; the proc label lets the head's worker-death sweep zero
      a killed proxy's series so a stale backlog can't scale out
      forever)
  rtpu_serve_autoscale_signal_total{app,deployment,reason} counter
      (TSDB-signal-driven scale-out decisions by triggering reason:
      shed | burn | ttft_slope | tenant_queue)
  rtpu_serve_proxies                                      gauge
  rtpu_serve_prefix_directory_hits_total{model}           counter
  rtpu_serve_prefix_directory_misses_total{model}         counter
  rtpu_serve_prefix_directory_imported_pages_total{model} counter
  rtpu_serve_prefix_directory_publishes_total{model}      counter
  rtpu_serve_prefix_directory_stale_total{model}          counter

``metrics_summary()`` condenses the merged store into finite p50/p95/p99
latencies (TTFT, e2e, replica) plus the headline gauges/counters — the
number a perf PR cites, and what ``bench_serve.py --metrics`` prints.
"""
from __future__ import annotations

from typing import Optional

from ..util.metrics import (LATENCY_BUCKETS as _LAT, Counter, Gauge,
                            Histogram, cached_metric as _metric,
                            collect_store as _um_collect_store,
                            histogram_stats as _um_histogram_stats)

_SIZES = (1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0)


def proxy_requests() -> Counter:
    return _metric(Counter, "rtpu_serve_proxy_requests_total",
                   "HTTP requests through the Serve proxy",
                   tag_keys=("route", "method", "status"))


def request_latency() -> Histogram:
    return _metric(Histogram, "rtpu_serve_request_latency_seconds",
                   "end-to-end request latency observed at the proxy",
                   boundaries=_LAT, tag_keys=("app", "route"))


def request_errors() -> Counter:
    return _metric(Counter, "rtpu_serve_request_errors_total",
                   "requests that returned an error",
                   tag_keys=("app", "route", "code"))


def handle_requests() -> Counter:
    return _metric(Counter, "rtpu_serve_handle_requests_total",
                   "requests routed through DeploymentHandles",
                   tag_keys=("app", "deployment"))


def router_wait() -> Histogram:
    return _metric(Histogram, "rtpu_serve_router_wait_seconds",
                   "handle call to replica hand-off (replica-set refresh "
                   "and cold-start wait)", boundaries=_LAT,
                   tag_keys=("app", "deployment"))


def replica_latency() -> Histogram:
    return _metric(Histogram, "rtpu_serve_replica_latency_seconds",
                   "request execution time inside a replica",
                   boundaries=_LAT, tag_keys=("app", "deployment"))


def replica_requests() -> Counter:
    return _metric(Counter, "rtpu_serve_replica_requests_total",
                   "requests executed by replicas",
                   tag_keys=("app", "deployment", "outcome"))


def queue_depth() -> Gauge:
    return _metric(Gauge, "rtpu_serve_queue_depth",
                   "ongoing requests summed over a deployment's replicas",
                   tag_keys=("app", "deployment"))


def replica_count() -> Gauge:
    return _metric(Gauge, "rtpu_serve_replicas",
                   "running replicas per deployment",
                   tag_keys=("app", "deployment"))


def autoscale_decisions() -> Counter:
    return _metric(Counter, "rtpu_serve_autoscale_decisions_total",
                   "autoscaler retarget decisions",
                   tag_keys=("app", "deployment", "direction"))


def stream_dispatches() -> Counter:
    return _metric(Counter, "rtpu_serve_stream_dispatches_total",
                   "control-plane dispatches (actor calls) made to serve "
                   "streaming responses: setup + per-chunk pulls on the "
                   "poll transport, setup + liveness probes only on the "
                   "static decode plan (chan transport)",
                   tag_keys=("app", "deployment", "transport"))


def stream_items() -> Counter:
    return _metric(Counter, "rtpu_serve_stream_items_total",
                   "items delivered by streaming responses, by transport",
                   tag_keys=("app", "deployment", "transport"))


# -- front door: admission control + prefix directory ----------------- #

def admission_admitted() -> Counter:
    return _metric(Counter, "rtpu_serve_admission_admitted_total",
                   "requests admitted by the proxy's SLO-aware gate "
                   "(immediately or after queueing)",
                   tag_keys=("app", "deployment"))


def admission_shed() -> Counter:
    return _metric(Counter, "rtpu_serve_admission_shed_total",
                   "requests shed 429+Retry-After instead of queueing "
                   "past the budget (reason: queue_full | slo | "
                   "deadline)",
                   tag_keys=("app", "deployment", "reason"))


def admission_queue_wait() -> Histogram:
    return _metric(Histogram, "rtpu_serve_admission_queue_wait_seconds",
                   "time admitted requests spent parked in the "
                   "admission queue before an execution slot freed",
                   boundaries=_LAT, tag_keys=("app", "deployment"))


def admission_inflight() -> Gauge:
    return _metric(Gauge, "rtpu_serve_admission_inflight",
                   "requests this proxy currently holds an admission "
                   "slot for, per deployment",
                   tag_keys=("app", "deployment", "proxy"))


def tenant_requests() -> Counter:
    return _metric(Counter, "rtpu_serve_tenant_requests_total",
                   "per-tenant admission outcomes at the front door "
                   "(outcome: admitted | shed); only requests that "
                   "resolve a tenant id mint series, and gate-side "
                   "bucketing bounds the tenant label set",
                   tag_keys=("app", "deployment", "tenant", "outcome"))


def tenant_inflight() -> Gauge:
    return _metric(Gauge, "rtpu_serve_tenant_inflight",
                   "admission slots a tenant currently holds at this "
                   "proxy",
                   tag_keys=("app", "deployment", "tenant", "proxy"))


def tenant_queued() -> Gauge:
    # the proc label (host:pid) rides along so the head's worker-death
    # sweep zeroes a killed proxy's series — this gauge DRIVES
    # autoscaling, and a pinned stale backlog would scale out forever
    return _metric(Gauge, "rtpu_serve_tenant_queued",
                   "requests parked in a tenant's admission queue at "
                   "this proxy (per-tenant queue depth; the "
                   "adapter-aware autoscaling signal's input series)",
                   tag_keys=("app", "deployment", "tenant", "proxy",
                             "proc"))


def autoscale_signal() -> Counter:
    return _metric(Counter, "rtpu_serve_autoscale_signal_total",
                   "scale-out decisions driven by the TSDB signals "
                   "(obs/scraper.py autoscale_signals), by the reason "
                   "that fired",
                   tag_keys=("app", "deployment", "reason"))


def proxy_count() -> Gauge:
    return _metric(Gauge, "rtpu_serve_proxies",
                   "live controller-managed proxy actors")


def prefix_directory_hits() -> Counter:
    return _metric(Counter, "rtpu_serve_prefix_directory_hits_total",
                   "admission-time prefix lookups that found a warmer "
                   "replica in the cluster directory and imported its "
                   "KV pages", tag_keys=("model",))


def prefix_directory_misses() -> Counter:
    return _metric(Counter, "rtpu_serve_prefix_directory_misses_total",
                   "admission-time prefix lookups the directory could "
                   "not improve on (no entry, or nothing beyond local "
                   "coverage)", tag_keys=("model",))


def prefix_directory_imported_pages() -> Counter:
    return _metric(Counter,
                   "rtpu_serve_prefix_directory_imported_pages_total",
                   "KV pages imported from other replicas via the "
                   "prefix directory", tag_keys=("model",))


def prefix_directory_publishes() -> Counter:
    return _metric(Counter,
                   "rtpu_serve_prefix_directory_publishes_total",
                   "page hashes this process published to the cluster "
                   "prefix directory", tag_keys=("model",))


def prefix_directory_stale() -> Counter:
    return _metric(Counter, "rtpu_serve_prefix_directory_stale_total",
                   "directory hints that failed on use (owner dead or "
                   "pages evicted) and were dropped; the request "
                   "prefilled cold — hints, never correctness",
                   tag_keys=("model",))


def batch_size() -> Histogram:
    return _metric(Histogram, "rtpu_serve_batch_size",
                   "items per @serve.batch invocation",
                   boundaries=_SIZES, tag_keys=("fn",))


def batch_wait() -> Histogram:
    return _metric(Histogram, "rtpu_serve_batch_wait_seconds",
                   "oldest item's queue wait per @serve.batch invocation",
                   boundaries=_LAT, tag_keys=("fn",))


# --------------------------------------------------------------------- #
# summary
# --------------------------------------------------------------------- #

# the store merge + histogram fold are shared with rl.podracer's
# summary; the canonical implementations live in util/metrics.py
_collect_store = _um_collect_store
_hist_stats = _um_histogram_stats


def _counter_total(rec: Optional[dict]) -> float:
    return sum(rec["series"].values()) if rec else 0.0


def metrics_summary() -> dict:
    """Percentiles and headline series from the merged metric store.

    Returns a dict with (present only when data exists):
      ttft / inter_token / queue_wait / e2e_latency / replica_latency —
          {count, mean, p50, p95, p99} in seconds
      kv_utilization / batch_occupancy — {<engine>: value of the
          most-loaded process}
      prefix_cache — {hits, misses, evictions, tokens_saved,
          imported_pages, exported_pages, hit_rate,
          cached_pages: {<engine>: pages on the deepest-cache process}}
      cache — the heat plane's per-chain fold: {chains: [{chain, hits,
          tokens_saved, resident_pages, last_hit_age_s}, ...hot-first],
          tracked_chains} summed across replicas from the bounded
          rtpu_llm_prefix_chain_* gauges; plus, when the spill tier
          ran anywhere, spill — {demotions, promotions, expired,
          drops, spilled_pages, spilled_bytes, resident_pages,
          resident_bytes} from the rtpu_llm_prefix_spill_* families
          (residency summed across replicas: every tier is distinct
          host memory)
      tenants — {<tenant>: {admitted, shed}} per-tenant admission
          outcomes (front-door fairness/quota counter-verification)
      lora — {requests, hits, loads, evictions, swaps, publishes,
          resident_adapters} multi-LoRA lifecycle counters
      requests — {proxy, handle, replica, errors} cumulative counts
    Worker-side series ship on a ~2s cadence; a summary taken immediately
    after traffic may trail by one flush tick.
    """
    store = _collect_store()
    out: dict = {}
    for key, name in (
            ("ttft", "rtpu_llm_ttft_seconds"),
            ("inter_token", "rtpu_llm_inter_token_seconds"),
            ("queue_wait", "rtpu_llm_queue_wait_seconds"),
            ("e2e_latency", "rtpu_serve_request_latency_seconds"),
            ("replica_latency", "rtpu_serve_replica_latency_seconds"),
            ("router_wait", "rtpu_serve_router_wait_seconds")):
        stats = _hist_stats(store.get(name))
        if stats is not None:
            out[key] = stats
    for key, name in (("kv_utilization", "rtpu_llm_kv_utilization"),
                      ("batch_occupancy", "rtpu_llm_batch_occupancy")):
        rec = store.get(name)
        if rec:
            # gauge series are per-process (proc label); the headline
            # number per engine kind is the MOST LOADED process — mean
            # would let one idle replica mask a saturated one
            agg: dict = {}
            for kk, vv in rec["series"].items():
                eng = next((v for k, v in kk if k == "engine"), "")
                agg[eng] = max(agg.get(eng, 0.0), vv)
            out[key] = agg
    hits = _counter_total(store.get("rtpu_llm_prefix_cache_hits_total"))
    misses = _counter_total(store.get("rtpu_llm_prefix_cache_misses_total"))
    if hits or misses:
        cached: dict = {}
        rec = store.get("rtpu_llm_prefix_cached_pages")
        if rec:
            for kk, vv in rec["series"].items():
                eng = next((v for k, v in kk if k == "engine"), "")
                cached[eng] = max(cached.get(eng, 0.0), vv)
        out["prefix_cache"] = {
            "hits": hits, "misses": misses,
            "evictions": _counter_total(
                store.get("rtpu_llm_prefix_cache_evictions_total")),
            "tokens_saved": _counter_total(
                store.get("rtpu_llm_prefix_cache_tokens_saved_total")),
            "imported_pages": _counter_total(
                store.get("rtpu_llm_prefix_cache_imported_pages_total")),
            "exported_pages": _counter_total(
                store.get("rtpu_llm_prefix_cache_exported_pages_total")),
            "hit_rate": hits / (hits + misses),
            "cached_pages": cached,
        }
    # cache heat plane: the per-chain gauge fold (bounded — top-K per
    # engine plus __overflow__ by construction, llm/telemetry.py)
    chains: dict = {}
    for name, field, fold in (
            ("rtpu_llm_prefix_chain_hits", "hits", "sum"),
            ("rtpu_llm_prefix_chain_tokens_saved", "tokens_saved",
             "sum"),
            ("rtpu_llm_prefix_chain_resident_pages", "resident_pages",
             "sum"),
            ("rtpu_llm_prefix_chain_last_hit_age_s", "last_hit_age_s",
             "min")):
        rec = store.get(name)
        for kk, vv in (rec or {}).get("series", {}).items():
            chain = next((v for k, v in kk if k == "chain"), "")
            row = chains.setdefault(chain, {"chain": chain})
            if fold == "sum":
                row[field] = row.get(field, 0.0) + vv
            else:
                row[field] = min(row.get(field, vv), vv)
    # spill tier (llm/tiering.py): lifecycle counters + live residency.
    # Zero everywhere unless some engine ran with kv_spill — the fold
    # only appears when the tier actually moved or holds pages.
    spill = {
        "demotions": _counter_total(
            store.get("rtpu_llm_prefix_spill_demotions_total")),
        "promotions": _counter_total(
            store.get("rtpu_llm_prefix_spill_promotions_total")),
        "expired": _counter_total(
            store.get("rtpu_llm_prefix_spill_expired_total")),
        "drops": _counter_total(
            store.get("rtpu_llm_prefix_spill_drops_total")),
        "spilled_pages": _counter_total(
            store.get("rtpu_llm_prefix_spill_pages_total")),
        "spilled_bytes": _counter_total(
            store.get("rtpu_llm_prefix_spill_bytes_total")),
        "resident_pages": _counter_total(
            store.get("rtpu_llm_prefix_spill_resident_pages")),
        "resident_bytes": _counter_total(
            store.get("rtpu_llm_prefix_spill_resident_bytes")),
    }
    if not any(spill.values()):
        spill = None
    if chains or spill:
        out["cache"] = {
            "chains": sorted(chains.values(),
                             key=lambda r: -r.get("hits", 0.0)),
            "tracked_chains": _counter_total(
                store.get("rtpu_llm_prefix_chain_tracked")),
        }
        if spill:
            out["cache"]["spill"] = spill
    disp = store.get("rtpu_serve_stream_dispatches_total")
    items = store.get("rtpu_serve_stream_items_total")
    if disp or items:
        by_transport: dict = {}
        for rec, field in ((disp, "dispatches"), (items, "items")):
            for kk, vv in (rec or {}).get("series", {}).items():
                tr = next((v for k, v in kk if k == "transport"), "")
                by_transport.setdefault(tr, {})[field] = \
                    by_transport.get(tr, {}).get(field, 0.0) + vv
        for tr, rec in by_transport.items():
            n_items = rec.get("items", 0.0)
            if n_items:
                # the decode-plan headline: ~0 for "chan" in steady state
                rec["dispatches_per_item"] = \
                    rec.get("dispatches", 0.0) / n_items
        out["stream"] = by_transport
    admitted = _counter_total(
        store.get("rtpu_serve_admission_admitted_total"))
    shed = _counter_total(store.get("rtpu_serve_admission_shed_total"))
    if admitted or shed:
        qw = _hist_stats(
            store.get("rtpu_serve_admission_queue_wait_seconds"))
        out["admission"] = {
            "admitted": admitted, "shed": shed,
            "shed_rate": shed / (admitted + shed),
        }
        if qw is not None:
            out["admission"]["queue_wait"] = qw
    trec = store.get("rtpu_serve_tenant_requests_total")
    if trec:
        tenants: dict = {}
        for kk, vv in trec["series"].items():
            ten = next((v for k, v in kk if k == "tenant"), "")
            outcome = next((v for k, v in kk if k == "outcome"), "")
            if ten:
                tenants.setdefault(ten, {"admitted": 0.0, "shed": 0.0})
                tenants[ten][outcome] = \
                    tenants[ten].get(outcome, 0.0) + vv
        if tenants:
            out["tenants"] = tenants
    lora_req = _counter_total(store.get("rtpu_llm_lora_requests_total"))
    lora_loads = _counter_total(store.get("rtpu_llm_lora_loads_total"))
    if lora_req or lora_loads:
        resident: dict = {}
        rec = store.get("rtpu_llm_lora_resident_adapters")
        if rec:
            for kk, vv in rec["series"].items():
                eng = next((v for k, v in kk if k == "engine"), "")
                resident[eng] = max(resident.get(eng, 0.0), vv)
        out["lora"] = {
            "requests": lora_req,
            "hits": _counter_total(
                store.get("rtpu_llm_lora_hits_total")),
            "loads": lora_loads,
            "evictions": _counter_total(
                store.get("rtpu_llm_lora_evictions_total")),
            "swaps": _counter_total(
                store.get("rtpu_llm_lora_swaps_total")),
            "publishes": _counter_total(
                store.get("rtpu_llm_lora_publishes_total")),
            "resident_adapters": resident,
        }
    dhits = _counter_total(
        store.get("rtpu_serve_prefix_directory_hits_total"))
    dmiss = _counter_total(
        store.get("rtpu_serve_prefix_directory_misses_total"))
    if dhits or dmiss:
        out["prefix_directory"] = {
            "hits": dhits, "misses": dmiss,
            "imported_pages": _counter_total(store.get(
                "rtpu_serve_prefix_directory_imported_pages_total")),
            "publishes": _counter_total(store.get(
                "rtpu_serve_prefix_directory_publishes_total")),
            "stale_dropped": _counter_total(store.get(
                "rtpu_serve_prefix_directory_stale_total")),
        }
    out["requests"] = {
        "proxy": _counter_total(
            store.get("rtpu_serve_proxy_requests_total")),
        "handle": _counter_total(
            store.get("rtpu_serve_handle_requests_total")),
        "replica": _counter_total(
            store.get("rtpu_serve_replica_requests_total")),
        "errors": _counter_total(
            store.get("rtpu_serve_request_errors_total")),
        "llm": _counter_total(store.get("rtpu_llm_requests_total")),
        "llm_tokens": _counter_total(
            store.get("rtpu_llm_tokens_generated_total")),
        "llm_preemptions": _counter_total(
            store.get("rtpu_llm_preemptions_total")),
    }
    return out
