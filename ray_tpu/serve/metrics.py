"""Serve telemetry: request-path metrics + the metrics_summary() helper.

Reference parity: serve/_private's per-deployment counters and latency
histograms feeding the metrics agent (_private/metrics_agent.py) and the
autoscaler. Everything here records through util/metrics.py, so series
from the proxy, handles, replicas and controller — each its own process —
merge on the head and render on `/metrics` with zero new transport.

Metric names and label sets:
  rtpu_serve_proxy_requests_total{route,method,status}   counter
  rtpu_serve_request_latency_seconds{app,route}          histogram (e2e,
      observed at the proxy: parse -> route -> replica -> respond)
  rtpu_serve_request_errors_total{app,route,code}        counter
  rtpu_serve_handle_requests_total{app,deployment}       counter
  rtpu_serve_router_wait_seconds{app,deployment}         histogram (handle
      call -> request handed to a replica: replica-set refresh + cold start)
  rtpu_serve_replica_latency_seconds{app,deployment}     histogram
  rtpu_serve_replica_requests_total{app,deployment,outcome} counter
  rtpu_serve_queue_depth{app,deployment}                 gauge (ongoing
      requests summed over replicas; the autoscaler's input signal)
  rtpu_serve_replicas{app,deployment}                    gauge
  rtpu_serve_autoscale_decisions_total{app,deployment,direction} counter
  rtpu_serve_batch_size{fn}                              histogram
  rtpu_serve_batch_wait_seconds{fn}                      histogram
  rtpu_serve_stream_dispatches_total{app,deployment,transport} counter
      (control-plane dispatches serving streams — the static decode
      plan's "dispatches per token -> ~0" headline reads from this)
  rtpu_serve_stream_items_total{app,deployment,transport} counter

``metrics_summary()`` condenses the merged store into finite p50/p95/p99
latencies (TTFT, e2e, replica) plus the headline gauges/counters — the
number a perf PR cites, and what ``bench_serve.py --metrics`` prints.
"""
from __future__ import annotations

from typing import Optional

from ..util.metrics import (LATENCY_BUCKETS as _LAT, Counter, Gauge,
                            Histogram, cached_metric as _metric,
                            histogram_quantiles)

_SIZES = (1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0)


def proxy_requests() -> Counter:
    return _metric(Counter, "rtpu_serve_proxy_requests_total",
                   "HTTP requests through the Serve proxy",
                   tag_keys=("route", "method", "status"))


def request_latency() -> Histogram:
    return _metric(Histogram, "rtpu_serve_request_latency_seconds",
                   "end-to-end request latency observed at the proxy",
                   boundaries=_LAT, tag_keys=("app", "route"))


def request_errors() -> Counter:
    return _metric(Counter, "rtpu_serve_request_errors_total",
                   "requests that returned an error",
                   tag_keys=("app", "route", "code"))


def handle_requests() -> Counter:
    return _metric(Counter, "rtpu_serve_handle_requests_total",
                   "requests routed through DeploymentHandles",
                   tag_keys=("app", "deployment"))


def router_wait() -> Histogram:
    return _metric(Histogram, "rtpu_serve_router_wait_seconds",
                   "handle call to replica hand-off (replica-set refresh "
                   "and cold-start wait)", boundaries=_LAT,
                   tag_keys=("app", "deployment"))


def replica_latency() -> Histogram:
    return _metric(Histogram, "rtpu_serve_replica_latency_seconds",
                   "request execution time inside a replica",
                   boundaries=_LAT, tag_keys=("app", "deployment"))


def replica_requests() -> Counter:
    return _metric(Counter, "rtpu_serve_replica_requests_total",
                   "requests executed by replicas",
                   tag_keys=("app", "deployment", "outcome"))


def queue_depth() -> Gauge:
    return _metric(Gauge, "rtpu_serve_queue_depth",
                   "ongoing requests summed over a deployment's replicas",
                   tag_keys=("app", "deployment"))


def replica_count() -> Gauge:
    return _metric(Gauge, "rtpu_serve_replicas",
                   "running replicas per deployment",
                   tag_keys=("app", "deployment"))


def autoscale_decisions() -> Counter:
    return _metric(Counter, "rtpu_serve_autoscale_decisions_total",
                   "autoscaler retarget decisions",
                   tag_keys=("app", "deployment", "direction"))


def stream_dispatches() -> Counter:
    return _metric(Counter, "rtpu_serve_stream_dispatches_total",
                   "control-plane dispatches (actor calls) made to serve "
                   "streaming responses: setup + per-chunk pulls on the "
                   "poll transport, setup + liveness probes only on the "
                   "static decode plan (chan transport)",
                   tag_keys=("app", "deployment", "transport"))


def stream_items() -> Counter:
    return _metric(Counter, "rtpu_serve_stream_items_total",
                   "items delivered by streaming responses, by transport",
                   tag_keys=("app", "deployment", "transport"))


def batch_size() -> Histogram:
    return _metric(Histogram, "rtpu_serve_batch_size",
                   "items per @serve.batch invocation",
                   boundaries=_SIZES, tag_keys=("fn",))


def batch_wait() -> Histogram:
    return _metric(Histogram, "rtpu_serve_batch_wait_seconds",
                   "oldest item's queue wait per @serve.batch invocation",
                   boundaries=_LAT, tag_keys=("fn",))


# --------------------------------------------------------------------- #
# summary
# --------------------------------------------------------------------- #

def _collect_store() -> dict:
    """The merged user-metric store: head tables on the head driver, the
    user_metrics_dump RPC from a remote driver/worker, this process's
    registry when no runtime exists (bench / unit tests)."""
    from ..core import runtime as rt_mod
    from ..util import metrics as um
    um.flush()   # ship this process's deltas first
    rt = rt_mod.get_runtime_if_exists()
    if rt is None:
        return um.local_store()
    if isinstance(rt, rt_mod.Runtime):
        with rt.lock:
            return {n: {"kind": r["kind"], "desc": r["desc"],
                        "series": dict(r["series"])}
                    for n, r in rt.user_metrics.items()}
    try:
        return rt._rpc("user_metrics_dump")
    except Exception:
        return um.local_store()


def _hist_stats(rec: Optional[dict]) -> Optional[dict]:
    """Aggregate one histogram record across its label sets into
    {count, mean, p50, p95, p99}."""
    if not rec:
        return None
    buckets: dict[str, float] = {}
    total_sum = 0.0
    for key, val in rec["series"].items():
        le = next((v for k, v in key if k == "le"), None)
        if le is not None:
            buckets[le] = buckets.get(le, 0.0) + val
        elif any(k == "__sum__" for k, _ in key):
            total_sum += val
    count = buckets.get("+Inf", 0.0)
    if count <= 0:
        return None
    p50, p95, p99 = histogram_quantiles(buckets, count, (0.5, 0.95, 0.99))
    return {"count": count, "mean": total_sum / count,
            "p50": p50, "p95": p95, "p99": p99}


def _counter_total(rec: Optional[dict]) -> float:
    return sum(rec["series"].values()) if rec else 0.0


def metrics_summary() -> dict:
    """Percentiles and headline series from the merged metric store.

    Returns a dict with (present only when data exists):
      ttft / inter_token / queue_wait / e2e_latency / replica_latency —
          {count, mean, p50, p95, p99} in seconds
      kv_utilization / batch_occupancy — {<engine>: value of the
          most-loaded process}
      prefix_cache — {hits, misses, evictions, tokens_saved, hit_rate,
          cached_pages: {<engine>: pages on the deepest-cache process}}
      requests — {proxy, handle, replica, errors} cumulative counts
    Worker-side series ship on a ~2s cadence; a summary taken immediately
    after traffic may trail by one flush tick.
    """
    store = _collect_store()
    out: dict = {}
    for key, name in (
            ("ttft", "rtpu_llm_ttft_seconds"),
            ("inter_token", "rtpu_llm_inter_token_seconds"),
            ("queue_wait", "rtpu_llm_queue_wait_seconds"),
            ("e2e_latency", "rtpu_serve_request_latency_seconds"),
            ("replica_latency", "rtpu_serve_replica_latency_seconds"),
            ("router_wait", "rtpu_serve_router_wait_seconds")):
        stats = _hist_stats(store.get(name))
        if stats is not None:
            out[key] = stats
    for key, name in (("kv_utilization", "rtpu_llm_kv_utilization"),
                      ("batch_occupancy", "rtpu_llm_batch_occupancy")):
        rec = store.get(name)
        if rec:
            # gauge series are per-process (proc label); the headline
            # number per engine kind is the MOST LOADED process — mean
            # would let one idle replica mask a saturated one
            agg: dict = {}
            for kk, vv in rec["series"].items():
                eng = next((v for k, v in kk if k == "engine"), "")
                agg[eng] = max(agg.get(eng, 0.0), vv)
            out[key] = agg
    hits = _counter_total(store.get("rtpu_llm_prefix_cache_hits_total"))
    misses = _counter_total(store.get("rtpu_llm_prefix_cache_misses_total"))
    if hits or misses:
        cached: dict = {}
        rec = store.get("rtpu_llm_prefix_cached_pages")
        if rec:
            for kk, vv in rec["series"].items():
                eng = next((v for k, v in kk if k == "engine"), "")
                cached[eng] = max(cached.get(eng, 0.0), vv)
        out["prefix_cache"] = {
            "hits": hits, "misses": misses,
            "evictions": _counter_total(
                store.get("rtpu_llm_prefix_cache_evictions_total")),
            "tokens_saved": _counter_total(
                store.get("rtpu_llm_prefix_cache_tokens_saved_total")),
            "hit_rate": hits / (hits + misses),
            "cached_pages": cached,
        }
    disp = store.get("rtpu_serve_stream_dispatches_total")
    items = store.get("rtpu_serve_stream_items_total")
    if disp or items:
        by_transport: dict = {}
        for rec, field in ((disp, "dispatches"), (items, "items")):
            for kk, vv in (rec or {}).get("series", {}).items():
                tr = next((v for k, v in kk if k == "transport"), "")
                by_transport.setdefault(tr, {})[field] = \
                    by_transport.get(tr, {}).get(field, 0.0) + vv
        for tr, rec in by_transport.items():
            n_items = rec.get("items", 0.0)
            if n_items:
                # the decode-plan headline: ~0 for "chan" in steady state
                rec["dispatches_per_item"] = \
                    rec.get("dispatches", 0.0) / n_items
        out["stream"] = by_transport
    out["requests"] = {
        "proxy": _counter_total(
            store.get("rtpu_serve_proxy_requests_total")),
        "handle": _counter_total(
            store.get("rtpu_serve_handle_requests_total")),
        "replica": _counter_total(
            store.get("rtpu_serve_replica_requests_total")),
        "errors": _counter_total(
            store.get("rtpu_serve_request_errors_total")),
        "llm": _counter_total(store.get("rtpu_llm_requests_total")),
        "llm_tokens": _counter_total(
            store.get("rtpu_llm_tokens_generated_total")),
        "llm_preemptions": _counter_total(
            store.get("rtpu_llm_preemptions_total")),
    }
    return out
