"""Model multiplexing: many models per deployment, LRU-cached per replica.

Reference parity: serve/multiplex.py (_ModelMultiplexWrapper, used via
@serve.multiplexed + handle.options(multiplexed_model_id=...)) and the
router's model-aware replica ranking.

Routing here is RENDEZVOUS HASHING in the handle (see
DeploymentHandle._pick): requests for the same model id deterministically
prefer the same replica of the current replica set, so each model's weights
load once and stay cache-hot — no replica→models gossip needed (the
reference pushes loaded-model sets through long-poll; stateless hashing
achieves the same affinity and degrades the same way on scale-changes).
"""
from __future__ import annotations

import asyncio
import collections
import functools
from typing import Any, Callable

from .context import get_multiplexed_model_id


class _ModelCache:
    """Per-instance async LRU of loaded models with eviction callbacks."""

    def __init__(self, loader: Callable, max_models: int):
        self.loader = loader
        self.max_models = max_models
        self.models: "collections.OrderedDict[str, Any]" = \
            collections.OrderedDict()
        self.locks: dict[str, asyncio.Lock] = {}

    async def get(self, model_id: str) -> Any:
        if model_id in self.models:
            self.models.move_to_end(model_id)
            return self.models[model_id]
        lock = self.locks.setdefault(model_id, asyncio.Lock())
        async with lock:
            if model_id in self.models:   # raced another loader
                self.models.move_to_end(model_id)
                return self.models[model_id]
            model = await self.loader(model_id)
            self.models[model_id] = model
            while len(self.models) > self.max_models:
                old_id, old = self.models.popitem(last=False)
                self.locks.pop(old_id, None)
                # best-effort destructor (reference calls __del__/release)
                release = getattr(old, "release", None)
                if callable(release):
                    try:
                        res = release()
                        if asyncio.iscoroutine(res):
                            await res
                    except Exception:
                        pass  # user hook failed; eviction proceeds
            return model


def multiplexed(_fn=None, *, max_num_models_per_replica: int = 3):
    """Decorate an async model loader ``async def get_model(self, model_id)``.
    Calling it with NO arguments inside a request loads/returns the model
    for the request's multiplexed_model_id (set via
    ``handle.options(multiplexed_model_id=...)``)."""
    def wrap(fn):
        if not asyncio.iscoroutinefunction(fn):
            raise TypeError("@serve.multiplexed requires an async def loader")
        caches: dict[int, _ModelCache] = {}

        @functools.wraps(fn)
        async def wrapper(*args) -> Any:
            # (self,) or (self, model_id) or () or (model_id,)
            if args and not isinstance(args[0], str):
                owner, rest = args[0], args[1:]
                key = id(owner)
                loader = functools.partial(fn, owner)
            else:
                owner, rest = None, args
                key = 0
                loader = fn
            model_id = rest[0] if rest else get_multiplexed_model_id()
            if not model_id:
                raise ValueError(
                    "no multiplexed model id: pass one explicitly or set "
                    "handle.options(multiplexed_model_id=...)")
            cache = caches.get(key)
            if cache is None:
                cache = caches[key] = _ModelCache(
                    loader, max_num_models_per_replica)
            return await cache.get(model_id)

        wrapper._rtpu_multiplex_caches = caches
        return wrapper

    if _fn is not None:
        return wrap(_fn)
    return wrap
