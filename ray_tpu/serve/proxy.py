"""HTTP proxy actor (aiohttp).

Reference parity: serve/_private/proxy.py:709 HTTPProxy / :1059 ProxyActor —
uvicorn/Starlette there, aiohttp here (what the image ships). Routes
`/<app_name>` (and `/` for the default app) to the app's ingress handle:
JSON bodies become the callable's argument, JSON-able returns become the
response body.
"""
from __future__ import annotations

import asyncio
import json
from typing import Optional


class ProxyActor:
    def __init__(self, port: int):
        self._port = port
        self._runner = None

    async def start(self) -> int:
        from aiohttp import web

        app = web.Application()
        app.router.add_route("*", "/{tail:.*}", self._dispatch)
        self._runner = web.AppRunner(app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, "127.0.0.1", self._port)
        await site.start()
        return self._port

    async def _dispatch(self, request):
        from aiohttp import web
        import ray_tpu
        from .handle import DeploymentHandle
        from .api import CONTROLLER_NAME

        path = request.match_info["tail"].strip("/")
        app_name = path.split("/", 1)[0] if path else "default"
        ctrl = ray_tpu.get_actor(CONTROLLER_NAME)
        try:
            ingress = ray_tpu.get(ctrl.get_ingress.remote(app_name))
        except ValueError:
            if app_name != "default":
                try:
                    ingress = ray_tpu.get(
                        ctrl.get_ingress.remote("default"))
                    app_name = "default"
                except ValueError:
                    return web.json_response(
                        {"error": f"no app {app_name!r}"}, status=404)
            else:
                return web.json_response(
                    {"error": "no default app"}, status=404)

        payload: Optional[dict] = None
        if request.can_read_body:
            try:
                payload = await request.json()
            except Exception:
                payload = {"body": (await request.read()).decode(
                    errors="replace")}

        def call():
            # handle.remote() itself may block (replica-set refresh, cold
            # start wait) — keep ALL of it off the proxy's event loop
            handle = DeploymentHandle(ingress, app_name, ctrl)
            resp = (handle.remote(payload) if payload is not None
                    else handle.remote())
            return resp.result(30.0)

        loop = asyncio.get_event_loop()
        out = await loop.run_in_executor(None, call)
        try:
            return web.json_response(out)
        except TypeError:
            return web.Response(text=json.dumps(str(out)),
                                content_type="application/json")

    async def stop(self):
        if self._runner is not None:
            await self._runner.cleanup()
