"""HTTP proxy actor (aiohttp) — one member of the front-door fleet.

Reference parity: serve/_private/proxy.py:709 HTTPProxy / :1059 ProxyActor —
uvicorn/Starlette there, aiohttp here (what the image ships). Routes
`/<app_name>` (and `/` for the default app) to the app's ingress handle:
JSON bodies become the callable's argument, JSON-able returns become the
response body.

Front door (serve/frontdoor/): the controller runs N of these behind
one shared route table (frontdoor/routetable.py — refreshed from the
head's directory service on a short TTL, controller RPC only as
fallback), and every request passes the SLO-aware admission gate
(frontdoor/admission.py) before it touches a handle. Past-budget
traffic queues bounded-and-deadlined, then sheds as ``429`` +
``Retry-After``; replica death surfaces as a typed ``503``, a replica
timeout as ``504`` — a healthy front door returns NO bare 500s under
overload or chaos. Session/prefix affinity is consistent across the
fleet for free: handles rendezvous-hash on stable replica actor ids,
so every proxy maps the same session/prefix to the same replica.
"""
from __future__ import annotations

import asyncio
import json
from typing import Optional


_STREAM_END = object()

# the proxy route registers METH_ANY; metric labels must come from this
# fixed set, never the raw (client-controlled) method token
_KNOWN_VERBS = frozenset(
    {"GET", "POST", "PUT", "DELETE", "PATCH", "HEAD", "OPTIONS"})


class ProxyActor:
    def __init__(self, port: int, index: int = 0):
        from .frontdoor.admission import AdmissionController
        self._port = port
        self._index = index
        self._runner = None
        # handle cache: a DeploymentHandle per routing variant, NOT per
        # request — each handle runs one long-poll listener thread, so
        # per-request construction would leak threads/waiters. Bounded
        # LRU; evicted handles are GC'd and their listener threads exit
        # (weakref-based, see handle._ensure_listener)
        from collections import OrderedDict
        self._handles: "OrderedDict" = OrderedDict()
        self._handles_max = 256
        # shared route table snapshot (frontdoor/routetable.py),
        # refreshed off-loop on a short TTL; None until the first fetch
        # (or forever in fallback mode — then per-request controller
        # calls resolve routing and admission stays unconfigured)
        self._snap: Optional[dict] = None
        self._routes: dict = {}
        self._routes_ts = 0.0
        self._admission = AdmissionController(f"proxy-{index}")

    def _handle_for(self, ingress, app_name, stream, model_id,
                    method="__call__"):
        from .handle import DeploymentHandle
        import ray_tpu
        from .api import CONTROLLER_NAME
        key = (app_name, ingress, stream, model_id, method)
        h = self._handles.get(key)
        if h is None:
            ctrl = ray_tpu.get_actor(CONTROLLER_NAME)
            h = DeploymentHandle(ingress, app_name, ctrl, method,
                                 stream=stream,
                                 multiplexed_model_id=model_id)
            self._handles[key] = h
            while len(self._handles) > self._handles_max:
                self._handles.popitem(last=False)
        else:
            self._handles.move_to_end(key)
        return h

    async def start(self) -> int:
        from aiohttp import web

        app = web.Application()
        app.router.add_route("*", "/{tail:.*}", self._dispatch)
        self._runner = web.AppRunner(app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, "127.0.0.1", self._port)
        await site.start()
        return self._port

    async def ping(self) -> dict:
        """Controller liveness probe (frontdoor fleet management); the
        pid lets chaos tooling SIGKILL a specific proxy."""
        import os
        return {"port": self._port, "pid": os.getpid(),
                "index": self._index}

    # -- shared route table ------------------------------------------------

    async def _refresh_table(self):
        """TTL-refresh the routing/admission state: ONE dir_query frame
        for the controller-published snapshot; falls back to controller
        RPCs (routing only — admission stays open) when the directory
        is unreachable. Runs off-loop: both paths block."""
        import time as _time
        if _time.monotonic() - self._routes_ts <= 1.0:
            return
        loop = asyncio.get_event_loop()

        def _fetch():
            from .frontdoor import routetable
            snap = routetable.fetch_snapshot()
            if snap is not None:
                return snap, snap.get("routes", {})
            # fallback: a cluster without the directory (local clusters
            # torn mid-test, head restarting) still routes
            try:
                import ray_tpu
                from .api import CONTROLLER_NAME
                ctrl0 = ray_tpu.get_actor(CONTROLLER_NAME)
                return None, ray_tpu.get(ctrl0.get_routes.remote())
            except Exception:
                return None, {}
        snap, routes = await loop.run_in_executor(None, _fetch)
        self._routes = routes
        self._routes_ts = _time.monotonic()
        if snap is not None:
            self._snap = snap
            live = set()
            n = max(1, int(snap.get("n_proxies", 1)))
            for key, cap in snap.get("capacity", {}).items():
                app, _, dep = key.partition("/")
                live.add((app, dep))
                self._admission.configure(
                    app, dep, max(int(cap[0]), 1) * max(int(cap[1]), 1),
                    n_proxies=n)
            self._admission.prune(live)

    def _resolve_ingress(self, app_name: str) -> Optional[str]:
        """Ingress deployment for an app: snapshot first, controller
        RPC fallback. None = unknown app."""
        if self._snap is not None:
            ing = self._snap.get("ingress", {}).get(app_name)
            if ing is not None:
                return ing
        import ray_tpu
        from .api import CONTROLLER_NAME
        try:
            ctrl = ray_tpu.get_actor(CONTROLLER_NAME)
            return ray_tpu.get(ctrl.get_ingress.remote(app_name))
        except ValueError:
            return None

    # -- request path ------------------------------------------------------

    async def _dispatch(self, request):
        """Telemetry shell around _dispatch_inner: mints the request id,
        opens the request's root trace span, and lands the per-route
        counters + e2e latency histogram whatever the outcome."""
        import secrets
        import time as _time

        from aiohttp import web

        from . import metrics as sm
        from ..util import tracing

        rid = secrets.token_hex(8)
        meta = {"app": "", "route": ""}
        t0 = _time.perf_counter()
        status = 500
        try:
            with tracing.span("serve.proxy", root=True) as span_rec:
                if span_rec is not None:
                    span_rec["request_id"] = rid
                resp = await self._dispatch_inner(request, rid, meta)
            status = resp.status
            return resp
        except web.HTTPException as e:
            status = e.status
            raise
        except (ConnectionResetError, asyncio.CancelledError):
            # the client dropped mid-stream: not a server error (499,
            # nginx's client-closed-request), and kept out of the error
            # counter an operator alerts on
            status = 499
            raise
        finally:
            try:
                route = meta["route"] or "/"
                # the route registers METH_ANY, so request.method is an
                # arbitrary client token: allowlist it (same unbounded-
                # cardinality guard as the app label below)
                method = request.method if request.method in _KNOWN_VERBS \
                    else "OTHER"
                sm.proxy_requests().inc(1.0, tags={
                    "route": route, "method": method,
                    # status is a server-chosen HTTP code — a bounded
                    # vocabulary, not client-controlled
                    "status": str(status)})  # graftlint: disable=GL011
                sm.request_latency().observe(
                    _time.perf_counter() - t0,
                    tags={"app": meta["app"], "route": route})
                # 499 (client hung up) and 429 (deliberate shed, its own
                # rtpu_serve_admission_shed_total series) stay out of the
                # error counter operators alert on
                if status >= 400 and status not in (429, 499):
                    sm.request_errors().inc(1.0, tags={
                        "app": meta["app"], "route": route,
                        # bounded server-chosen HTTP code (as above)
                        "code": str(status)})  # graftlint: disable=GL011
                if status >= 500:
                    # the replica-death/timeout paths raise and catch
                    # through executor threads; the exception->traceback
                    # ->frame cycles pin the failed call's ObjectRefs
                    # (and their store error objects) until a gc pass
                    # happens to run. Errors are rare: collect shortly
                    # after, so a chaos kill can't hold the store above
                    # baseline until allocation pressure triggers gc.
                    import gc
                    asyncio.get_event_loop().call_later(0.5, gc.collect)
            except Exception:
                pass  # telemetry must never turn a response into a 500

    async def _dispatch_inner(self, request, rid: str, meta: dict):
        from aiohttp import web

        path = request.match_info["tail"].strip("/")
        # route_prefix longest-match first (reference: the proxy's route
        # table); falls back to /<app_name> addressing
        app_name, subpath = None, ""
        await self._refresh_table()
        routes = self._routes
        full = "/" + path
        for prefix, app in sorted(routes.items(), key=lambda kv:
                                  -len(kv[0])):
            p = prefix.rstrip("/")
            if not p:
                continue  # "/" prefixes never reach the route table
            if full == p or full.startswith(p + "/"):
                app_name = app
                subpath = full[len(p):].strip("/")
                meta["route"] = p
                break
        if app_name is None:
            app_name = path.split("/", 1)[0] if path else "default"
            subpath = path.split("/", 1)[1] if "/" in path else ""
        method = subpath.strip("/").replace("/", "_").replace(
            ".", "_").replace("-", "_") if subpath else "__call__"
        if method != "__call__" and (
                method.startswith("_") or not method.isidentifier()):
            # never expose private/dunder attributes over HTTP
            return web.json_response(
                {"error": f"no route {subpath!r}"}, status=404)
        loop = asyncio.get_event_loop()
        ingress = await loop.run_in_executor(
            None, self._resolve_ingress, app_name)
        if ingress is None:
            if app_name != "default":
                ingress = await loop.run_in_executor(
                    None, self._resolve_ingress, "default")
                if ingress is None:
                    return web.json_response(
                        {"error": f"no app {app_name!r}"}, status=404)
                app_name = "default"
            else:
                return web.json_response(
                    {"error": "no default app"}, status=404)
        # label AFTER ingress resolution: app_name is client-controlled
        # until it resolves against deployed apps, and unresolved names
        # must not mint metric series (unbounded label cardinality —
        # every scanner probe would become a permanent head-store series)
        meta["app"] = app_name
        if not meta["route"]:
            meta["route"] = "/" + app_name

        # body parse BEFORE the gate: tenant resolution (adapter id /
        # body fields) needs it, and a shed should not have done any
        # replica work anyway
        payload: Optional[dict] = None
        if request.can_read_body:
            try:
                payload = await request.json()
            except Exception:
                payload = {"body": (await request.read()).decode(
                    errors="replace")}

        # -- admission gate (frontdoor/admission.py): budget-admit,
        # bounded-queue (weighted-fair per tenant), or shed BEFORE any
        # replica work happens ------------------------------------------
        from ..core.config import cfg as _cfg
        release = None
        if _cfg.serve_admission_control:
            from .frontdoor.admission import ShedError, resolve_tenant
            tenant = resolve_tenant(request.headers, payload)
            try:
                release = await self._admission.acquire(
                    app_name, ingress, tenant)
            except ShedError as shed:
                return web.json_response(
                    {"error": "overloaded", "reason": shed.reason,
                     "retry_after_s": shed.retry_after_s},
                    status=429,
                    headers={"Retry-After": str(shed.retry_after_s)})
        import time as _time
        t_adm = _time.perf_counter()
        try:
            return await self._dispatch_admitted(
                request, rid, meta, app_name, ingress, method, payload)
        finally:
            if release is not None:
                release(_time.perf_counter() - t_adm)

    async def _dispatch_admitted(self, request, rid: str, meta: dict,
                                 app_name: str, ingress: str,
                                 method: str, payload: Optional[dict]):
        from aiohttp import web

        from ..exceptions import (ActorDiedError, GetTimeoutError,
                                  WorkerCrashedError)

        # session affinity across the fleet: an explicit session header
        # becomes the request's affinity key (handle._affinity_key), so
        # every proxy rendezvous-routes the session to the same replica
        sid = request.headers.get("serve_session_id", "")
        if sid and isinstance(payload, dict) and \
                "session_id" not in payload:
            payload["session_id"] = sid

        # streaming ingress: ?stream=1, Accept: text/event-stream, or an
        # OpenAI-style {"stream": true} body field
        # (reference: proxy.py streams ASGI responses chunk by chunk)
        want_stream = (request.query.get("stream") in ("1", "true")
                       or "text/event-stream" in
                       request.headers.get("Accept", "")
                       or (isinstance(payload, dict)
                           and payload.get("stream") is True))
        model_id = request.headers.get("serve_multiplexed_model_id", "")

        handle = self._handle_for(ingress, app_name, want_stream, model_id,
                                  method)

        def call():
            # handle.remote() itself may block (replica-set refresh, cold
            # start wait) — keep ALL of it off the proxy's event loop
            resp = (handle.remote(payload) if payload is not None
                    else handle.remote())
            if want_stream:
                return resp  # a DeploymentResponseGenerator
            return resp.result(30.0)

        # run_in_executor does NOT carry contextvars: capture the handler
        # context (active proxy span + request context) explicitly so the
        # replica call parents to the proxy span and rides the request id
        import contextvars

        from .context import reset_request_context, set_request_context
        token = set_request_context(request_id=rid, app_name=app_name)
        try:
            call_ctx = contextvars.copy_context()
        finally:
            reset_request_context(token)

        loop = asyncio.get_event_loop()
        try:
            out = await loop.run_in_executor(None,
                                             lambda: call_ctx.run(call))
        except (ActorDiedError, WorkerCrashedError) as e:
            # replica died mid-call and the handle's one retry found no
            # healthy replacement yet: a TYPED, retryable 503 — the
            # controller is already replacing the replica
            return web.json_response(
                {"error": "replica_unavailable",
                 "detail": type(e).__name__},
                status=503, headers={"Retry-After": "1"})
        except GetTimeoutError:
            return web.json_response(
                {"error": "upstream_timeout"}, status=504,
                headers={"Retry-After": "1"})
        except RuntimeError as e:
            if "no replicas" in str(e):
                return web.json_response(
                    {"error": "replica_unavailable",
                     "detail": "no replicas"},
                    status=503, headers={"Retry-After": "1"})
            if str(e).startswith("overloaded") or "overloaded:" in str(e):
                # replica-side overload raised as a typed marker (e.g.
                # multi-LoRA: every adapter slot live) — retryable, not
                # a bare 500
                return web.json_response(
                    {"error": "overloaded", "detail": str(e)[:200]},
                    status=503, headers={"Retry-After": "1"})
            raise
        if want_stream:
            stream = web.StreamResponse()
            stream.headers["Content-Type"] = "text/event-stream"
            await stream.prepare(request)
            it = iter(out)
            try:
                while True:
                    try:
                        chunk = await loop.run_in_executor(
                            None, lambda: next(it, _STREAM_END))
                    except (ActorDiedError, WorkerCrashedError,
                            GetTimeoutError) as e:
                        # mid-stream replica loss: the status line is
                        # gone (200 already sent); surface a typed error
                        # chunk, then end the stream cleanly
                        await stream.write(json.dumps(
                            {"error": "replica_unavailable",
                             "detail": type(e).__name__}).encode())
                        break
                    if chunk is _STREAM_END:
                        break
                    if not isinstance(chunk, (bytes, str)):
                        chunk = json.dumps(chunk)
                    if isinstance(chunk, str):
                        chunk = chunk.encode()
                    await stream.write(chunk)
                await stream.write_eof()
            finally:
                # client disconnect / write error: release the
                # replica-retained generator and its ongoing slot
                await loop.run_in_executor(None, out.cancel)
            return stream
        try:
            return web.json_response(out)
        except TypeError:
            return web.Response(text=json.dumps(str(out)),
                                content_type="application/json")

    async def stop(self):
        if self._runner is not None:
            await self._runner.cleanup()
